#!/bin/bash
# Continuation-session watcher: when the wedged tunnel frees, run the
# outstanding round-5 A/B variants (sweep 3).  Same discipline as
# bench_watch.sh: probes are never killed, at most MAX_PENDING of THIS
# watcher's probes live at once (earlier sessions' orphan claim clients
# are not ours to manage and exit on their own when the terminal
# recovers), sweeps run serially after a probe confirms the chip
# answers.
set -u
cd "$(dirname "$0")/.."
PROBE_DIR=${PROBE_DIR:-/tmp/bench_probes_r05b}
MAX_PENDING=${MAX_PENDING:-2}
SLEEP=${SLEEP:-300}
mkdir -p "$PROBE_DIR"

run() {
  echo "=== $* ==="
  local out
  out=$(env "$@" python bench.py 2>&1 | grep -E '^\{' || echo FAILED)
  echo "$out"
  # Abort ONLY on a probe-guard timeout ('"error"' key): every later
  # variant would also park 300s while queueing one more orphan claim
  # client each.  A fast FAILED (compile error / OOM) is a property of
  # that variant — keep sweeping the rest.
  case "$out" in *'"error"'*) return 1;; esac
  return 0
}

sweep3() {
  echo "=== sweep 3 via watcher ($(date -u +%T)) ==="
  run HOROVOD_BENCH_SCAN=10 || return            # confirm the 16,636 run
  run HOROVOD_BENCH_MODEL=bert HOROVOD_BENCH_BATCH=256 \
      HOROVOD_BENCH_REMAT=0 HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_BENCH_MODEL=bert HOROVOD_BENCH_BATCH=512 \
      HOROVOD_BENCH_REMAT=0 HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_BENCH_MODEL=resnet HOROVOD_BENCH_BATCH=256 \
      HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_BENCH_MODEL=longctx HOROVOD_BENCH_REMAT=0 || return
  run HOROVOD_BENCH_MODEL=longctx HOROVOD_BENCH_BATCH=2 \
      HOROVOD_BENCH_REMAT=0 || return
  run HOROVOD_BENCH_REMAT_POLICY=dots || return
  run HOROVOD_BENCH_REMAT_POLICY=dots HOROVOD_BENCH_REMAT_SKIP=0 || return
  run HOROVOD_BENCH_REMAT_POLICY=dots HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_FLASH_BLOCK=256 || return
  run HOROVOD_FLASH_ATTENTION=0 || return
}

launch_probe() {
  local tag="$PROBE_DIR/probe_$(date +%s)"
  setsid nohup python -c "import jax; jax.devices(); print('ok', flush=True)" \
    > "$tag.out" 2> "$tag.err" < /dev/null &
  echo "$!" > "$tag.pid"
  echo "$(date -u +%T) launched probe $tag (pid $!)" >> "$PROBE_DIR/watch.log"
}

chip_free() {
  grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null | head -1
}

pending_probes() {
  # THIS watcher's live, not-yet-answered probes only (orphans from
  # earlier bench runs are invisible to chip_free, so counting them
  # here would deadlock the watcher while they idle)
  local n=0
  for pidf in "$PROBE_DIR"/probe_*.pid; do
    [ -f "$pidf" ] || continue
    local pid out
    pid=$(cat "$pidf"); out="${pidf%.pid}.out"
    if kill -0 "$pid" 2>/dev/null && ! grep -q "^ok" "$out" 2>/dev/null; then
      n=$((n + 1))
    fi
  done
  echo "$n"
}

while true; do
  if [ -n "$(chip_free)" ]; then
    SWEEP_OUT=$(mktemp)
    sweep3 > "$SWEEP_OUT" 2>&1
    cat "$SWEEP_OUT" >> bench_ab_r05_rest.log
    # Done only when the sweep ran END TO END with no probe-guard
    # timeout: a mid-sweep re-wedge leaves unmeasured variants, so the
    # watcher keeps retrying the full list (re-measuring a leading
    # variant costs ~5 min; missing the tail silently costs the round).
    if ! grep '^{' "$SWEEP_OUT" | grep -q '"error"' \
        && grep '^{' "$SWEEP_OUT" | grep -q '"value"'; then
      rm -f "$SWEEP_OUT"
      echo "$(date -u +%T) sweep 3 complete — watcher done" \
        >> "$PROBE_DIR/watch.log"
      exit 0
    fi
    rm -f "$SWEEP_OUT"
    for okf in $(grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null); do
      base="${okf%.out}"
      rm -f "$base.out" "$base.pid" "$base.err"
    done
  fi
  if [ "$(pending_probes)" -lt "$MAX_PENDING" ]; then
    launch_probe
  fi
  sleep "$SLEEP"
done
