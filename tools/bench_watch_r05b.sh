#!/bin/bash
# Continuation-session watcher: when the wedged tunnel frees, run the
# outstanding round-5 A/B variants (sweep 3).  Probe discipline and the
# watch loop live in bench_watch_lib.sh: probes are never killed, at
# most MAX_PENDING of THIS watcher's probes live at once, sweeps run
# serially after a probe confirms the chip answers, and the watcher is
# done only when the sweep ran END TO END with no probe-guard timeout
# (a mid-sweep re-wedge leaves unmeasured variants; re-measuring a
# leading variant costs ~5 min, missing the tail silently costs the
# round).
set -u
cd "$(dirname "$0")/.."
PROBE_DIR=${PROBE_DIR:-/tmp/bench_probes_r05b}
SWEEP_LOG=bench_ab_r05_rest.log
. tools/bench_watch_lib.sh

sweep() {
  echo "=== sweep 3 via watcher ($(date -u +%T)) ==="
  run HOROVOD_BENCH_SCAN=10 || return            # confirm the 16,636 run
  run HOROVOD_BENCH_MODEL=bert HOROVOD_BENCH_BATCH=256 \
      HOROVOD_BENCH_REMAT=0 HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_BENCH_MODEL=bert HOROVOD_BENCH_BATCH=512 \
      HOROVOD_BENCH_REMAT=0 HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_BENCH_MODEL=resnet HOROVOD_BENCH_BATCH=256 \
      HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_BENCH_MODEL=longctx HOROVOD_BENCH_REMAT=0 || return
  run HOROVOD_BENCH_MODEL=longctx HOROVOD_BENCH_BATCH=2 \
      HOROVOD_BENCH_REMAT=0 || return
  run HOROVOD_BENCH_REMAT_POLICY=dots || return
  run HOROVOD_BENCH_REMAT_POLICY=dots HOROVOD_BENCH_REMAT_SKIP=0 || return
  run HOROVOD_BENCH_REMAT_POLICY=dots HOROVOD_BENCH_SCAN=10 || return
  run HOROVOD_FLASH_BLOCK=256 || return
  run HOROVOD_FLASH_ATTENTION=0 || return
}

watch_loop
