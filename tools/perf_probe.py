"""Perf probe: step timing + device trace for the flagship bench config.

Usage (real chip; keep /root/.axon_site on PYTHONPATH):

    python tools/perf_probe.py [--trace /tmp/hvd_trace] [--steps 10]
        [--flash-block 512] [--no-flash]

Runs the same ~1B llama training step as bench.py, prints per-step wall
time and MFU, and (with --trace) captures a Perfetto trace through
``hvd.start_profiler`` for kernel-level attribution (view in
ui.perfetto.dev or tensorboard).
"""

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--trace", default=None)
    p.add_argument("--flash-block", type=int, default=None,
                   help="override flash kernel block size (bq=bk)")
    p.add_argument("--no-flash", action="store_true")
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--remat", default="full", choices=["full", "dots"])
    p.add_argument("--loss-chunk", type=int, default=0)
    p.add_argument("--remat-skip", type=int, default=0)
    p.add_argument("--pipelined", action="store_true",
                   help="time like bench.py: sync once at the end")
    p.add_argument("--opt", default="adamw", choices=["adamw", "adamw_lp"])
    args = p.parse_args()

    if args.no_flash:
        os.environ["HOROVOD_FLASH_ATTENTION"] = "0"

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu import training
    from horovod_tpu.models import llama
    from horovod_tpu.parallel.mesh import MeshConfig, ParallelMesh
    from bench import detect_peak

    if args.flash_block:
        # the supported override mechanism (ops/flash_attention.py
        # _block_sizes reads it; keeps its <=0 and parse guards)
        os.environ["HOROVOD_FLASH_BLOCK"] = str(args.flash_block)

    cfg = llama.LlamaConfig(
        vocab_size=32768, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=8, d_ff=8192, max_seq_len=args.seq, remat=True,
        remat_policy=args.remat, loss_chunk=args.loss_chunk,
        remat_skip_layers=args.remat_skip)
    if jax.devices()[0].platform == "cpu":  # smoke-test shrink
        cfg = dataclasses.replace(
            cfg, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
            d_ff=1024, vocab_size=4096)
    n_chips = jax.local_device_count()
    pmesh = ParallelMesh(MeshConfig(dp=n_chips, pp=1, sp=1, tp=1))
    if args.opt == "adamw_lp":
        from horovod_tpu.optim.precision import adamw_lp
        opt = adamw_lp(3e-4)
    else:
        opt = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    ts = training.make_llama_train_step(cfg, pmesh, optimizer=opt)
    params, opt_state = ts.init_fn(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    sh = training.make_data_sharding(ts)
    toks = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.vocab_size, (args.batch * n_chips, args.seq)),
        jnp.int32), sh)
    tgts = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.vocab_size, (args.batch * n_chips, args.seq)),
        jnp.int32), sh)

    t0 = time.perf_counter()
    params, opt_state, loss = ts.step_fn(params, opt_state, toks, tgts)
    float(loss)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s")

    if args.trace:
        import horovod_tpu as hvd
        hvd.init()
        hvd.start_profiler(args.trace)

    if args.pipelined:
        # bench.py-style timing: one device sync at the end, so host
        # dispatch overlaps device steps (the deployment-realistic number)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, opt_state, loss = ts.step_fn(params, opt_state, toks,
                                                 tgts)
        float(loss)
        times = np.full(args.steps,
                        (time.perf_counter() - t0) / args.steps)
    else:
        times = []
        for _ in range(args.steps):
            t0 = time.perf_counter()
            params, opt_state, loss = ts.step_fn(params, opt_state, toks,
                                                 tgts)
            float(loss)
            times.append(time.perf_counter() - t0)

    if args.trace:
        import horovod_tpu as hvd
        hvd.stop_profiler()
        print(f"trace written to {args.trace}")

    times = np.asarray(times)
    tok = args.batch * n_chips * args.seq
    tps = tok / times.mean() / n_chips
    mfu = tps * 6 * llama.count_params(cfg) / (detect_peak() * 1e12)
    if args.pipelined:
        # amortized timing has no per-step distribution to report
        print(f"step: mean {times.mean()*1e3:.1f} ms (pipelined)")
    else:
        print(f"step: mean {times.mean()*1e3:.1f} ms  "
              f"min {times.min()*1e3:.1f} ms  "
              f"p90 {np.percentile(times, 90)*1e3:.1f} ms")
    print(f"{tps:.0f} tokens/s/chip  MFU {mfu:.3f}  "
          f"vs_baseline {mfu/0.40:.3f}")


if __name__ == "__main__":
    main()
