#!/usr/bin/env python
"""Checkpointless-recovery bench: fleet rebuild vs blob-store re-read.

Prices the recovery plane end to end on a loopback fleet of
:class:`~horovod_tpu.elastic.recovery.RecoveryAgent` workers whose frame
sizes come from a REAL ZeRO tile layout (``sharded_tile_layout`` over a
transformer-shaped tree — the same ``shard_numel`` arithmetic that
prices the shards themselves).  Three gated readings
(docs/elastic.md "Checkpointless recovery"):

  * **rebuild time A/B**: wall time to pull a lost worker's frame from
    its surviving replica over real RPC vs a simulated blob-store
    re-read (pinned first-byte latency + bandwidth model, actually
    slept) — the fleet rebuild must win;
  * **redundancy fraction**: steady-state push bytes per boundary must
    stay under a bounded fraction of the analytic per-worker gradient
    wire bytes (ring allreduce: ``2 * G * (N-1) / N``);
  * **liveness**: a pinned ``recovery.push`` chaos seed (delay on one
    rank, transport error on another) must show up in the injections
    counter AND the requeue counter through a driver-shaped
    ``GET /metrics/job`` scrape — a silently inert seed fails the run.

    python tools/bench_recovery.py           # 4-way fleet, ~8M params
    python tools/bench_recovery.py --smoke   # CI stage 10: fast gates

Results print as JSON; the last line is the CI summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# pinned blob-store model: object-store first-byte latency plus
# streaming bandwidth — deliberately favorable to the blob store (a
# warm regional bucket), so the gate is conservative
BLOB_FIRST_BYTE_S = 0.15
BLOB_BANDWIDTH_BPS = 200e6


def _make_tree(np, n_layers: int, width: int):
    tree = {"embed/table": np.zeros((width * 4 + 3, width), np.float32)}
    for i in range(n_layers):
        tree[f"layer{i:02d}/kernel"] = np.zeros((width, width),
                                                np.float32)
        tree[f"layer{i:02d}/bias"] = np.zeros((width + 1,), np.float32)
    return tree


def _mk_fleet(R, JsonRpcServer, size: int, every: int):
    agents, servers = [], []
    for r in range(size):
        a = R.RecoveryAgent(rank=r, size=size, mode="neighbor",
                            every=every, pull_deadline_s=20.0,
                            register=False)
        agents.append(a)
        servers.append(JsonRpcServer(a.worker_handlers(), secret=None))
    peers = {r: ("127.0.0.1", s.port) for r, s in enumerate(servers)}
    for a in agents:
        a.update_plan(0, peers)
    return agents, servers, peers


def _simulate_blob_restore(frame: bytes):
    """A checkpoint re-read from remote blob storage, enacted for real:
    sleep the pinned first-byte + streaming time, then decode."""
    from horovod_tpu.elastic.recovery import decode_frame
    time.sleep(BLOB_FIRST_BYTE_S + len(frame) / BLOB_BANDWIDTH_BPS)
    return decode_frame(frame)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--boundaries", type=int, default=12)
    ap.add_argument("--every", type=int, default=2,
                    help="push cadence in boundaries (default 2: at "
                         "cadence 1 the 3-copy frame is ~half the ring "
                         "allreduce bytes; 2 halves it under the gate)")
    ap.add_argument("--max-fraction", type=float, default=0.35,
                    help="redundancy / gradient-wire bytes gate")
    ap.add_argument("--smoke", action="store_true",
                    help="CI: tiny model, same gates, fast")
    args = ap.parse_args()
    if args.smoke:
        args.layers, args.width, args.boundaries = 2, 128, 4

    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import horovod_tpu.chaos as _chaos
    from horovod_tpu.elastic import recovery as R
    from horovod_tpu.metrics import aggregate
    from horovod_tpu.optim.distributed import sharded_tile_layout
    from horovod_tpu.runner.rpc import JsonRpcServer

    n = args.workers
    tree = _make_tree(np, args.layers, args.width)
    grad_bytes = sum(a.nbytes for a in tree.values())
    layout = sharded_tile_layout(tree, shards=n)
    # protected copies: Adam m+v plus the error-feedback residual
    shard_bytes = R.priced_tile_bytes(layout, state_copies=3)

    agents, servers, peers = _mk_fleet(R, JsonRpcServer, n, args.every)
    # driver-shaped merged metrics route: every reading below goes
    # through GET /metrics/job exactly as production scrapes it
    job_srv = JsonRpcServer({}, secret=None, get_routes={
        "metrics/job": lambda: (
            200, "text/plain; version=0.0.4; charset=utf-8",
            aggregate.scrape_and_merge(
                {"0": ("127.0.0.1", servers[0].port)}))})

    # pinned seed: a delay on rank 0's first push (liveness probe) and a
    # transport error on rank 2's first push (requeue path); both must
    # land in the injection counter or the seed was inert
    _chaos.install(_chaos.FaultSchedule.parse(
        "recovery.push rank=0 nth=1 action=delay:0.01;"
        "recovery.push rank=2 nth=1 action=error:injected push loss",
        seed=17))

    def payload_for(rank: int, step: int):
        gen = np.random.default_rng(1000 * rank + step)
        return {"tiles": gen.standard_normal(
            shard_bytes // 4).astype(np.float32)}

    push_bytes = 0
    t0 = time.perf_counter()
    try:
        for step in range(args.boundaries):
            for a in agents:
                a.note_boundary(step, payload_for(a.rank, step))
        # drain any chaos-requeued frame (next boundary would retry it)
        for a in agents:
            a.flush()
    finally:
        _chaos.uninstall()
    steady_s = time.perf_counter() - t0
    pushes = sum(1 for _ in range(0, args.boundaries, args.every)) * n
    frame_len = len(R.encode_frame(payload_for(0, 0)))
    push_bytes = frame_len * pushes

    # --- rebuild A/B: lose rank 1, rebuild from the fleet vs blob ------
    victim_frame = agents[2].store.get_replica(1)[1]
    fresh = R.RecoveryAgent(rank=1, size=n, mode="neighbor",
                            every=args.every, pull_deadline_s=20.0,
                            register=False)
    fresh.update_plan(0, {r: ep for r, ep in peers.items() if r != 1})
    t0 = time.perf_counter()
    rebuilt = fresh.rebuild(min_epoch=0)
    t_fleet = time.perf_counter() - t0
    t0 = time.perf_counter()
    from_blob = _simulate_blob_restore(victim_frame)
    t_blob = time.perf_counter() - t0

    # --- gates ---------------------------------------------------------
    # 1) correctness: the fleet rebuild IS the checkpoint, bit for bit —
    # identical to the simulated blob restore AND to the oracle payload
    last_push = ((args.boundaries - 1) // args.every) * args.every
    want = payload_for(1, last_push)["tiles"]
    assert rebuilt["tiles"].tobytes() == want.tobytes(), \
        "fleet rebuild is not bit-identical to the lost worker's state"
    assert from_blob["tiles"].tobytes() == rebuilt["tiles"].tobytes()
    # 2) latency: rebuilding from a peer beats re-reading a blob store
    assert t_fleet < t_blob, (t_fleet, t_blob)
    # 3) wire budget: redundancy bytes per boundary stay a bounded
    # fraction of the per-worker gradient ring-allreduce bytes
    redundancy_per_boundary = frame_len / args.every
    grad_wire = 2.0 * grad_bytes * (n - 1) / n
    fraction = redundancy_per_boundary / grad_wire
    assert fraction <= args.max_fraction, (fraction, args.max_fraction)

    # 4) observability through GET /metrics/job: recovery families
    # populated, the pinned seed provably live, the requeue retried
    fams = aggregate.parse_prometheus(aggregate.scrape(
        "127.0.0.1", job_srv.port, route="metrics/job"))
    def count(fam, suffix="_total", **want):
        return sum(v for nm, lbl, v in fams[fam]["samples"]
                   if nm.endswith(suffix)
                   and all(lbl.get(k) == w for k, w in want.items()))
    rebuild_count = count("hvd_recovery_time_seconds", "_count")
    assert rebuild_count >= 1, fams["hvd_recovery_time_seconds"]
    # one snapshot is lost by design (the injected push error is
    # superseded by the next boundary before its retry)
    assert count("hvd_recovery_snapshots_total") >= pushes - 1
    injections = count("hvd_chaos_injections_total",
                       site="recovery.push")
    assert injections >= 2, fams["hvd_chaos_injections_total"]["samples"]
    assert count("hvd_recovery_push_requeues_total") >= 1
    # the errored push was retried and landed (store holds rank 2)
    assert agents[3].store.get_replica(2) is not None

    result = {
        "workers": n,
        "grad_bytes": grad_bytes,
        "frame_bytes": frame_len,
        "cadence": args.every,
        "boundaries": args.boundaries,
        "steady_state_s": round(steady_s, 4),
        "push_bytes_total": push_bytes,
        "redundancy_fraction_of_grad_wire": round(fraction, 4),
        "rebuild_fleet_s": round(t_fleet, 4),
        "rebuild_blob_s": round(t_blob, 4),
        "speedup": round(t_blob / max(t_fleet, 1e-9), 2),
        "chaos_injections": int(injections),
        "rebuilds_on_metrics_job": int(rebuild_count),
    }
    print(json.dumps(result, indent=2, sort_keys=True))

    for s in servers + [job_srv]:
        s.close()
    print(f"bench_recovery {'smoke ' if args.smoke else ''}OK "
          f"(fleet {t_fleet * 1e3:.0f} ms vs blob {t_blob * 1e3:.0f} ms, "
          f"redundancy {fraction * 100:.0f}% of grad wire, "
          f"{int(injections)} live injections)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
