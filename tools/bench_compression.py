#!/usr/bin/env python
"""Quantized-collective microbench: wire bytes, overflow safety, and
error-feedback convergence on the CPU mesh.

Measures what ROADMAP item 2 changes — the bytes a gradient crosses the
wire with, and whether the block-scaled int8/fp8 staging (EQuARX-class;
``HOROVOD_COMPRESSION``) preserves training — three readings:

  * **wire bytes**: ring-model transmit bytes per worker computed from
    the TRACED collective schedule (``analysis/schedule.py``), for (a)
    the DCN stage of ``hierarchical_allreduce_p`` quantized vs
    full-width — the acceptance claim is >= 3.5x cross-group reduction —
    and (b) the full ``DistributedOptimizer`` step (quantized
    all_to_all/all_gather staging vs the fused psum plan),
  * **no-overflow**: a quantized SUM whose true value is far outside
    int8 range must come back correct (a naive int8 psum overflows at
    the second summand; the staging accumulates dequantized fp32),
  * **error-feedback convergence**: a toy regression trained at int8
    matches the full-width trajectory (documented bound) and every
    worker holds BIT-IDENTICAL weights after N steps — quantization
    error lives in the per-worker residual, never in replica skew.

    python tools/bench_compression.py          # full readings
    python tools/bench_compression.py --smoke  # CI: fast, asserts only

Results print as JSON; see docs/performance.md "Quantized collectives".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup_jax(n_devices: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _schedule_bytes(fn, args, axis_env, axis_filter=None):
    # ring-model accounting shared with bench_zero/bench_overlap
    # (horovod_tpu/analysis/wire.py; unit-tested in tests/test_wire.py)
    from horovod_tpu.analysis.wire import trace_transmit_bytes
    return trace_transmit_bytes(fn, args, axis_env, axis_filter,
                                entry="bench")


def bench_dcn_wire(jax, numel: int, groups: int, group: int, fmt):
    """Cross-group (DCN) transmit bytes of hierarchical_allreduce_p,
    full-width vs quantized cross stage."""
    import jax.numpy as jnp
    from horovod_tpu.ops.collectives import hierarchical_allreduce_p
    spec = (jax.ShapeDtypeStruct((numel,), jnp.float32),)
    env = [("hvd_cross", groups), ("hvd_local", group)]

    def full(x):
        return hierarchical_allreduce_p(x, "hvd_cross", "hvd_local",
                                        op="average")

    def quant(x):
        return hierarchical_allreduce_p(x, "hvd_cross", "hvd_local",
                                        op="average", wire_format=fmt)

    base = _schedule_bytes(full, spec, env, axis_filter="hvd_cross")
    comp = _schedule_bytes(quant, spec, env, axis_filter="hvd_cross")
    return {"numel": numel, "groups": groups, "group_size": group,
            "dcn_bytes_fp32": base, "dcn_bytes_quantized": comp,
            "dcn_ratio": round(base / comp, 2)}


def bench_distopt_wire(jax, fmt, n: int, layers: int, width: int):
    """Per-worker transmit bytes of one full DistributedOptimizer step,
    fused-psum plan vs quantized staging."""
    import jax.numpy as jnp
    import optax
    from horovod_tpu.optim.distributed import DistributedOptimizer

    params = {"embed": jnp.zeros((width * 4 + 3, width), jnp.float32)}
    for i in range(layers):
        params[f"l{i:02d}/kernel"] = jnp.zeros((width, width), jnp.float32)
        params[f"l{i:02d}/bias"] = jnp.zeros((width + 1,), jnp.float32)
    spec = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    env = [("bw", n)]

    def step_for(wire):
        tx = DistributedOptimizer(optax.adam(1e-3), axis_name="bw",
                                  threshold_bytes=1 << 20,
                                  wire_format=wire,
                                  wire_block_size=fmt.block_size)

        def step(g, p):
            u, _ = tx.update(g, tx.init(p), p)
            return u
        return step

    base = _schedule_bytes(step_for("none"), (spec, spec), env)
    comp = _schedule_bytes(step_for(fmt), (spec, spec), env)
    total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    return {"params": total, "workers": n,
            "step_bytes_fp32": base, "step_bytes_quantized": comp,
            "step_ratio": round(base / comp, 2)}


def bench_overflow(jax, fmt, n: int):
    """SUM far outside int8 range must survive the staging exactly
    (to quantization tolerance): fp32 accumulation, never int8 psum."""
    import numpy as np
    from horovod_tpu.ops.collectives import quantized_allreduce_p
    vals = np.stack([np.full((512,), 1000.0 + 7 * r, np.float32)
                     for r in range(n)])
    want = vals.sum(0)

    def f(v):
        out, _ = quantized_allreduce_p(v, "ow", fmt, op="sum")
        return out
    got = np.asarray(jax.pmap(f, axis_name="ow")(vals)[0])
    err = float(np.abs(got - want).max() / np.abs(want).max())
    assert err < 0.02, f"quantized sum overflowed/degraded: rel err {err}"
    return {"true_sum": float(want[0]), "int8_lane_max": 127,
            "rel_err": round(err, 6)}


def bench_training(jax, fmt, n: int, steps: int, seed: int = 0):
    """Toy regression, full-width vs quantized-with-error-feedback:
    final-loss parity and bit-identical replicas."""
    import numpy as np
    import optax
    from horovod_tpu.optim.distributed import DistributedOptimizer

    rng = np.random.default_rng(seed)
    dim, rows = 32, 64
    w_true = rng.standard_normal((dim, 1)).astype(np.float32)
    X = rng.standard_normal((n, rows, dim)).astype(np.float32)
    y = X @ w_true + 0.01 * rng.standard_normal(
        (n, rows, 1)).astype(np.float32)
    params0 = {"w": np.zeros((dim, 1), np.float32),
               "b": np.zeros((1,), np.float32)}

    def loss_fn(p, xb, yb):
        pred = xb @ p["w"] + p["b"]
        return ((pred - yb) ** 2).mean()

    def run(wire):
        tx = DistributedOptimizer(optax.adam(5e-2), axis_name="tw",
                                  threshold_bytes=64,
                                  wire_format=wire,
                                  wire_block_size=fmt.block_size)
        st = jax.pmap(lambda p, _: tx.init(p), axis_name="tw",
                      in_axes=(None, 0))(params0, np.zeros(n))

        def step(p, s, xb, yb):
            g = jax.grad(loss_fn)(p, xb, yb)
            u, ns = tx.update(g, s, p)
            return optax.apply_updates(p, u), ns

        f = jax.pmap(step, axis_name="tw", in_axes=(None, 0, 0, 0))
        p = params0
        for _ in range(steps):
            pstack, st = f(p, st, X, y)
            for leaf in jax.tree_util.tree_leaves(pstack):
                a = np.asarray(leaf)
                assert (a[0] == a[-1]).all(), \
                    "replicas diverged under the quantized wire"
            p = jax.tree_util.tree_map(lambda x: x[0], pstack)
        losses = [float(loss_fn(p, X[r], y[r])) for r in range(n)]
        return p, float(np.mean(losses))

    p_full, loss_full = run("none")
    p_q, loss_q = run(fmt)
    w_delta = float(max(np.abs(np.asarray(p_q[k]) - np.asarray(p_full[k]))
                        .max() for k in p_q))
    # documented bound (docs/performance.md): int8 + error feedback keeps
    # the final loss within 10% relative of full-width on the toy model
    rel = abs(loss_q - loss_full) / max(loss_full, 1e-9)
    assert rel < 0.10, (loss_q, loss_full)
    return {"steps": steps, "final_loss_fp32": round(loss_full, 6),
            "final_loss_quantized": round(loss_q, 6),
            "final_loss_rel_delta": round(rel, 4),
            "max_weight_delta": round(w_delta, 6),
            "replicas_identical": True}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=4,
                    help="CPU mesh size (default 4)")
    ap.add_argument("--format", default="int8",
                    help="wire format to bench (default int8)")
    ap.add_argument("--block", type=int, default=256,
                    help="scale block size (default 256)")
    ap.add_argument("--numel", type=int, default=1 << 20,
                    help="hierarchical payload elements (default 1M)")
    ap.add_argument("--steps", type=int, default=60,
                    help="training steps (default 60)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI: small sizes, assert invariants, fast")
    args = ap.parse_args()

    if args.smoke:
        args.numel, args.steps = 1 << 16, 25

    jax = _setup_jax(args.devices)
    sys.path.insert(0, REPO)
    from horovod_tpu.compression import resolve_wire_format
    fmt = resolve_wire_format(args.format, args.block)

    result = {
        "format": fmt.name,
        "block_size": fmt.block_size,
        "dcn": bench_dcn_wire(jax, args.numel, 2, args.devices // 2, fmt),
        "distopt": bench_distopt_wire(jax, fmt, args.devices,
                                      layers=2, width=64),
        "overflow": bench_overflow(jax, fmt, args.devices),
        "training": bench_training(jax, fmt, args.devices, args.steps),
    }
    print(json.dumps(result, indent=2, sort_keys=True))

    # invariants (always checked; --smoke exists so CI runs them fast):
    # the acceptance claim is the DCN-stage wire reduction — int8 at
    # block 256 models out at ~3.9x and must never fall below 3.5x
    assert result["dcn"]["dcn_ratio"] >= 3.5, result["dcn"]
    assert result["distopt"]["step_ratio"] >= 3.0, result["distopt"]
    if args.smoke:
        print("bench_compression smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
