#!/bin/bash
# Serial A/B of the bench.py llama_1b config knobs on the real chip.
# ONE TPU client at a time (the axon tunnel serializes; a killed client
# wedges the chip — let every run exit on its own).
#
#   bash tools/bench_ab.sh [steps]
#
# Prints one JSON line per variant; highest tokens/s wins and its knobs
# belong in BENCH defaults.
set -u
cd "$(dirname "$0")/.."
run() {
  echo "=== $* ==="
  # NO timeout wrapper: SIGTERM/SIGKILL on a mid-claim PJRT client is
  # exactly what wedges the tunnel (BENCH_NOTE_r03.md) — each variant
  # runs ~5 min; babysit the sweep rather than killing clients
  env "$@" python bench.py 2>&1 | grep -E '^\{' || echo FAILED
}
# r2-era configuration, pinned — including eager dispatch (bench.py
# defaults are now the round-5 measured winner: chunk2048 + lp +
# remat_skip2 + scan10, so every knob the winner moved must be pinned
# back here for the baseline row to stay the r2 configuration)
run HOROVOD_BENCH_LOSS_CHUNK=0 HOROVOD_BENCH_OPT=std HOROVOD_BENCH_REMAT_SKIP=0 HOROVOD_BENCH_SCAN=1
run HOROVOD_BENCH_NOOP=1   # current defaults (= the round-5 winner)
run HOROVOD_BENCH_LOSS_CHUNK=1024 HOROVOD_BENCH_OPT=lp HOROVOD_BENCH_REMAT_SKIP=1
# fused xent at the r2 config: pin SCAN=1 too, same reason as row 1
# (rows below it compare fused against the CURRENT defaults, so they
# inherit on purpose)
run HOROVOD_BENCH_FUSED_XENT=1 HOROVOD_BENCH_LOSS_CHUNK=0 HOROVOD_BENCH_OPT=std HOROVOD_BENCH_REMAT_SKIP=0 HOROVOD_BENCH_SCAN=1
run HOROVOD_BENCH_FUSED_XENT=1
run HOROVOD_BENCH_FUSED_XENT=1 HOROVOD_BENCH_REMAT_SKIP=1
run HOROVOD_BENCH_MODEL=bert
run HOROVOD_BENCH_MODEL=longctx
run HOROVOD_BENCH_MODEL=resnet
