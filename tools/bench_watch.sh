#!/bin/bash
# Round-5 tunnel watcher: poll for TPU availability all round and run the
# full A/B sweep the moment the claim lock frees.
#
# Discipline (BENCH_NOTE_r03/r04, memory: tpu-single-client):
#   - NEVER kill a mid-claim PJRT client (that is what wedges the tunnel);
#     probes are left running and exit cleanly on their own when the chip
#     frees or the relay drops them.
#   - at most MAX_PENDING live probes at a time, so a long wedge does not
#     accumulate an unbounded claim queue.
#   - ONE TPU client does real work at a time: the sweep runs only after a
#     probe confirms the chip answers.
set -u
cd "$(dirname "$0")/.."
PROBE_DIR=${PROBE_DIR:-/tmp/bench_probes_r05}
MAX_PENDING=${MAX_PENDING:-2}
SLEEP=${SLEEP:-300}
mkdir -p "$PROBE_DIR"

# wait for any already-running sweep to finish before watching (pgrep -f
# matches the sweep script's own processes; this watcher's cmdline does
# not contain "bench_ab.sh", so no self-match to filter)
while pgrep -f "tools/bench_ab.sh" > /dev/null; do sleep 60; done

launch_probe() {
  local tag="$PROBE_DIR/probe_$(date +%s)"
  setsid nohup python -c "import jax; jax.devices(); print('ok', flush=True)" \
    > "$tag.out" 2> "$tag.err" < /dev/null &
  echo "$!" > "$tag.pid"
  echo "$(date -u +%T) launched probe $tag (pid $!)" >> "$PROBE_DIR/watch.log"
}

chip_free() {
  # any probe (old or new) that printed ok proves the tunnel answers
  grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null | head -1
}

pending_probes() {
  local n=0
  for pidf in "$PROBE_DIR"/probe_*.pid; do
    [ -f "$pidf" ] || continue
    local pid out
    pid=$(cat "$pidf"); out="${pidf%.pid}.out"
    if kill -0 "$pid" 2>/dev/null && ! grep -q "^ok" "$out" 2>/dev/null; then
      n=$((n + 1))
    fi
  done
  echo "$n"
}

while true; do
  if [ -n "$(chip_free)" ]; then
    echo "$(date -u +%T) chip answered — running full A/B sweep" \
      >> "$PROBE_DIR/watch.log"
    # capture THIS sweep's output separately: the success check must see
    # only fresh rows, never value lines accumulated from earlier runs
    SWEEP_OUT=$(mktemp)
    bash tools/bench_ab.sh > "$SWEEP_OUT" 2>&1
    cat "$SWEEP_OUT" >> bench_ab_r05.log
    # success = at least one variant emitted a real JSON line (error
    # lines carry an "error" key; real runs never do, whatever the value)
    if grep '^{' "$SWEEP_OUT" | grep -v '"error"' \
        | grep -q '"value"'; then
      rm -f "$SWEEP_OUT"
      echo "$(date -u +%T) sweep produced numbers — watcher done" \
        >> "$PROBE_DIR/watch.log"
      exit 0
    fi
    rm -f "$SWEEP_OUT"
    # sweep ran but still failed (lock re-wedged mid-claim).  Consume
    # ONLY the stale ok markers: a probe that printed ok has already
    # exited, so removing its files is safe — probes still pending keep
    # their files so pending_probes() keeps counting them (never exceed
    # MAX_PENDING live claim clients; see header)
    for okf in $(grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null); do
      base="${okf%.out}"
      rm -f "$base.out" "$base.pid" "$base.err"
    done
  fi
  if [ "$(pending_probes)" -lt "$MAX_PENDING" ]; then
    launch_probe
  fi
  sleep "$SLEEP"
done
