#!/bin/bash
# Round-5 tunnel watcher: poll for TPU availability all round and run the
# full A/B sweep (tools/bench_ab.sh) the moment the claim lock frees.
# Probe discipline and the watch loop live in bench_watch_lib.sh.
set -u
cd "$(dirname "$0")/.."
PROBE_DIR=${PROBE_DIR:-/tmp/bench_probes_r05}
SWEEP_LOG=bench_ab_r05.log
. tools/bench_watch_lib.sh

# wait for any already-running sweep to finish before watching (pgrep -f
# matches the sweep script's own processes; this watcher's cmdline does
# not contain "bench_ab.sh", so no self-match to filter)
while pgrep -f "tools/bench_ab.sh" > /dev/null; do sleep 60; done

sweep() {
  echo "=== full A/B sweep via watcher ($(date -u +%T)) ==="
  bash tools/bench_ab.sh
}

watch_loop
