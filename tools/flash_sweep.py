"""Sweep flash-attention block sizes on the flagship bench config.

Usage (real chip):

    python tools/flash_sweep.py [--steps 8] [--blocks 256,384,512,768]

Runs the bench.py llama_1b step once per (bq=bk) candidate and prints a
table — feeds the answer back into ops/flash_attention._block_sizes.
Run serially: the axon tunnel admits ONE TPU client at a time.
"""

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--blocks", default="256,384,512,768")
    args = p.parse_args()

    results = {}
    for blk in [int(b) for b in args.blocks.split(",")]:
        cmd = [sys.executable, os.path.join(REPO, "tools", "perf_probe.py"),
               "--steps", str(args.steps), "--flash-block", str(blk)]
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=600, cwd=REPO)
        except subprocess.TimeoutExpired:
            # the timeout just killed a mid-claim TPU client, which is
            # exactly what wedges the axon tunnel (BENCH_NOTE_r03.md) —
            # every later candidate would hang too; stop the sweep
            results[blk] = "TIMEOUT"
            print(f"block {blk:4d}: TIMEOUT — aborting sweep (killed "
                  f"candidate likely wedged the TPU tunnel; remaining "
                  f"candidates would hang)")
            break
        line = next((ln for ln in out.stdout.splitlines()
                     if "tokens/s/chip" in ln), None)
        if line is None:
            tail = (out.stderr or out.stdout).strip().splitlines()[-3:]
            line = "FAILED: " + " | ".join(tail)
        results[blk] = line
        print(f"block {blk:4d}: {line}")
    best = max((b for b, l in results.items() if "tokens" in l),
               key=lambda b: float(results[b].split()[0]), default=None)
    if best is not None:
        print(f"best block: {best}")


if __name__ == "__main__":
    main()
