#!/usr/bin/env python
"""Tail-tolerant collective microbench: p99 round bound, bit-exact
parity, convergence cost, and byte conservation on the CPU mesh.

Measures what ISSUE 11 changes — whether one straggler host still sets
the DCN round time of a hierarchical fused reduce — on a virtual
(cross × local) CPU mesh (nested ``pmap`` over
``--xla_force_host_platform_device_count`` devices).  Four gates, all
asserted every run:

  * **p99 bound** (the tail claim itself): under a fixed
    ``collective.dcn`` chaos seed injecting an 800 ms arrival delay on
    one cross-group, the strict policy's round p99 tracks the injected
    delay (it waits the straggler out) while the bounded policy's p99
    stays ≤ ``deadline + ε`` — the deadline gate, not the slowest host,
    sets the round time.  The same rounds feed the stall inspector's
    straggler EWMA, which must conclusively finger the injected group.
  * **bit-exact parity**: the strict/bounded A/B runs ONE compiled
    program with a runtime ``fire`` gate (strict branch vs
    masked-bounded branch inside ``lax.cond``) — with no deadline
    firing (all-ones mask) the weights after ``--steps`` adam steps
    must be BIT-IDENTICAL across plain / sharded(-update) / int8-wire
    configs.  (Two separately compiled programs differ by XLA fusion
    ulps — the bench_overlap lesson — hence the runtime gate.)
  * **convergence cost**: a toy regression trained with a recurring
    straggler (one group excluded every third round) under ``bounded``
    and ``stale`` must keep its final loss within the documented gate
    of the strict trajectory (docs/performance.md "Tail-tolerant
    collectives").
  * **byte conservation**: ring-model transmit bytes
    (``analysis/wire.py``, ``strict=True`` accounting so an unmodeled
    primitive fails loudly) — bounded adds ONLY the pmin
    membership-agreement round over strict; stale's DCN hop rewrites
    the cross psum into a per-group all_gather at exactly G/2 the ring
    psum ratio.

    python tools/bench_tail.py               # 2x4 mesh
    python tools/bench_tail.py --smoke       # CI: 2x2, fast, asserts

Results print as JSON; see docs/performance.md "Tail-tolerant
collectives".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CROSS, LOCAL = "tc", "tl"   # DCN / ICI axis names


def _setup_jax(n_devices: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _pmap2(jax, fn, G, L, in_axes):
    """Nested pmap over the (cross, local) factoring: data leading dims
    [G, L, ...]."""
    inner = jax.pmap(fn, axis_name=LOCAL, in_axes=in_axes)
    outer_axes = tuple(0 if a is not None else None for a in in_axes)
    return jax.pmap(inner, axis_name=CROSS, in_axes=outer_axes)


# ---------------------------------------------------------------------------
# gate 1: chaos-seeded p99 round bound + straggler scoring
# ---------------------------------------------------------------------------

def bench_p99(jax, G, L, rounds, delay_s, deadline_s):
    import numpy as np
    import horovod_tpu.chaos as chaos
    from horovod_tpu.ops import collectives
    from horovod_tpu.stall import StallInspector

    x = np.arange(G * L * 64, dtype=np.float32).reshape(G, L, 64)

    def reduce_fn(xs, present):
        red, _, _ = collectives.tail_allreduce_p(
            xs, CROSS, "bounded", present=present, agree_axes=(LOCAL,))
        return red
    f = _pmap2(jax, reduce_fn, G, L, in_axes=(0, None))
    f(x, np.ones(G, np.float32))   # warm the compile out of the timings

    def run(policy):
        insp = StallInspector(check_time=1e9, use_native=False)
        sched = chaos.FaultSchedule.parse(
            f"collective.dcn group=1 every=3 action=delay:{delay_s}",
            seed=11)
        chaos.install(sched)
        times = []
        try:
            for _ in range(rounds):
                t0 = time.perf_counter()
                present = collectives.tail_round(
                    "bench_tail", policy, G, deadline_s, stall=insp)
                out = f(x, np.asarray(present, np.float32))
                jax.block_until_ready(out)
                times.append(time.perf_counter() - t0)
        finally:
            chaos.uninstall()
        assert sched.fired_at("collective.dcn"), \
            "chaos seed was inert: no collective.dcn injection fired"
        return np.asarray(times), insp.straggler_scores()

    t_strict, _ = run("strict")
    t_bounded, scores = run("bounded")
    p99_strict = float(np.quantile(t_strict, 0.99))
    p99_bounded = float(np.quantile(t_bounded, 0.99))
    eps = 0.1
    # the tail claim: strict p99 tracks the injected delay, bounded p99
    # is bounded by the deadline — not by the slowest host
    assert p99_strict >= delay_s, (p99_strict, delay_s)
    assert p99_bounded <= deadline_s + eps, (p99_bounded, deadline_s)
    # the same rounds must conclusively finger the straggler
    assert scores.get(1, 0.0) > scores.get(0, 0.0) and scores[1] > 0.0, \
        scores
    return {
        "rounds": rounds, "injected_delay_s": delay_s,
        "deadline_s": deadline_s,
        "p99_strict_s": round(p99_strict, 4),
        "p99_bounded_s": round(p99_bounded, 4),
        "p50_strict_s": round(float(np.quantile(t_strict, 0.5)), 4),
        "p50_bounded_s": round(float(np.quantile(t_bounded, 0.5)), 4),
        "straggler_scores": {str(k): round(v, 4)
                             for k, v in sorted(scores.items())},
    }


# ---------------------------------------------------------------------------
# gate 2: one-program strict/bounded A/B, bit-identical weights
# ---------------------------------------------------------------------------

def _toy_data(np, G, L, dim, rows, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal((dim, 1)).astype(np.float32)
    X = rng.standard_normal((G, L, rows, dim)).astype(np.float32)
    y = X @ w_true + 0.01 * rng.standard_normal(
        (G, L, rows, 1)).astype(np.float32)
    return X, y


def _loss(jnp, p, xb, yb):
    pred = xb @ p["w"] + p["b"]
    return ((pred - yb) ** 2).mean()


def bench_ab(jax, G, L, steps, threshold, wire_format=None):
    """plain / int8 config: grads reduced with fused_tail_reduce_tree,
    one program whose cond arm flips strict <-> bounded."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.optim.distributed import fused_tail_reduce_tree

    dim, rows = 24, 32
    X, y = _toy_data(np, G, L, dim, rows)
    params0 = {"w": np.zeros((dim, 1), np.float32),
               "b": np.zeros((1,), np.float32)}
    tx = optax.adam(5e-2)

    def step(p, s, xb, yb, fire, present):
        g = jax.grad(lambda q: _loss(jnp, q, xb, yb))(p)

        def armed(gg):
            r, _ = fused_tail_reduce_tree(
                gg, CROSS, LOCAL, op="average", threshold_bytes=threshold,
                tail_policy="bounded", present=present,
                wire_format=wire_format)
            return r

        def boundary(gg):
            r, _ = fused_tail_reduce_tree(
                gg, CROSS, LOCAL, op="average", threshold_bytes=threshold,
                tail_policy="strict", wire_format=wire_format)
            return r

        g = jax.lax.cond(fire, armed, boundary, g)
        u, ns = tx.update(g, s, p)
        return optax.apply_updates(p, u), ns

    f = _pmap2(jax, step, G, L, in_axes=(None, None, 0, 0, None, None))
    s0 = tx.init(params0)
    ones = np.ones(G, np.float32)

    def trajectory(fire):
        p, s = params0, s0
        for _ in range(steps):
            pk, sk = f(p, s, X, y, np.asarray(fire), ones)
            for leaf in jax.tree_util.tree_leaves(pk):
                a = np.asarray(leaf).reshape(G * L, -1)
                assert (a[0] == a).all(), \
                    "replicas diverged under the tail reduce"
            p = jax.tree_util.tree_map(lambda a: a[0, 0], pk)
            s = jax.tree_util.tree_map(lambda a: a[0, 0], sk)
        return p

    p_on = trajectory(True)
    p_off = trajectory(False)
    for a, b in zip(jax.tree_util.tree_leaves(p_on),
                    jax.tree_util.tree_leaves(p_off)):
        a, b = np.asarray(a), np.asarray(b)
        assert (a == b).all(), \
            f"weights not bit-identical: max delta {np.abs(a - b).max()}"
    return {"steps": steps, "weights_bit_identical": True}


def bench_ab_sharded(jax, G, L, steps):
    """sharded config: ZeRO-style hierarchical update — psum_scatter
    over the local axis, the tail DCN stage (cond strict/bounded) on
    the 1/L chunk, adam on this worker's tile, all_gather of updated
    params — the per-chip-state composition the tail policy must not
    perturb."""
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu import compat
    from horovod_tpu.ops import collectives

    dim, rows = 24, 32
    X, y = _toy_data(np, G, L, dim, rows, seed=1)
    n_param = dim + 1
    pad = (-n_param) % L
    P = n_param + pad
    lr, b1, b2, eps = 5e-2, 0.9, 0.999, 1e-8

    def split(p_flat):
        return {"w": p_flat[:dim].reshape(dim, 1),
                "b": p_flat[dim:dim + 1]}

    def step(p_flat, m, v, t, xb, yb, fire, present):
        g = jax.grad(lambda q: _loss(jnp, split(q), xb, yb))(p_flat)
        gp = jnp.concatenate([g, jnp.zeros((pad,), g.dtype)]) if pad else g
        chunk = compat.psum_scatter(gp, LOCAL)        # ICI stage: 1/L tile

        def armed(c):
            r, _, _ = collectives.tail_allreduce_p(
                c, CROSS, "bounded", present=present, agree_axes=(LOCAL,))
            return r

        def boundary(c):
            r, _, _ = collectives.tail_allreduce_p(c, CROSS, "strict")
            return r

        chunk = jax.lax.cond(fire, armed, boundary, chunk) / (G * L)
        # adam on this worker's 1/L tile (state is tile-shaped)
        t2 = t + 1
        m2 = b1 * m + (1 - b1) * chunk
        v2 = b2 * v + (1 - b2) * chunk * chunk
        mh = m2 / (1 - b1 ** t2)
        vh = v2 / (1 - b2 ** t2)
        idx = jax.lax.axis_index(LOCAL)
        tile = jax.lax.dynamic_slice_in_dim(
            jnp.concatenate([p_flat, jnp.zeros((pad,), p_flat.dtype)])
            if pad else p_flat, idx * (P // L), P // L)
        new_tile = tile - lr * mh / (jnp.sqrt(vh) + eps)
        p_new = jax.lax.all_gather(new_tile, LOCAL, tiled=True)[:n_param]
        return p_new, m2, v2, t2

    f = _pmap2(jax, step, G, L,
               in_axes=(None, 0, 0, None, 0, 0, None, None))
    ones = np.ones(G, np.float32)
    p0 = np.zeros((n_param,), np.float32)
    m0 = np.zeros((G, L, P // L), np.float32)
    v0 = np.zeros((G, L, P // L), np.float32)

    def trajectory(fire):
        p, m, v, t = p0, m0, v0, 0
        for _ in range(steps):
            pk, m, v, tk = f(p, m, v, np.float32(t), X, y,
                             np.asarray(fire), ones)
            a = np.asarray(pk).reshape(G * L, -1)
            assert (a[0] == a).all(), "replicas diverged (sharded tail)"
            p = np.asarray(pk)[0, 0]
            t = float(np.asarray(tk)[0, 0])
        return p

    p_on, p_off = trajectory(True), trajectory(False)
    import numpy as _np
    assert (_np.asarray(p_on) == _np.asarray(p_off)).all(), \
        "sharded weights not bit-identical"
    return {"steps": steps, "weights_bit_identical": True}


# ---------------------------------------------------------------------------
# gate 3: convergence cost under a recurring straggler
# ---------------------------------------------------------------------------

#: documented rel-loss gate (docs/performance.md "Tail-tolerant
#: collectives"): a 1-in-3-rounds straggler under bounded/stale must
#: keep the toy final loss within 15% relative of the strict run.
REL_LOSS_GATE = 0.15


def bench_training(jax, G, L, steps, threshold):
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.optim.distributed import fused_tail_reduce_tree

    dim, rows = 32, 64
    X, y = _toy_data(np, G, L, dim, rows, seed=2)
    params0 = {"w": np.zeros((dim, 1), np.float32),
               "b": np.zeros((1,), np.float32)}
    tx = optax.adam(5e-2)

    def make_step(policy):
        def step(p, s, state, xb, yb, present):
            g = jax.grad(lambda q: _loss(jnp, q, xb, yb))(p)
            g, new_state = fused_tail_reduce_tree(
                g, CROSS, LOCAL, op="average", threshold_bytes=threshold,
                tail_policy=policy, present=present,
                tail_state=state if policy == "stale" else None,
                max_staleness=4)
            u, ns = tx.update(g, s, p)
            if new_state is None:
                new_state = state
            return optax.apply_updates(p, u), ns, new_state
        return step

    def run(policy):
        step = make_step(policy)
        # stale threads per-bucket (prev, staleness) state; shapes come
        # from a throwaway trace on the real plan (init round, ones)
        f = _pmap2(jax, step, G, L,
                   in_axes=(None, None, 0, 0, 0, None))
        p, s = params0, tx.init(params0)
        # first call initializes state inside the trace (tail_state=None
        # path needs static None) — so thread an explicit zeros state
        # built by one abstract eval
        if policy == "stale":
            # per-bucket zeros state, shaped from the same plan the
            # traced step computes (prev [G, chunk] + staleness [G] per
            # device, stacked over the [G, L] mesh for pmap threading)
            from horovod_tpu.optim.distributed import (_plan_buckets,
                                                       _tree_leaves_sorted)
            from horovod_tpu.ops.fusion import pad_to_multiple
            leaves, names, _o = _tree_leaves_sorted(params0)
            buckets, _s = _plan_buckets(leaves, names, "average", 1.0,
                                        1.0, threshold,
                                        tail_policy="stale")
            state = tuple(
                (np.zeros((G, L, G,
                           pad_to_multiple(sum(leaves[i].size
                                               for i in b), L) // L),
                          np.float32),
                 np.zeros((G, L, G), np.int32))
                for b in buckets)
        else:
            state = tuple()
        losses = []
        for k in range(steps):
            present = np.ones(G, np.float32)
            if policy != "strict" and k % 3 == 2:
                present[G - 1] = 0.0   # the recurring straggler
            p_k, s_k, state = f(p, s, state, X, y, present)
            p = jax.tree_util.tree_map(lambda a: a[0, 0], p_k)
            s = jax.tree_util.tree_map(lambda a: a[0, 0], s_k)
        flat = [float(_loss(jnp, {k2: jnp.asarray(v) for k2, v in p.items()},
                            X[i, j], y[i, j]))
                for i in range(G) for j in range(L)]
        return p, float(np.mean(flat))

    _, loss_strict = run("strict")
    out = {"steps": steps, "final_loss_strict": round(loss_strict, 6)}
    for policy in ("bounded", "stale"):
        _, loss_p = run(policy)
        rel = abs(loss_p - loss_strict) / max(loss_strict, 1e-9)
        assert rel < REL_LOSS_GATE, (policy, loss_p, loss_strict, rel)
        out[f"final_loss_{policy}"] = round(loss_p, 6)
        out[f"rel_delta_{policy}"] = round(rel, 4)
    out["rel_loss_gate"] = REL_LOSS_GATE
    return out


# ---------------------------------------------------------------------------
# byte conservation: the tail adds only the agreement round
# ---------------------------------------------------------------------------

def bench_bytes(jax, G, L, threshold):
    import jax.numpy as jnp
    from horovod_tpu.analysis.schedule import trace_schedule
    from horovod_tpu.analysis.wire import (prim_counts,
                                           ring_transmit_bytes,
                                           schedule_transmit_bytes)
    from horovod_tpu.optim.distributed import fused_tail_reduce_tree

    sds = jax.ShapeDtypeStruct
    spec = {"w": sds((96, 8), jnp.float32), "b": sds((33,), jnp.float32)}
    env = [(CROSS, G), (LOCAL, L)]

    def step_for(policy):
        def step(g):
            r, _ = fused_tail_reduce_tree(
                g, CROSS, LOCAL, op="average", threshold_bytes=threshold,
                tail_policy=policy,
                present=(None if policy == "strict"
                         else jnp.ones((G,), jnp.float32)),
                max_staleness=4)
            return r
        return step

    scheds = {p: trace_schedule(step_for(p), (spec,), axis_env=env,
                                entry=f"bench_tail_{p}")
              for p in ("strict", "bounded", "stale")}
    sizes = dict(env)
    # strict accounting: an unmodeled primitive in any tail schedule
    # must fail the gate loudly, never be silently mis-priced
    total = {p: schedule_transmit_bytes(s, strict=True)
             for p, s in scheds.items()}
    agree = {p: sum(ring_transmit_bytes(r, sizes, strict=True)
                    for r in s.records if r.prim == "pmin")
             for p, s in scheds.items()}
    # bounded = strict + the pmin membership agreement, nothing else
    assert agree["strict"] == 0, prim_counts(scheds["strict"])
    assert agree["bounded"] > 0, prim_counts(scheds["bounded"])
    assert total["bounded"] == total["strict"] + agree["bounded"], \
        (total, agree)
    # stale rewrites the DCN psum into a per-group all_gather: ring
    # cost G/2 x the psum's on the cross axis (exact for even G)
    dcn_strict = schedule_transmit_bytes(scheds["strict"], sizes,
                                         axis_filter=CROSS, strict=True)
    dcn_stale = schedule_transmit_bytes(scheds["stale"], sizes,
                                        axis_filter=CROSS, strict=True)
    agree_c = sum(ring_transmit_bytes(r, sizes, strict=True)
                  for r in scheds["stale"].records
                  if r.prim == "pmin" and r.axes == [CROSS])
    assert dcn_stale - agree_c == dcn_strict * G // 2, \
        (dcn_stale, agree_c, dcn_strict, G)
    # and no stale schedule may carry a cross-axis psum at all
    assert not any(r.prim == "psum" and CROSS in r.axes
                   for r in scheds["stale"].records), \
        prim_counts(scheds["stale"])
    return {
        "prims": {p: prim_counts(s) for p, s in scheds.items()},
        "total_bytes": total,
        "agreement_bytes_bounded": agree["bounded"],
        "dcn_bytes_strict": dcn_strict,
        "dcn_bytes_stale": dcn_stale,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8,
                    help="CPU mesh size (default 8 -> 2x4 cross x local)")
    ap.add_argument("--groups", type=int, default=2,
                    help="cross (DCN) groups (default 2)")
    ap.add_argument("--rounds", type=int, default=24,
                    help="p99 sample rounds (default 24)")
    ap.add_argument("--delay", type=float, default=0.8,
                    help="injected straggler arrival delay, seconds")
    ap.add_argument("--deadline", type=float, default=0.25,
                    help="bounded-policy deadline, seconds")
    ap.add_argument("--steps", type=int, default=30,
                    help="training steps for the A/B + convergence gates")
    ap.add_argument("--threshold", type=int, default=512,
                    help="fusion threshold bytes (small: multi-bucket)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI: 2x2 mesh, fewer rounds/steps, asserts only")
    args = ap.parse_args()

    if args.smoke:
        args.devices, args.rounds, args.steps = 4, 9, 12

    jax = _setup_jax(args.devices)
    sys.path.insert(0, REPO)
    G = args.groups
    L = args.devices // G
    assert G * L == args.devices, (G, args.devices)

    result = {
        "mesh": {"cross": G, "local": L},
        "p99": bench_p99(jax, G, L, args.rounds, args.delay,
                         args.deadline),
        "ab_plain": bench_ab(jax, G, L, args.steps, args.threshold),
        "ab_int8": bench_ab(jax, G, L, args.steps, args.threshold,
                            wire_format="int8"),
        "ab_sharded": bench_ab_sharded(jax, G, L, args.steps),
        "training": bench_training(jax, G, L, args.steps,
                                   args.threshold),
        "bytes": bench_bytes(jax, G, L, args.threshold),
    }
    print(json.dumps(result, indent=2, sort_keys=True))
    if args.smoke:
        print("bench_tail smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
