# Shared probe-watcher scaffolding for the wedged-tunnel bench watchers
# (bench_watch*.sh source this).  Contract:
#   - caller defines sweep()   — serial bench runs, writes to stdout
#   - caller sets PROBE_DIR    — per-watcher probe state directory
#   - caller sets SWEEP_LOG    — file the sweep output is appended to
#   - then calls watch_loop
# Discipline (BENCH_NOTE_r03..r05): probes are NEVER killed — a
# SIGTERM/SIGKILL on a mid-claim PJRT client is what wedges the
# tunnel; at most MAX_PENDING of THIS watcher's probes are live at
# once (orphans from earlier runs are not ours to manage); sweeps run
# serially only after a probe confirms the chip answers.

MAX_PENDING=${MAX_PENDING:-2}
SLEEP=${SLEEP:-300}

run() {
  echo "=== $* ==="
  local out
  out=$(env "$@" python bench.py 2>&1 | grep -E '^\{' || echo FAILED)
  echo "$out"
  # Abort ONLY on a probe-guard timeout ('"error"' key): every later
  # variant would also park 300s while queueing one more orphan claim
  # client each.  A fast FAILED (compile error / OOM) is a property of
  # that variant — keep sweeping the rest.
  case "$out" in *'"error"'*) return 1;; esac
  return 0
}

launch_probe() {
  local tag="$PROBE_DIR/probe_$(date +%s)"
  setsid nohup python -c "import jax; jax.devices(); print('ok', flush=True)" \
    > "$tag.out" 2> "$tag.err" < /dev/null &
  echo "$!" > "$tag.pid"
  echo "$(date -u +%T) launched probe $tag (pid $!)" >> "$PROBE_DIR/watch.log"
}

chip_free() {
  grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null | head -1
}

pending_probes() {
  # THIS watcher's live, not-yet-answered probes only (orphans from
  # earlier bench runs are invisible to chip_free, so counting them
  # here would deadlock the watcher while they idle)
  local n=0
  for pidf in "$PROBE_DIR"/probe_*.pid; do
    [ -f "$pidf" ] || continue
    local pid out
    pid=$(cat "$pidf"); out="${pidf%.pid}.out"
    if kill -0 "$pid" 2>/dev/null && ! grep -q "^ok" "$out" 2>/dev/null; then
      n=$((n + 1))
    fi
  done
  echo "$n"
}

watch_loop() {
  mkdir -p "$PROBE_DIR"
  while true; do
    if [ -n "$(chip_free)" ]; then
      local SWEEP_OUT
      SWEEP_OUT=$(mktemp)
      sweep > "$SWEEP_OUT" 2>&1
      cat "$SWEEP_OUT" >> "$SWEEP_LOG"
      # Done only when the sweep produced at least one value and no
      # probe-guard error: a mid-sweep re-wedge leaves unmeasured
      # variants, so the watcher keeps retrying the full list.
      if ! grep '^{' "$SWEEP_OUT" | grep -q '"error"' \
          && grep '^{' "$SWEEP_OUT" | grep -q '"value"'; then
        rm -f "$SWEEP_OUT"
        echo "$(date -u +%T) sweep complete — watcher done" \
          >> "$PROBE_DIR/watch.log"
        return 0
      fi
      rm -f "$SWEEP_OUT"
      for okf in $(grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null); do
        local base="${okf%.out}"
        rm -f "$base.out" "$base.pid" "$base.err"
      done
    fi
    if [ "$(pending_probes)" -lt "$MAX_PENDING" ]; then
      launch_probe
    fi
    sleep "$SLEEP"
  done
}
