#!/usr/bin/env python
"""Serving-plane loopback bench: the four tail-latency gates, CPU-only.

Real OS worker processes run real ``ServingWorker`` pull loops against a
real ``ServingPlane`` over the HMAC-free loopback RPC transport; the
driver sweeps an OPEN-LOOP (seeded Poisson) arrival process over the
``serve_submit`` data path and measures per-request end-to-end latency
from the result stream.  Every gate must hold every run:

1. **throughput**: at ~0.9x the sequential path's capacity the cap-1
   plane queues hard (that IS the sequential serving system); the
   batched plane at >= 3x that offered load must complete everything
   with p50 no worse — micro-batching buys >= 3x throughput at equal
   p50.
2. **tail under chaos**: under the pinned ``serve.batch worker=1``
   delay seed one worker straggles every batch; the plane's EWMA
   rotation must evict it and the post-rotation p99 must sit under the
   bound (while the pre-rotation max proves the seed was not inert).
3. **elasticity**: SIGKILL a worker mid-traffic; the lease reaper
   requeues its in-flight batch and every request still completes with
   the right answer — zero lost requests.
4. **no recompiles**: across the whole sweep every worker's forward
   compiles at most once per shape bucket and never after warmup
   (``recompiles == 0``) — the compile-cache hit-rate invariant.

    python tools/bench_serve.py            # full sweep
    python tools/bench_serve.py --smoke    # CI: small matrix, all gates

Results print as JSON; see docs/serving.md and docs/performance.md.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The pinned chaos seed of gate 2: worker 1 sleeps on EVERY batch.
CHAOS_DELAY_S = 0.25
CHAOS_RULE = f"serve.batch worker=1 every=1 action=delay:{CHAOS_DELAY_S}"
CHAOS_SEED = 7

SEQ_BUCKETS = "8,16,32"
MAX_BATCH = 8

# -- llama phases (--paged / --mp): tiny llama, reduced bucket table --
# (6 compiled shapes per worker, not 12 — decode compiles dominate the
# phase wall on CPU and the gates need shapes, not scale)
LLAMA_SEQ = "8,16"
LLAMA_CAP = 4
LLAMA_NEW = 4
#: KV block size: 4 divides every seq bucket AND max_new_tokens, so
#: the paged logical width (blocks x 4) equals the dense max_len
#: (bucket + new) exactly — the bit-parity precondition.
LLAMA_BLOCK = 4
#: Shared prompt head of the reuse mix: exactly 2 full blocks.
LLAMA_HEAD = [7] * (2 * LLAMA_BLOCK)

#: Deterministic parity probes: the driver decodes these sequentially
#: (greedy_generate) and every serving path — paged, mesh-sliced —
#: must return bit-identical rows THROUGH the plane.  Lengths sweep
#: both seq buckets; first tokens are unique across the bench so no
#: probe shares a prefix block with the reuse mix.
VERIFY_PROMPTS = [
    [31, 5, 9, 2, 7],
    [37, 1, 8, 3, 6, 4, 2, 9],
    [41, 2, 2, 7, 5, 9, 1, 3, 8, 6, 4, 2],
    [43, 9, 4, 4, 1, 6, 2, 8, 5, 3, 7, 1, 9, 2, 6, 4],
]


def _percentile(sorted_vals, q):
    # lazy: sys.path gains the repo inside worker/_Phase setup
    from horovod_tpu.metrics.aggregate import percentile
    return percentile(sorted_vals, q)


# -- worker -------------------------------------------------------------------

def run_worker(args) -> int:
    sys.path.insert(0, REPO)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from horovod_tpu.runner.rpc import JsonRpcServer
    from horovod_tpu.serving.models import toy_echo_forward
    from horovod_tpu.serving.shapes import ShapeBuckets
    from horovod_tpu.serving.worker import ServingWorker

    kv_post_warmup = None
    if args.model == "toy":
        buckets = ShapeBuckets(
            batch_buckets=tuple(
                1 << i for i in range(MAX_BATCH.bit_length())
                if (1 << i) <= MAX_BATCH),
            seq_buckets=tuple(int(s) for s in SEQ_BUCKETS.split(",")))
        fwd = toy_echo_forward(buckets)
    else:
        from horovod_tpu.models import llama
        cfg = llama.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        buckets = ShapeBuckets(
            batch_buckets=tuple(
                1 << i for i in range(LLAMA_CAP.bit_length())
                if (1 << i) <= LLAMA_CAP),
            seq_buckets=tuple(int(s) for s in LLAMA_SEQ.split(",")))
        if args.model == "paged":
            from horovod_tpu.serving.models import \
                paged_llama_decode_forward
            fwd = paged_llama_decode_forward(
                params, cfg, LLAMA_NEW, buckets,
                block_size=LLAMA_BLOCK)
        elif args.model == "mp":
            from horovod_tpu.serving.models import mp_llama_decode_forward
            fwd = mp_llama_decode_forward(params, cfg, LLAMA_NEW,
                                          buckets, mp=2)
        else:
            raise SystemExit(f"unknown bench model {args.model!r}")
    # per-worker metrics exposition: the plane learns the port from the
    # pull payload, so the driver can scrape-and-merge /metrics across
    # workers exactly like the elastic driver's /metrics/job
    msrv = JsonRpcServer({}, secret=None)
    if args.model == "paged":
        # warm here (not in the worker loop) so the driver's exact
        # fresh/reuse block expectations can start from a post-warmup
        # allocator snapshot
        fwd.warmup()
        kv_post_warmup = fwd.allocator.stats()
    worker = ServingWorker(args.addr, args.port, fwd,
                           worker_id=str(args.id), wait_s=2.0,
                           secret=None, metrics_port=msrv.port,
                           warmup=args.model != "paged")
    worker.run()   # returns on the plane's {"stop"} after close()
    stats = worker.stats()
    if kv_post_warmup is not None:
        stats["kv_post_warmup"] = kv_post_warmup
        stats["pool_nbytes"] = fwd.pool_nbytes
        stats["n_blocks"] = fwd.allocator.n_blocks
    with open(args.out, "w") as f:
        json.dump(stats, f)
    msrv.close()
    return 0


# -- driver -------------------------------------------------------------------

class _Phase:
    """One plane + worker-pool lifecycle."""

    def __init__(self, n_workers: int, max_batch: int,
                 chaos: str = "", lease_s: float = 10.0,
                 straggler_factor: float = 0.0, tmp: str = ".",
                 model: str = "toy", seq_buckets: str = SEQ_BUCKETS,
                 cap: int = MAX_BATCH):
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from horovod_tpu.runner.rpc import JsonRpcServer
        from horovod_tpu.serving.plane import ServingPlane
        # buckets always cover the full batch table; ``max_batch`` only
        # moves the ADMISSION cap (cap 1 = the sequential baseline —
        # same plane, same workers, one request per forward)
        self.plane = ServingPlane(
            tick_ms=2.0, max_batch=cap, seq_buckets=seq_buckets,
            deadline_ms=0, lease_s=lease_s,
            straggler_factor=straggler_factor)
        if max_batch != cap:
            self.plane.set_max_batch(max_batch)
        self.srv = JsonRpcServer(self.plane.rpc_handlers(), secret=None)
        self.tmp = tmp
        self.procs = []
        for wid in range(n_workers):
            env = dict(os.environ)
            env.update({"JAX_PLATFORMS": "cpu",
                        "HOROVOD_TPU_FORCE_PLATFORM": "cpu",
                        "PYTHONPATH": REPO + os.pathsep
                        + env.get("PYTHONPATH", "")})
            env.pop("HOROVOD_SECRET_KEY", None)
            if model == "mp":
                # the mesh slice: 2 virtual CPU devices per worker
                # process (x 2 worker processes = the 2x2 bench mesh)
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + " --xla_force_host_platform_device_count=2").strip()
            if chaos:
                env["HVD_CHAOS"] = chaos
                env["HVD_CHAOS_SEED"] = str(CHAOS_SEED)
            else:
                env.pop("HVD_CHAOS", None)
            out = os.path.join(tmp, f"w{len(self.procs)}_{wid}.json")
            cmd = [sys.executable, os.path.abspath(__file__), "--worker",
                   "--id", str(wid), "--addr", "127.0.0.1",
                   "--port", str(self.srv.port), "--out", out,
                   "--model", model]
            self.procs.append((subprocess.Popen(cmd, env=env), out, wid))

    def wait_ready(self, timeout: float = 180.0):
        """Block until every worker has pulled once (jax import +
        shape warmup are seconds; traffic must not race them)."""
        deadline = time.monotonic() + timeout
        want = len(self.procs)
        while time.monotonic() < deadline:
            if len(self.plane.stats()["workers"]) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(f"only {len(self.plane.stats()['workers'])}"
                           f"/{want} bench workers came up")

    def submit(self, rid: str, tokens):
        from horovod_tpu.runner.rpc import json_request
        json_request("127.0.0.1", self.srv.port, "serve_submit",
                     {"id": rid, "tokens": tokens}, secret=None)

    def result(self, rid: str, wait_s: float = 30.0):
        # one serve_result hold is server-capped at 30 s; re-poll up to
        # the caller's deadline so a slow machine waits, never asserts
        from horovod_tpu.runner.rpc import json_request
        deadline = time.monotonic() + wait_s
        while True:
            hold = min(max(deadline - time.monotonic(), 0.0), 20.0)
            res = json_request("127.0.0.1", self.srv.port,
                               "serve_result",
                               {"id": rid, "wait_s": hold},
                               timeout=hold + 10.0, secret=None)
            if res.get("done") or time.monotonic() >= deadline:
                return res

    def drain(self, wait_s: float = 1.0):
        from horovod_tpu.runner.rpc import json_request
        return json_request("127.0.0.1", self.srv.port, "serve_drain",
                            {"wait_s": wait_s}, timeout=wait_s + 10.0,
                            secret=None)

    def close(self, expect_stats: bool = True) -> list:
        self.plane.close()
        stats = []
        for proc, out, wid in self.procs:
            try:
                rc = proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
            if rc == 0 and os.path.exists(out):
                with open(out) as f:
                    stats.append(json.load(f))
            elif expect_stats and rc not in (0, -9):
                raise RuntimeError(f"bench worker {wid} exited {rc}")
        self.srv.close()
        return stats


def _open_loop(phase: _Phase, n: int, rate: float, seed: int,
               rng_tokens, tag: str, submitters: int = 4):
    """Submit ``n`` requests at seeded-Poisson ``rate``; wait for every
    result; returns (latencies sorted, per-request records, wall).

    The arrival SCHEDULE (tokens + absolute due times) is pre-generated
    single-threaded from the seed, then driven by several submitter
    threads — one thread's POST round-trip must not throttle the
    offered rate below the schedule.
    """
    rng = random.Random(seed)
    toks_list = [rng_tokens(rng) for _ in range(n)]
    due = []
    t_acc = 0.0
    for _ in range(n):
        t_acc += rng.expovariate(rate)
        due.append(t_acc)
    expected = {f"{tag}{i}": toks_list[i] for i in range(n)}
    submits: dict = {}
    records: dict = {}
    lock = threading.Lock()
    fail = []
    t0 = time.monotonic()

    def collector():
        # one fan-in serve_drain long-poll instead of a result poll per
        # request: the client must not throttle the offered rate
        hard = time.monotonic() + 120
        try:
            while len(records) < n and time.monotonic() < hard:
                reply = phase.drain(wait_s=1.0)
                t_done = time.monotonic()
                for rid, res in reply.get("results", {}).items():
                    toks = expected.get(rid)
                    if toks is None:
                        continue
                    assert res.get("done") and not res.get("expired"), \
                        (rid, res)
                    got = (res.get("output") or [])[:len(toks)]
                    assert got == [t * 2 + 1 for t in toks], \
                        f"{tag}: wrong answer for {rid}"
                    records[rid] = {"lat": float(res["latency_s"]),
                                    "t_done": t_done}
        except Exception as e:  # noqa: BLE001 - surfaced by the join
            fail.append(e)

    col = threading.Thread(target=collector, daemon=True)
    col.start()

    def submit_loop(indices):
        for i in indices:
            target = t0 + due[i]
            while True:
                dt = target - time.monotonic()
                if dt <= 0:
                    break
                time.sleep(min(dt, 0.0005))
            rid = f"{tag}{i}"
            t_submit = time.monotonic()
            phase.submit(rid, toks_list[i])
            with lock:
                submits[rid] = t_submit

    subs = [threading.Thread(
        target=submit_loop, args=(range(k, n, submitters),), daemon=True)
        for k in range(submitters)]
    for th in subs:
        th.start()
    for th in subs:
        th.join(timeout=120)
        assert not th.is_alive(), f"{tag}: submitter wedged"
    col.join(timeout=120)
    if fail:
        raise fail[0]
    assert len(records) == n, (f"{tag}: {len(records)}/{n} requests "
                               f"completed")
    wall = max(r["t_done"] for r in records.values()) - t0
    recs = [{"rid": rid, "t_submit": submits[rid],
             "t_done": r["t_done"], "lat": r["lat"]}
            for rid, r in records.items()]
    lats = sorted(r["lat"] for r in recs)
    return lats, recs, wall


def _tokens_sampler(rng):
    # lengths sweep all three seq buckets (workers pre-warm every
    # bucket, so this only varies which compiled shapes serve)
    length = rng.choice((5, 8, 13, 16, 21, 32))
    return [rng.randrange(0, 100) for _ in range(length)]


def _short_sampler(rng):
    # one seq class: the latency-gated phases keep the arrival stream
    # in a single shape bucket so micro-batches fill instead of
    # fragmenting across classes (real fleets route per shape class)
    length = rng.choice((3, 5, 8))
    return [rng.randrange(0, 100) for _ in range(length)]


def _gate(report, name, ok, detail):
    report["gates"][name] = {"ok": bool(ok), **detail}
    status = "PASS" if ok else "FAIL"
    print(f"gate {name}: {status} {json.dumps(detail)}", file=sys.stderr)
    if not ok:
        report["failed"] = True


def _submit_collect(phase: _Phase, reqs, tag: str,
                    stagger: float = 0.0) -> list:
    """Submit ``reqs`` (token lists), wait for every result, return the
    outputs in request order.  Deterministic closed-loop driver for the
    llama phases — the exact block-count gates need a known request
    set, not a Poisson sample."""
    for i, toks in enumerate(reqs):
        phase.submit(f"{tag}{i}", toks)
        if stagger:
            time.sleep(stagger)
    outs: dict = {}
    deadline = time.monotonic() + 120
    while len(outs) < len(reqs) and time.monotonic() < deadline:
        reply = phase.drain(wait_s=1.0)
        for rid, res in reply.get("results", {}).items():
            if not rid.startswith(tag):
                continue
            assert res.get("done") and not res.get("expired"), (rid, res)
            outs[rid] = res.get("output")
    assert len(outs) == len(reqs), \
        f"{tag}: {len(outs)}/{len(reqs)} requests completed"
    return [outs[f"{tag}{i}"] for i in range(len(reqs))]


def _verify_reference():
    """Driver-side sequential decode of VERIFY_PROMPTS — the
    bit-parity reference every serving path must match exactly
    (greedy_generate at the same max_len the bucketed forward uses)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from horovod_tpu.models import llama
    from horovod_tpu.models.generate import greedy_generate
    from horovod_tpu.serving.shapes import ShapeBuckets
    cfg = llama.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    buckets = ShapeBuckets(
        (1,), tuple(int(s) for s in LLAMA_SEQ.split(",")))
    ref = []
    for toks in VERIFY_PROMPTS:
        s = buckets.seq_bucket(len(toks))
        out = greedy_generate(params, cfg,
                              np.asarray([toks], np.int32), LLAMA_NEW,
                              max_len=s + LLAMA_NEW)
        ref.append([int(t) for t in np.asarray(out)[0]])
    return ref


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="CI run: small request counts, all four gates")
    p.add_argument("--paged", action="store_true",
                   help="paged-KV phase: tiny-llama worker through the "
                        "block allocator; exact byte/block gates + "
                        "prefix-reuse gate + bit-parity probes")
    p.add_argument("--mp", action="store_true",
                   help="model-parallel phase: 2 workers x mp=2 (the "
                        "2x2 CPU mesh); exact per-chip param-byte gate "
                        "+ bit-parity probes")
    p.add_argument("--seed", type=int, default=5)
    p.add_argument("--n-seq", type=int, default=150)
    p.add_argument("--n-batched", type=int, default=400)
    p.add_argument("--n-chaos", type=int, default=300)
    p.add_argument("--n-kill", type=int, default=200)
    # internal: worker mode
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--model", default="toy", help=argparse.SUPPRESS)
    p.add_argument("--id", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--addr", default="127.0.0.1", help=argparse.SUPPRESS)
    p.add_argument("--port", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--out", default="", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.worker:
        return run_worker(args)

    if args.smoke:
        args.n_seq, args.n_batched = 80, 240
        args.n_chaos, args.n_kill = 300, 120

    import tempfile
    report = {"gates": {}, "failed": False}
    all_worker_stats = []
    with tempfile.TemporaryDirectory(prefix="bench_serve_") as tmp:
        # ---- gates 1 + 4: sequential baseline vs batched, one worker ----
        phase = _Phase(n_workers=1, max_batch=1, tmp=tmp)
        try:
            phase.wait_ready()
            # closed-loop service probe: per-request latency with no
            # queueing — the sequential system's service time (sweeps
            # every seq class; the worker pre-warmed all shapes)
            svc = []
            rng = random.Random(args.seed)
            for i in range(24):
                rid = f"probe{i}"
                t0 = time.monotonic()
                phase.submit(rid, _tokens_sampler(rng))
                res = phase.result(rid, wait_s=60.0)
                assert res.get("done"), res
                svc.append(time.monotonic() - t0)
            svc_p50 = _percentile(sorted(svc), 0.5)
            # the sequential serving system AT LOAD: ~0.85x its
            # capacity, Poisson arrivals — the queueing its p50 pays
            # there is the cost micro-batching exists to remove
            seq_rate = 0.85 / svc_p50
            lats_seq, _, wall_seq = _open_loop(
                phase, args.n_seq, seq_rate, args.seed + 1,
                _short_sampler, "seq")
            thr_seq = args.n_seq / wall_seq

            phase.plane.set_max_batch(MAX_BATCH)
            batched_rate = 3.5 * thr_seq
            lats_b, _, wall_b = _open_loop(
                phase, args.n_batched, batched_rate, args.seed + 2,
                _short_sampler, "bat")
            thr_b = args.n_batched / wall_b
        finally:
            all_worker_stats += phase.close()
        p50_seq = _percentile(lats_seq, 0.5)
        p50_b = _percentile(lats_b, 0.5)
        report["sequential"] = {
            "service_p50_ms": round(svc_p50 * 1e3, 2),
            "offered_rps": round(seq_rate, 1),
            "throughput_rps": round(thr_seq, 1),
            "p50_ms": round(p50_seq * 1e3, 2),
            "p99_ms": round(_percentile(lats_seq, 0.99) * 1e3, 2)}
        report["batched"] = {
            "offered_rps": round(batched_rate, 1),
            "throughput_rps": round(thr_b, 1),
            "p50_ms": round(p50_b * 1e3, 2),
            "p99_ms": round(_percentile(lats_b, 0.99) * 1e3, 2)}
        _gate(report, "throughput_3x_at_equal_p50",
              # "equal p50" with a 10% measurement tolerance: both
              # medians ride loopback RPC + scheduler noise
              thr_b >= 3.0 * thr_seq and p50_b <= 1.10 * p50_seq,
              {"speedup": round(thr_b / max(thr_seq, 1e-9), 2),
               "p50_seq_ms": round(p50_seq * 1e3, 2),
               "p50_batched_ms": round(p50_b * 1e3, 2)})

        # ---- gate 2: chaos straggler + rotation ----
        phase = _Phase(n_workers=3, max_batch=MAX_BATCH,
                       chaos=CHAOS_RULE, straggler_factor=3.0, tmp=tmp)
        try:
            phase.wait_ready()
            lats_c, recs_c, _ = _open_loop(
                phase, args.n_chaos, 1.5 * thr_seq, args.seed + 3,
                _short_sampler, "chaos")
            stats = phase.plane.stats()
        finally:
            all_worker_stats += phase.close()
        rotated = [wid for wid, w in stats["workers"].items()
                   if w["rotated"]]
        # tail window: requests submitted after the rotation landed
        # (plus one injected-delay drain margin) must see healthy-path
        # latency — the straggler's last held batch finishes slow, but
        # nothing NEW rides it
        rot_at = max((w["rotated_at"] or 0.0
                      for w in stats["workers"].values()), default=0.0)
        tail = sorted(r["lat"] for r in recs_c
                      if r["t_submit"] >= rot_at + CHAOS_DELAY_S)
        p99_tail = _percentile(tail, 0.99)
        worst = max(lats_c)
        bound = 0.6 * CHAOS_DELAY_S
        report["chaos"] = {
            "rule": CHAOS_RULE, "seed": CHAOS_SEED,
            "rotated_workers": rotated,
            "p99_all_ms": round(_percentile(lats_c, 0.99) * 1e3, 2),
            "post_rotation_n": len(tail),
            "p99_post_rotation_ms": round(p99_tail * 1e3, 2),
            "max_ms": round(worst * 1e3, 2),
            "bound_ms": round(bound * 1e3, 2)}
        _gate(report, "chaos_p99_bounded_with_rotation",
              rotated == ["1"] and len(tail) >= args.n_chaos // 6
              and p99_tail <= bound and worst >= CHAOS_DELAY_S,
              {"rotated": rotated, "post_rotation_n": len(tail),
               "p99_post_rotation_ms": round(p99_tail * 1e3, 2),
               "bound_ms": round(bound * 1e3, 2),
               "seed_not_inert_max_ms": round(worst * 1e3, 2)})

        # ---- gate 3: kill a worker mid-traffic ----
        # the victim (worker 0) gets one injected 1.2 s batch hold; the
        # assassin SIGKILLs it MID-LEASE, so the requeue path is
        # exercised every run, not only on lucky timing
        phase = _Phase(n_workers=2, max_batch=MAX_BATCH, lease_s=2.0,
                       chaos="serve.batch worker=0 nth=10 "
                             "action=delay:1.2", tmp=tmp)
        killed = {"done": False}
        try:
            phase.wait_ready()
            victim = phase.procs[0][0]

            def assassin():
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if "0" in phase.plane.stats()["leased_workers"]:
                        # re-check after a beat: a normal ~ms lease has
                        # been pushed by now; the injected hold has not
                        time.sleep(0.15)
                        if "0" in phase.plane.stats()["leased_workers"]:
                            victim.kill()
                            killed["done"] = True
                            return
                    time.sleep(0.002)

            th = threading.Thread(target=assassin, daemon=True)
            th.start()
            lats_k, _, _ = _open_loop(
                phase, args.n_kill, 2.0 * thr_seq, args.seed + 4,
                _tokens_sampler, "kill")
            th.join(timeout=60)
            kstats = phase.plane.stats()
        finally:
            all_worker_stats += phase.close(expect_stats=False)
        requeued = kstats["queue"]["requeued"]
        _gate(report, "kill_worker_zero_lost",
              killed["done"] and len(lats_k) == args.n_kill
              and kstats["completed"] == args.n_kill and requeued >= 1,
              {"killed": killed["done"],
               "completed": kstats["completed"], "expected": args.n_kill,
               "requeued": requeued,
               "p99_ms": round(_percentile(lats_k, 0.99) * 1e3, 2)})

        # ---- paged-KV phase (--paged): exact bytes, reuse, parity ----
        verify_ref = None
        if args.paged or args.mp:
            verify_ref = _verify_reference()
        if args.paged:
            from horovod_tpu.models import llama as _llama
            from horovod_tpu.serving.paging import (dense_kv_nbytes,
                                                    kv_block_nbytes,
                                                    row_blocks)
            _cfg = _llama.tiny()
            bs, new = LLAMA_BLOCK, LLAMA_NEW
            phase = _Phase(n_workers=1, max_batch=LLAMA_CAP,
                           model="paged", seq_buckets=LLAMA_SEQ,
                           cap=LLAMA_CAP, tmp=tmp)
            try:
                phase.wait_ready()
                rngp = random.Random(args.seed + 7)
                # unique mix: first token unique per request, so no two
                # prompts share a prefix block — every block is fresh
                lens_a = [5, 8, 11, 16, 3, 13] * 4
                reqs_a = [[100 + i] + [rngp.randrange(0, 256)
                                       for _ in range(ln - 1)]
                          for i, ln in enumerate(lens_a)]
                _submit_collect(phase, reqs_a, "pgA", stagger=0.002)
                # shared-head mix: every prompt opens with the same 2
                # full blocks — request 0 allocates them, every later
                # request must reuse both
                n_b = 12
                reqs_b = [LLAMA_HEAD + [rngp.randrange(0, 256)
                                        for _ in range(3 + (i % 5))]
                          for i in range(n_b)]
                _submit_collect(phase, reqs_b, "pgB", stagger=0.002)
                # parity probes THROUGH the plane (padded, batched,
                # paged) vs the driver's sequential greedy_generate
                outs_v = _submit_collect(phase, VERIFY_PROMPTS, "pgV")
                # final probe burst: equal-length rows (one seq class,
                # distinct heads — no sharing), so whatever batch split
                # admission picks, the last batch's ledger must price
                # every real row at exactly row_blocks(9) blocks while
                # the dense cache would pay bucket-max for the whole
                # batch bucket, pad rows included
                probe_len = 9
                probes = [[60 + i] + [9] * (probe_len - 1)
                          for i in range(4)]
                _submit_collect(phase, probes, "pgP")
                plane_kv = phase.plane.stats()["kv"]
            finally:
                pstats = phase.close()
            all_worker_stats += pstats
            kv0 = pstats[0]["kv_post_warmup"]
            kv1 = pstats[0]["forward"]["kv"]
            pool_nbytes = pstats[0]["pool_nbytes"]
            n_blocks = pstats[0]["n_blocks"]
            blk = kv_block_nbytes(_cfg, bs)
            # exact accounting: the allocator's per-block price times
            # the pool size must equal tree_nbytes of the LIVE pool
            # arrays — priced, not estimated (the sharded_tile_layout
            # precedent)
            _gate(report, "paged_bytes_exact_vs_tree_nbytes",
                  kv1["block_nbytes"] == blk
                  and pool_nbytes == n_blocks * blk
                  and kv1["bytes_capacity"] == (n_blocks - 1) * blk,
                  {"block_nbytes": kv1["block_nbytes"],
                   "expected_block_nbytes": blk,
                   "pool_tree_nbytes": pool_nbytes,
                   "n_blocks": n_blocks})
            # per-row pricing: every real row of the last probe batch
            # held exactly ceil((len+new)/block) blocks, priced at the
            # exact per-block bytes, vs the dense cache's bucket-max
            # for the batch bucket (pad rows included — dense pays them)
            per_row = row_blocks(probe_len, new, bs)
            last = kv1["last"]
            from horovod_tpu.serving.shapes import ShapeBuckets
            bkts = ShapeBuckets(
                tuple(1 << i for i in range(LLAMA_CAP.bit_length())
                      if (1 << i) <= LLAMA_CAP),
                tuple(int(s) for s in LLAMA_SEQ.split(",")))
            s_bkt = bkts.seq_bucket(probe_len)
            dense_b = dense_kv_nbytes(
                _cfg, bkts.batch_bucket(last["rows"]), s_bkt + new)
            paged_b = last["bytes_in_use"]
            _gate(report, "paged_per_row_bytes_exact",
                  last["rows"] >= 1
                  and last["blocks"] == per_row * last["rows"]
                  and paged_b == per_row * last["rows"] * blk
                  and paged_b < dense_b,
                  {"last": last, "row_blocks": per_row,
                   "expected_bytes": per_row * last["rows"] * blk,
                   "dense_bucket_bytes": dense_b,
                   "paged_fraction": round(paged_b / dense_b, 4)})
            # exact block ledger across the whole request set: every
            # grant is either predicted-fresh or predicted-reused
            exp_reuse = len(LLAMA_HEAD) // bs * (n_b - 1)
            exp_total = (sum(row_blocks(ln, new, bs) for ln in lens_a)
                         + sum(row_blocks(len(r), new, bs)
                               for r in reqs_b)
                         + sum(row_blocks(len(p), new, bs)
                               for p in VERIFY_PROMPTS)
                         + per_row * len(probes))
            fresh_d = kv1["fresh"] - kv0["fresh"]
            reuse_d = kv1["reuse_hits"] - kv0["reuse_hits"]
            _gate(report, "paged_alloc_ledger_exact",
                  reuse_d == exp_reuse
                  and fresh_d == exp_total - exp_reuse
                  and kv1["in_use"] == 0,
                  {"fresh_delta": fresh_d, "reuse_delta": reuse_d,
                   "expected_total_blocks": exp_total,
                   "expected_reuse": exp_reuse,
                   "in_use_after_drain": kv1["in_use"]})
            # prefix reuse measurably cuts allocation under the
            # shared-head mix: the head blocks were allocated once and
            # served n_b requests
            _gate(report, "paged_prefix_reuse_cuts_blocks",
                  reuse_d > 0 and reuse_d == exp_reuse,
                  {"blocks_saved": reuse_d,
                   "shared_head_requests": n_b,
                   "saved_fraction_of_mix": round(
                       reuse_d / sum(row_blocks(len(r), new, bs)
                                     for r in reqs_b), 4)})
            _gate(report, "paged_parity_with_sequential",
                  outs_v == verify_ref,
                  {"probes": len(VERIFY_PROMPTS),
                   "match": outs_v == verify_ref})
            # satellite: the KV ledger rides serve_push onto the
            # plane's GET /serve/stats
            _gate(report, "paged_kv_on_serve_stats",
                  plane_kv is not None
                  and plane_kv["bytes_capacity"]
                  == kv1["bytes_capacity"],
                  {"plane_kv": plane_kv})
            report["paged"] = {
                "block_size": bs, "block_nbytes": blk,
                "pool_blocks": n_blocks,
                "fresh_blocks": fresh_d, "reused_blocks": reuse_d,
                "evictions": kv1["evictions"] - kv0["evictions"]}

        # ---- model-parallel phase (--mp): the 2x2 CPU mesh ----
        if args.mp:
            import jax as _jax
            from jax.sharding import PartitionSpec as _P
            from horovod_tpu.models import llama as _llama
            from horovod_tpu.training import fsdp_param_specs
            _cfg = _llama.tiny()
            phase = _Phase(n_workers=2, max_batch=LLAMA_CAP,
                           model="mp", seq_buckets=LLAMA_SEQ,
                           cap=LLAMA_CAP, tmp=tmp)
            try:
                phase.wait_ready()
                outs_m = _submit_collect(phase, VERIFY_PROMPTS, "mpV")
                rngm = random.Random(args.seed + 8)
                extra = [[51 + i] + [rngm.randrange(0, 256)
                                     for _ in range(7)]
                         for i in range(8)]
                _submit_collect(phase, extra, "mpX", stagger=0.002)
            finally:
                mstats = phase.close()
            all_worker_stats += mstats
            # expected per-chip residency: replicated leaves whole,
            # sharded leaves exactly 1/mp — computed from the same
            # specs the worker shards with
            shapes = _jax.eval_shape(
                lambda: _llama.init_params(_cfg,
                                           _jax.random.PRNGKey(0)))
            specs = fsdp_param_specs(shapes, 2, axis="hvd_serve_mp")
            is_p = lambda x: isinstance(x, _P)  # noqa: E731
            exp_chip = exp_full = 0
            for spec, leaf in zip(
                    _jax.tree_util.tree_leaves(specs, is_leaf=is_p),
                    _jax.tree_util.tree_leaves(shapes)):
                n = 1
                for d in leaf.shape:
                    n *= d
                n *= leaf.dtype.itemsize
                exp_full += n
                sharded = any(
                    "hvd_serve_mp" in (e if isinstance(e, tuple)
                                       else (e,))
                    for e in spec)
                exp_chip += n // 2 if sharded else n
            fwd_m = [s.get("forward", {}) for s in mstats]
            _gate(report, "mp_per_chip_bytes_exact",
                  len(fwd_m) == 2
                  and all(f.get("mp") == 2 for f in fwd_m)
                  and all(f.get("per_chip_param_nbytes") == exp_chip
                          for f in fwd_m)
                  and all(f.get("replica_param_nbytes") == exp_full
                          for f in fwd_m)
                  and exp_chip < exp_full,
                  {"per_chip_nbytes": exp_chip,
                   "replica_nbytes": exp_full,
                   "resident_fraction": round(exp_chip / exp_full, 4),
                   "mesh": "2 workers x mp=2"})
            _gate(report, "mp_parity_with_sequential",
                  outs_m == verify_ref,
                  {"probes": len(VERIFY_PROMPTS),
                   "match": outs_m == verify_ref})
            report["mp"] = {"workers": 2, "mp": 2,
                            "per_chip_param_nbytes": exp_chip,
                            "replica_param_nbytes": exp_full}

        # ---- gate 4: zero recompiles after warmup ----
        n_buckets_max = 4 * len(SEQ_BUCKETS.split(","))  # batch x seq
        fwd = [s.get("forward", {}) for s in all_worker_stats]
        recompiles = sum(f.get("recompiles", 0) for f in fwd)
        over = [f for f in fwd
                if f.get("compiles", 0) > n_buckets_max
                or f.get("compiles", 0) != f.get("shapes_seen", 0)]
        seen = max((f.get("shapes_seen", 0) for f in fwd), default=0)
        _gate(report, "zero_recompiles_after_warmup",
              recompiles == 0 and not over and seen >= 3
              and len(fwd) >= 4,
              {"recompiles": recompiles, "workers_reporting": len(fwd),
               "max_shapes_seen": seen,
               "bucket_ceiling": n_buckets_max})

    print(json.dumps(report, indent=2))
    if report["failed"]:
        print("bench_serve: GATE FAILURE", file=sys.stderr)
        return 1
    if args.smoke:
        print("bench_serve smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
