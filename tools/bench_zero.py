#!/usr/bin/env python
"""ZeRO sharded-update microbench: CPU-mesh A/B of sharded vs replicated.

Measures what ROADMAP item 1 changes — per-worker optimizer-state bytes
and the in-jit collective schedule — on the virtual CPU mesh (``pmap``
over ``--xla_force_host_platform_device_count`` devices; the same XLA
collective lowering that runs over ICI on hardware).  Three readings per
mode (replicated psum vs ``sharded_update=True`` reduce-scatter →
1/N update → allgather, arXiv:2004.13336):

  * **state bytes**: ``tree_nbytes`` of one worker's inner optimizer
    state (the HBM the update sharding frees N×),
  * **per-step wall time**: median of ``--repeats`` timed runs of
    ``--steps`` compiled steps (CPU collectives are memcpys, so this is
    a regression canary, not an ICI claim),
  * **collective schedule**: primitive counts from the jaxpr
    (``analysis/schedule.py``) — the reviewable proof that no
    full-gradient psum survives in sharded mode.

    python tools/bench_zero.py               # 4-way mesh, ~8M params
    python tools/bench_zero.py --smoke       # CI: fast correctness run

Results print as JSON; see docs/performance.md "Sharded weight update".
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup_jax(n_devices: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _make_params(jax, n_layers: int, width: int):
    """A transformer-shaped tree: per-layer kernels/biases + an embed
    table, with a deliberately odd bias size so buckets need padding."""
    import jax.numpy as jnp
    params = {"embed/table": jnp.zeros((width * 4 + 3, width),
                                       jnp.float32)}
    for i in range(n_layers):
        params[f"layer{i:02d}/kernel"] = jnp.zeros((width, width),
                                                   jnp.float32)
        params[f"layer{i:02d}/bias"] = jnp.zeros((width + 1,), jnp.float32)
    return params


def _schedule_counts(jax, tx, params, axis, n):
    from horovod_tpu.analysis.schedule import trace_schedule
    from horovod_tpu.analysis.wire import (schedule_prim_counts,
                                           schedule_transmit_bytes)
    spec = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)

    def step(g, p):
        u, _ = tx.update(g, tx.init(p), p)
        return u
    sched = trace_schedule(step, (spec, spec), axis_env=[(axis, n)],
                           entry="bench_zero")
    counts = schedule_prim_counts(sched)
    # ring-model per-worker wire bytes of the whole step (shared
    # accounting: analysis/wire.py) — sharded (RS+AG) must not exceed
    # the replicated fused-psum plan's bytes
    counts["_wire_bytes"] = schedule_transmit_bytes(sched)
    return counts


def _run_mode(jax, sharded: bool, params, axis: str, n: int,
              threshold: int, steps: int, repeats: int):
    import numpy as np
    import optax
    from horovod_tpu.optim.distributed import DistributedOptimizer
    from horovod_tpu.optim.precision import tree_nbytes

    devs = jax.devices()[:n]
    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=axis,
                              threshold_bytes=threshold,
                              sharded_update=sharded)
    state = jax.pmap(lambda p, _: tx.init(p), axis_name=axis,
                     in_axes=(None, 0), devices=devs)(params,
                                                      np.zeros(n))

    def step(p, s, g):
        import optax as _optax
        u, ns = tx.update(g, s, p)
        return _optax.apply_updates(p, u), ns

    f = jax.pmap(step, axis_name=axis, in_axes=(None, 0, 0),
                 out_axes=(0, 0), devices=devs)
    rng = np.random.default_rng(0)
    grads = jax.tree_util.tree_map(
        lambda x: rng.standard_normal((n,) + x.shape,
                                      dtype=np.float32) * 1e-2, params)

    # compile + warm
    pstack, state = f(params, state, grads)
    jax.block_until_ready(pstack)
    p0 = jax.tree_util.tree_map(lambda x: x[0], pstack)

    times = []
    for _ in range(repeats):
        p, st = p0, state
        t0 = time.perf_counter()
        for _ in range(steps):
            pstack, st = f(p, st, grads)
            p = jax.tree_util.tree_map(lambda x: x[0], pstack)
        jax.block_until_ready(pstack)
        times.append((time.perf_counter() - t0) / steps)

    per_worker_state = jax.tree_util.tree_map(lambda x: x[0], state)
    return {
        "mode": "sharded" if sharded else "replicated",
        "inner_state_bytes_per_worker": tree_nbytes(
            per_worker_state.inner),
        "step_ms_median": round(statistics.median(times) * 1e3, 3),
        "schedule": _schedule_counts(jax, tx, params, axis, n),
    }, p0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=4,
                    help="CPU mesh size (default 4)")
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--threshold", type=int, default=1 << 20,
                    help="fusion threshold bytes (default 1 MiB)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--smoke", action="store_true",
                    help="CI: tiny model, assert invariants, fast")
    args = ap.parse_args()

    if args.smoke:
        args.layers, args.width = 2, 64
        args.threshold = 16 << 10
        args.steps, args.repeats = 3, 2

    jax = _setup_jax(args.devices)
    sys.path.insert(0, REPO)
    import numpy as np

    axis, n = "zw", args.devices
    params = _make_params(jax, args.layers, args.width)
    total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    rep, p_rep = _run_mode(jax, False, params, axis, n, args.threshold,
                           args.steps, args.repeats)
    sh, p_sh = _run_mode(jax, True, params, axis, n, args.threshold,
                         args.steps, args.repeats)

    result = {
        "devices": n,
        "params": total,
        "threshold_bytes": args.threshold,
        "replicated": rep,
        "sharded": sh,
        "state_bytes_ratio": round(
            rep["inner_state_bytes_per_worker"]
            / max(1, sh["inner_state_bytes_per_worker"]), 3),
    }
    print(json.dumps(result, indent=2, sort_keys=True))

    # invariants (always checked; --smoke exists so CI runs them fast):
    # the schedules ARE the claim — replicated never scatters, sharded
    # never materializes a full-gradient psum — and both modes step to
    # the same weights
    assert "psum" in rep["schedule"] and \
        "reduce_scatter" not in rep["schedule"], rep["schedule"]
    assert "psum" not in sh["schedule"], sh["schedule"]
    # same total ring bytes as the fused allreduce plan, modulo the
    # reduce-scatter's divisibility padding (shared accounting:
    # analysis/wire.py)
    assert sh["schedule"]["_wire_bytes"] <= \
        rep["schedule"]["_wire_bytes"] * 1.05, (sh, rep)
    assert sh["schedule"]["reduce_scatter"] == \
        sh["schedule"]["all_gather"], sh["schedule"]
    assert sh["inner_state_bytes_per_worker"] < \
        rep["inner_state_bytes_per_worker"], result
    for a, b in zip(jax.tree_util.tree_leaves(p_rep),
                    jax.tree_util.tree_leaves(p_sh)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    if args.smoke:
        print("bench_zero smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
