"""AOT compile rehearsal for BASELINE config 4 (Llama-3-8B DP, v5p-128).

The single tunneled chip cannot run the 8B workload, so this rehearses
it the AOT way: build the REAL ``llama3_8b()`` training step — dp x tp
mesh, vocab-parallel embedding/head, ZeRO-1, bf16-moment AdamW, chunked
vocab cross-entropy, full remat — over a SIMULATED 64-chip mesh
(v5p-128 = 64 chips) of virtual CPU devices, ``jax.jit(...).lower()``
it end to end (trace + StableHLO emission, no executable build), and
report the per-chip HBM the sharded train state needs, computed from
the actual shapes and NamedShardings.

Prints ONE JSON line; ``tests/test_llama.py`` runs this in a subprocess
and asserts the contract, and docs/estimators.md records the numbers.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=64")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402


def per_chip_bytes(tree_shapes, tree_shardings, mesh) -> int:
    """Bytes one chip holds for ``tree_shapes`` under ``tree_shardings``
    (a leaf's per-chip share is nbytes / prod(mesh axes in its spec))."""
    total = 0
    leaves_s = jax.tree_util.tree_leaves(tree_shapes)
    leaves_p = jax.tree_util.tree_leaves(
        tree_shardings, is_leaf=lambda x: hasattr(x, "spec"))
    assert len(leaves_s) == len(leaves_p), (len(leaves_s), len(leaves_p))
    for sh, nsh in zip(leaves_s, leaves_p):
        denom = 1
        for axes in nsh.spec:
            if axes is None:
                continue
            for ax in (axes if isinstance(axes, tuple) else (axes,)):
                denom *= mesh.shape[ax]
        total += sh.size * sh.dtype.itemsize // denom
    return total


def main():
    from horovod_tpu import training
    from horovod_tpu.models import llama
    from horovod_tpu.optim.precision import adamw_lp
    from horovod_tpu.parallel.mesh import MeshConfig, ParallelMesh

    dp, tp = llama.LLAMA8B_DP, llama.LLAMA8B_TP   # 64 chips = v5p-128
    seq = int(os.environ.get("REHEARSE_SEQ", "4096"))
    per_dp_batch = 1
    # the SAME configuration bench.py's llama8b_dp mode measures
    # (shared helper — rehearsal and measurement cannot drift apart)
    cfg = llama.llama3_8b_train_cfg(seq=seq)
    pmesh = ParallelMesh(MeshConfig(dp=dp, tp=tp))
    ts = training.make_llama_train_step(
        cfg, pmesh, optimizer=adamw_lp(3e-4), zero1=True)

    rng = jax.random.PRNGKey(0)
    params_s, opt_s = jax.eval_shape(ts.init_fn, rng)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params_s))

    B = per_dp_batch * dp
    tok = jax.ShapeDtypeStruct((B, seq), jnp.int32)
    lowered = ts.step_fn.lower(params_s, opt_s, tok, tok)
    hlo_bytes = len(lowered.as_text("stablehlo"))

    # per-chip steady-state HBM from the REAL shapes + shardings:
    # fp32 master params (tp-sharded; norms replicated) ...
    p_bytes = per_chip_bytes(params_s, ts.param_sharding, pmesh.mesh)
    # ... moments follow the param specs (norm moments are tp-replicated)
    # and ZeRO-1 additionally shards them over dp; non-param-shaped
    # leaves (step counters, scalars) are replicated
    pdef = jax.tree_util.tree_structure(params_s)

    def _is_param_tree(x):
        try:
            return jax.tree_util.tree_structure(x) == pdef
        except Exception:  # noqa: BLE001 - non-pytree nodes
            return False

    o_bytes = 0
    for sub in jax.tree_util.tree_leaves(opt_s, is_leaf=_is_param_tree):
        if _is_param_tree(sub):
            o_bytes += per_chip_bytes(sub, ts.param_sharding,
                                      pmesh.mesh) // dp
        else:
            o_bytes += sub.size * sub.dtype.itemsize
    # ... transient: bf16 compute copy of the tp shard + fp32 grads
    g_bytes = p_bytes                    # fp32 grads, param-sharded
    c_bytes = p_bytes // 2               # bf16 cast of the tp shard
    gib = 1 << 30

    # --- composed spec-aware plane (ISSUE 14): the same 8B geometry
    # under DistributedGradientTransform(param_specs=..., sharded_
    # update=True) with bf16 moments — tp is the model axis, dp the
    # data axis, and the per-chip moment bytes are the EXACT data-axis
    # tile sizes of the tp-local bucket layout (planner metadata, the
    # same accounting tools/bench_fsdp.py gates against the live state)
    from horovod_tpu.optim.distributed import (make_spec_plan,
                                               sharded_tile_layout)
    leaves_s = jax.tree_util.tree_leaves(params_s)
    leaves_p = jax.tree_util.tree_leaves(
        ts.param_sharding, is_leaf=lambda x: hasattr(x, "spec"))
    treedef = jax.tree_util.tree_structure(params_s)
    local_leaves, spec_leaves = [], []
    for sh, nsh in zip(leaves_s, leaves_p):
        dims = list(sh.shape)
        for d, axes in enumerate(nsh.spec):
            if axes is None:
                continue
            for ax in (axes if isinstance(axes, tuple) else (axes,)):
                dims[d] //= pmesh.mesh.shape[ax]
        local_leaves.append(jax.ShapeDtypeStruct(tuple(dims), sh.dtype))
        spec_leaves.append(nsh.spec)
    local_shapes = jax.tree_util.tree_unflatten(treedef, local_leaves)
    spec_tree = jax.tree_util.tree_unflatten(treedef, spec_leaves)
    layout = sharded_tile_layout(
        local_shapes, dp,
        spec_plan=make_spec_plan(spec_tree, "dp"))
    local_numel = sum(x.size for x in local_leaves)
    # 2 moments (mu, nu) x bf16 (2 B): replicated-DP vs tiled per chip
    mo_repl = 2 * 2 * local_numel
    mo_spec = 2 * 2 * sum(bl.shard_numel for bl in layout.buckets)

    # --- serving-side KV accounting (ISSUE 20): what one serving chip
    # holds for the decode cache at the real 8B shapes, priced exactly
    # (per-block bytes x block counts, the same ledger bench_serve's
    # --paged gates) — dense pays batch x bucket-max unconditionally;
    # paged pays ceil((len + new)/block) blocks per row
    from horovod_tpu.serving.paging import (dense_kv_nbytes,
                                            kv_block_nbytes, row_blocks)
    kv_block = 16
    kv_new = 256
    kv_batch = 8
    blk = kv_block_nbytes(cfg, kv_block)
    dense_bytes = dense_kv_nbytes(cfg, kv_batch, seq + kv_new)
    paged_at = {
        str(ln): kv_batch * row_blocks(ln, kv_new, kv_block) * blk
        for ln in (512, 1024, 2048, seq)}

    print(json.dumps({
        "ok": True,
        "n_params": int(n_params),
        "mesh": {"dp": dp, "tp": tp, "chips": dp * tp},
        "seq": seq,
        "global_batch": B,
        "stablehlo_bytes": hlo_bytes,
        "per_chip_gib": {
            "params_fp32": round(p_bytes / gib, 2),
            "opt_moments_bf16_zero1": round(o_bytes / gib, 2),
            "grads_fp32_transient": round(g_bytes / gib, 2),
            "bf16_copy_transient": round(c_bytes / gib, 2),
            "steady_plus_peak": round(
                (p_bytes + o_bytes + g_bytes + c_bytes) / gib, 2),
        },
        # ISSUE 14: the composed spec-aware path's state accounting
        # (exact planner tile bytes, not a fraction estimate) next to
        # the GSPMD zero1 number above — what the explicit gradient
        # plane holds when ZeRO tiles/quantized wire/overlap taps ride
        # the dp axis of the dp x tp mesh
        "specaware": {
            "moments_bf16_replicated_dp_bytes": mo_repl,
            "moments_bf16_zero_tiles_bytes": mo_spec,
            "state_drop_vs_replicated": round(mo_repl / mo_spec, 2),
            "per_chip_gib": round(mo_spec / gib, 3),
        },
        # ISSUE 20: serving decode-cache residency at the same shapes —
        # a batch of kv_batch rows decoding kv_new tokens from a
        # bucket_seq-token bucket.  Dense is the bucket-max buffer every
        # row pays; paged is the exact block count at the given TRUE
        # prompt length (the win grows as real lengths fall short of
        # the bucket)
        "serving_kv": {
            "block": kv_block,
            "block_nbytes": blk,
            "batch": kv_batch,
            "bucket_seq": seq,
            "max_new_tokens": kv_new,
            "dense_gib": round(dense_bytes / gib, 3),
            "paged_gib_at_len": {
                k: round(v / gib, 3) for k, v in paged_at.items()},
            "paged_fraction_at_len": {
                k: round(v / dense_bytes, 4)
                for k, v in paged_at.items()},
        },
        "v5p_hbm_gib": 95,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
