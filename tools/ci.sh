#!/bin/bash
# Build/test matrix (reference: the superbuild's framework x feature CI
# matrix, SURVEY.md §2.1 "Build system" + §4 test strategy).
#
#   bash tools/ci.sh [--quick]
#
# Stages:
#   1. package: wheel + sdist build (no isolation - deps are baked in).
#      dist/ artifacts are BUILD OUTPUTS, rebuilt fresh here every run —
#      they are not committed to git (they went stale against planner
#      fixes once; see CHANGES.md).
#   2. wheel install smoke: install the wheel into a scratch --target dir
#      and run an eager-collectives smoke from OUTSIDE the repo (catches
#      wheels that build but don't ship runnable code)
#   3. sdist install smoke: same, building from source (skipped --quick)
#   4. native:  build the C++ core in place, run its parity tests
#   5. purepy:  the HOROVOD_TPU_NATIVE_CORE=0 fallback paths
#   6. noctl:   single-process semantics with the controller disabled
#   7. full:    the whole suite (skipped with --quick)
#   8. hvdlint: static collective-consistency, lock-order, guarded-by
#      race and SPMD rank-divergence dataflow analysis (HVD200–HVD205)
#      over the framework and examples, gated on the findings baseline
#      (docs/analysis.md)
#   9. chaos:   the elastic join path under pinned fault-injection seeds
#      must converge, and the leader-join regression stays pinned
#      (docs/env.md "Chaos engineering")
#  10. bench:   tools/bench_control.py --smoke — real multi-process
#      negotiation over the RPC KV; watch-transport invariants (one
#      set + one watch per round, zero polled dir-gets) stay pinned —
#      tools/bench_zero.py --smoke — CPU-mesh A/B of the ZeRO
#      sharded update (1/N state bytes, no full-gradient psum in the
#      sharded schedule, sharded == replicated weights) — and
#      tools/bench_compression.py --smoke — quantized-wire invariants
#      (>=3.5x DCN bytes at int8, no overflow, error-feedback parity
#      with bit-identical replicas) — and tools/bench_overlap.py
#      --smoke — overlapped-dispatch invariants (per-layer buckets
#      inside the backward scan, boundary/overlapped weights
#      bit-identical incl. sharded x int8) — and tools/bench_tail.py
#      --smoke — tail-tolerant-collective invariants (chaos-seeded
#      p99 bound, strict/bounded one-program bit-exactness,
#      convergence gate, byte conservation) — and tools/bench_fsdp.py
#      --smoke — mesh-axis-aware gradient-plane invariants (exact
#      model-shard-fraction per-chip bytes, data-hop wire bytes with
#      int8 on the 2-D mesh, one-program fire-gated A/B bit-identical
#      weights across plain/zero/int8/int8+zero, replicated parity)
#      — and tools/hvdtrace
#      --smoke — merged-trace critical-path attribution over the
#      recorded chaos-seeded 4-host fixture (the injected straggler
#      must be the verdict) — and tools/hvddoctor --smoke —
#      training-health verdict under a pinned collective.corrupt seed
#      (the evaluator must name the injected rank+bucket via
#      GET /health/job; the clean run must stay verdict-free) — and
#      tools/bench_serve.py --smoke — serving-plane invariants
#      (batched >= 3x sequential throughput at equal p50, chaos-seeded
#      straggler rotated out with post-rotation p99 bounded,
#      kill-worker-mid-lease re-forms with zero lost requests, zero
#      post-warmup recompiles across the shape buckets)
#  11. hvdsched: re-trace the builtin step entries to jaxprs on CPU and
#      diff their collective schedules against tests/schedules/
#      (HVD211 drift; incl. the sharded_distopt_step reduce_scatter →
#      all_gather plan and the tail_distopt_step rewritten DCN stage) +
#      the cross-mesh-size consistency check
#      (HVD210); any fusion-plan change is an explicit snapshot update
#      in review (docs/analysis.md "Schedule snapshots"); incl. the
#      EMPTY serve_forward_step entry (a serving forward must never
#      negotiate a gradient collective)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/11 package: wheel + sdist =="
rm -rf dist/
python -m build --no-isolation --outdir dist/ . > /tmp/ci_build.log 2>&1 \
  || { tail -30 /tmp/ci_build.log; exit 1; }
ls -l dist/

echo "== 2/11 wheel install smoke (scratch target, run from /tmp) =="
WHEEL_TGT=$(mktemp -d)
trap 'rm -rf "$WHEEL_TGT"' EXIT
REPO_DIR="$(pwd)"

dist_smoke() {  # $1 = a wheel or sdist under dist/ (exactly one)
  if [ "$#" -ne 1 ]; then
    # the caller passes a glob: more than one match means stale
    # artifacts are lying around and we could smoke-test the wrong one
    echo "dist_smoke: expected exactly one artifact, got $#: $*" >&2
    exit 1
  fi
  if [ ! -f "$1" ]; then
    echo "dist_smoke: no such artifact: $1" >&2
    exit 1
  fi
  rm -rf "$WHEEL_TGT"/*
  pip install --no-deps --no-build-isolation --quiet \
    --target "$WHEEL_TGT" "$1"
  (cd /tmp && HOROVOD_TPU_FORCE_PLATFORM=cpu PYTHONPATH="$WHEEL_TGT" \
    REPO_DIR="$REPO_DIR" python - <<'PYEOF'
import os, sys
repo = os.environ["REPO_DIR"]
assert not any(p == repo for p in sys.path)
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["HOROVOD_CYCLE_TIME"] = "0.2"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd
assert "horovod_tpu" in hvd.__file__ and not hvd.__file__.startswith(repo)
hvd.init()
assert hvd.size() == 8
x = hvd.worker_values(lambda r: np.full((3,), float(r)))
np.testing.assert_allclose(
    np.asarray(hvd.allreduce(x, op=hvd.Sum)), np.full((3,), 28.0))

# hvdmetrics smoke: scrape /metrics + /healthz from a live server in the
# installed process; the core families must be present and the body must
# parse as Prometheus text format (docs/metrics.md)
import json
from horovod_tpu.metrics import aggregate
from horovod_tpu.runner.rpc import JsonRpcServer
srv = JsonRpcServer({}, secret=None)
health = json.loads(aggregate.scrape("127.0.0.1", srv.port,
                                     route="healthz"))
assert health["status"] == "ok", health
fams = aggregate.parse_prometheus(aggregate.scrape("127.0.0.1", srv.port))
for fam in ("hvd_engine_cycles_total", "hvd_cycle_duration_seconds",
            "hvd_negotiation_duration_seconds",
            "hvd_rpc_request_duration_seconds",
            "hvd_response_cache_total", "hvd_wire_bytes_total"):
    assert fam in fams, f"missing metric family {fam}"
# wire accounting (quantized collectives): the uncompressed allreduce
# above must have recorded its payload under format="float32"
wire = [(lbl, v) for _, lbl, v in fams["hvd_wire_bytes_total"]["samples"]
        if lbl.get("format") == "float32"]
assert wire and wire[0][1] >= 12, fams["hvd_wire_bytes_total"]["samples"]
assert fams["hvd_cycle_duration_seconds"]["type"] == "histogram"
cycles = [v for n, _, v in fams["hvd_engine_cycles_total"]["samples"]]
assert cycles and cycles[0] >= 1, cycles

# event-driven control plane smoke (ISSUE 5): one negotiation round over
# the installed RpcKvClient/KvServer must ride the long-poll watch and
# the keep-alive pool, and both must be visible on /metrics
import hashlib, threading, time
from horovod_tpu.ops import controller as ctl_mod
from horovod_tpu.runner.kv import KvServer, RpcKvClient
kv_srv = KvServer(secret=None)
kv_cli = RpcKvClient("127.0.0.1", kv_srv.port, secret=None)
orig_client, orig_pi = ctl_mod._client, ctl_mod.jax.process_index
ctl_mod._client = lambda: kv_cli
ctl_mod.jax.process_index = lambda: 0
try:
    ctl = ctl_mod.Controller(namespace="cismoke")
    tok = json.dumps(
        {"s": [["t", "allreduce", "sum", "float32", [2], 0, False, -1,
                1.0, 1.0]], "r": -1, "sp": None},
        separators=(",", ":"), sort_keys=True)
    gk = "g" + hashlib.sha1(b"0,1").hexdigest()[:12]
    h = hashlib.sha1(tok.encode()).hexdigest()
    for seq in range(2):
        threading.Timer(0.05, kv_srv.store.set,
                        (f"hvdctl/cismoke/{gk}/{seq}/a/1",
                         json.dumps({"h": h, "e": [tok]},
                                    separators=(",", ":")))).start()
        res = ctl.negotiate([tok], (0, 1))
        assert res.counts[tok] == 1, res
    st = ctl.stats()
    assert st["kv_dir_watches"] >= 2 and st["kv_dir_gets"] == 0, st
finally:
    ctl_mod._client = orig_client
    ctl_mod.jax.process_index = orig_pi
    kv_srv.close()
# overlapped-dispatch accounting (ROADMAP item 3): arm a toy grad tap
# and assert the trace-time bucket counter rides /metrics
import jax.numpy as jnp
import optax
from horovod_tpu.optim import overlap as ovl
from horovod_tpu.optim.distributed import DistributedOptimizer
otx = DistributedOptimizer(optax.sgd(1e-2), axis_name="smk",
                           threshold_bytes=1024, overlap=True)
def _ov_step(g):
    with ovl.overlapped_backprop(otx):
        _, gr = jax.value_and_grad(
            lambda p: (ovl.grad_tap(p)["a"] ** 2).sum())({"a": g})
    return gr
jax.make_jaxpr(_ov_step, axis_env=[("smk", 2)])(jnp.zeros((8,)))

# tail-tolerant collectives (ISSUE 11): one chaos-seeded bounded DCN
# round through the eager deadline gate — the straggler misses the
# deadline, is excluded from the mask, and both the round counter and
# its straggler score must land on /metrics
import horovod_tpu.chaos as hvchaos
from horovod_tpu.ops import collectives as hvcoll
from horovod_tpu.stall import StallInspector
insp = StallInspector(check_time=1e9, use_native=False)
hvchaos.install(hvchaos.FaultSchedule.parse(
    "collective.dcn group=1 nth=1 action=delay:0.3", seed=5))
try:
    present = hvcoll.tail_round("ci_smoke", "bounded", 2, 0.05,
                                stall=insp)
finally:
    hvchaos.uninstall()
assert list(present) == [1.0, 0.0], present
assert insp.straggler_scores()[1] > 0, insp.straggler_scores()

# training-health verdict plane (ISSUE 13): the fused dispatches above
# fed the eager numerics taps; the local GET /health route serves this
# worker's slice, and a driver-shaped GET /health/job merges >=2
# workers into one job verdict (healthy here — the corrupt-seeded
# unhealthy path is stage 10's hvddoctor smoke)
import horovod_tpu.health as hhealth
from horovod_tpu.health.evaluate import HealthEvaluator
assert hhealth.ACTIVE
hlocal = json.loads(aggregate.scrape("127.0.0.1", srv.port,
                                     route="health"))
assert hlocal["enabled"] and hlocal["healthy"], hlocal
assert hlocal["checks"]["stats_ingested"] >= 1, hlocal["checks"]
hevB = HealthEvaluator()
hevB.process, hevB.host = 1, "cismoke-hostB"
hsrvA = JsonRpcServer({"health_pull": hhealth.pull_handler}, secret=None)
hsrvB = JsonRpcServer({"health_pull": lambda p: hevB.snapshot()},
                      secret=None)
h_endpoints = {"0": ("127.0.0.1", hsrvA.port),
               "1": ("127.0.0.1", hsrvB.port)}
def _health_job_route():
    return (200, "application/json",
            json.dumps(hhealth.scrape_job_health(h_endpoints,
                                                 secret=None)))
hjsrv = JsonRpcServer({}, secret=None,
                      get_routes={"health/job": _health_job_route})
hjob = json.loads(aggregate.scrape("127.0.0.1", hjsrv.port,
                                   route="health/job"))
assert hjob["verdict"] == "healthy", hjob
assert hjob["scraped"] >= 2, hjob
assert not hjob["verdicts"], hjob["verdicts"]
for _s in (hsrvA, hsrvB, hjsrv):
    _s.close()

# job-wide distributed trace (ISSUE 12): the negotiation rounds above
# recorded spans into the installed tracer; serve them plus a second
# simulated host's buffer and scrape GET /trace/job (the driver-shaped
# merged route) — the result must be valid Chrome-trace JSON with one
# pid per host (>=2 distinct) and >=1 negotiation-round span per worker
import horovod_tpu.tracing as htrace
assert htrace.ACTIVE
neg_local = [s for s in htrace.buffer().snapshot()["spans"]
             if s["cat"] == "negotiate" and s["round"] >= 0]
assert len(neg_local) >= 2, neg_local
trbufB = htrace.SpanBuffer(host="cismoke-hostB", process=1)
trbufB.set_context(round=0, epoch=0)
_tB = trbufB.now()
trbufB.add("negotiate", "round0", _tB - 0.01, _tB, kind="full")
wsrvA = JsonRpcServer({"trace_pull": htrace.pull_handler}, secret=None)
wsrvB = JsonRpcServer({"trace_pull": trbufB.pull_handler()}, secret=None)
tr_endpoints = {"0": ("127.0.0.1", wsrvA.port),
                "1": ("127.0.0.1", wsrvB.port)}
def _trace_job_route():
    tr = htrace.merge.scrape_job_trace(tr_endpoints, probes=2,
                                       secret=None)
    return (200, "application/json", json.dumps(tr))
tsrv = JsonRpcServer({}, secret=None,
                     get_routes={"trace/job": _trace_job_route})
trace = json.loads(aggregate.scrape("127.0.0.1", tsrv.port,
                                    route="trace/job"))
host_pids = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
assert len(host_pids) >= 2, host_pids
tr_rounds = {}
for e in trace["traceEvents"]:
    if (e.get("ph") == "X" and e.get("cat") == "negotiate"
            and e["args"].get("round", -1) >= 0):
        tr_rounds[e["args"]["process"]] = \
            tr_rounds.get(e["args"]["process"], 0) + 1
assert tr_rounds.get(0, 0) >= 1 and tr_rounds.get(1, 0) >= 1, tr_rounds
from horovod_tpu.tracing import critical as htrace_critical
htrace_critical.analyze(trace)   # analyzable, not just parseable
for _s in (wsrvA, wsrvB, tsrv):
    _s.close()

# serving plane (ISSUE 15): an in-process plane + worker serve a small
# request burst end to end; hvd_serve_requests_total and a computable
# p99 from the request-latency histogram must ride a /metrics/job-shaped
# scrape-and-merge, and engine.stats() must grow a "serving" section
from horovod_tpu.serving.models import toy_echo_forward
from horovod_tpu.serving.plane import ServingPlane
from horovod_tpu.serving.worker import ServingWorker
splane = ServingPlane(tick_ms=2.0, max_batch=8, seq_buckets="8,16",
                      deadline_ms=0)
ssrv = JsonRpcServer(splane.rpc_handlers(), secret=None)
sworker = ServingWorker("127.0.0.1", ssrv.port,
                        toy_echo_forward(splane.buckets, burn_dim=32,
                                         burn_iters=1),
                        worker_id="0", wait_s=2.0, secret=None)
sworker.start()
# continuous telemetry plane (ISSUE 18): two explicit on-worker rings
# window the serve burst below (baseline at construction, so each
# window holds exactly the burst's deltas), then a driver-shaped
# GET /timeseries/job merges >=2 workers with a computable windowed
# serve p99 (docs/metrics.md "Time series")
from horovod_tpu.metrics import timeseries as hts
ts_ring_a = hts.TimeSeriesRing(window=8, every_s=60.0)
ts_ring_b = hts.TimeSeriesRing(window=8, every_s=60.0)
from horovod_tpu.runner.rpc import json_request as _jr
sids = []
for i in range(12):
    toks = [i, i + 1, i + 2]
    _jr("127.0.0.1", ssrv.port, "serve_submit",
        {"id": f"smoke{i}", "tokens": toks}, secret=None)
    sids.append((f"smoke{i}", toks))
for rid, toks in sids:
    res = _jr("127.0.0.1", ssrv.port, "serve_result",
              {"id": rid, "wait_s": 20.0}, secret=None)
    assert res.get("done") and res["output"][:3] == [t * 2 + 1
                                                    for t in toks], res
from horovod_tpu.runtime import _state as _hvd_state
est = _hvd_state().engine.stats()
assert est.get("serving", {}).get("plane", {})["completed"] == 12, \
    est.get("serving")
# job-shaped merge over this worker's /metrics: the serve families
# must merge and the latency histogram must yield a p99
merged = aggregate.parse_prometheus(aggregate.scrape_and_merge(
    {"0": ("127.0.0.1", srv.port)}))
sreq = sum(v for _, lbl, v
           in merged["hvd_serve_requests_total"]["samples"]
           if lbl.get("outcome") == "completed")
assert sreq >= 12, merged["hvd_serve_requests_total"]["samples"]
slat = [(lbl.get("le"), v) for nm, lbl, v
        in merged["hvd_serve_request_latency_seconds"]["samples"]
        if nm.endswith("_bucket")]
scount = sum(v for nm, _, v
             in merged["hvd_serve_request_latency_seconds"]["samples"]
             if nm.endswith("_count"))
assert scount >= 12, scount
sp99 = next(float(le) for le, cum in slat
            if le != "+Inf" and cum >= 0.99 * scount)
assert sp99 < 128.0, sp99   # inside the histogram's finite edges
ts_ring_a.sample()
ts_ring_b.sample()
def _ts_route(ring):
    def route():
        return (200, "application/json",
                json.dumps({"enabled": True, "windows": ring.windows()}))
    return route
tssrvA = JsonRpcServer({}, secret=None,
                       get_routes={"timeseries": _ts_route(ts_ring_a)})
tssrvB = JsonRpcServer({}, secret=None,
                       get_routes={"timeseries": _ts_route(ts_ring_b)})
tsjob = hts.scrape_job_timeseries(
    {"0": ("127.0.0.1", tssrvA.port), "1": ("127.0.0.1", tssrvB.port)})
assert tsjob["scraped"] >= 2, tsjob
assert not tsjob["unreachable"], tsjob["unreachable"]
ts_hist = tsjob["merged"]["histograms"][
    "hvd_serve_request_latency_seconds"]
# both rings windowed the same 12-request burst: 24 merged deltas and
# a finite windowed p99 (NaN would mean the window missed the burst)
assert ts_hist["count"] >= 24, ts_hist
assert ts_hist["p99"] == ts_hist["p99"], ts_hist
for _s in (tssrvA, tssrvB):
    _s.close()
splane.close()
sworker.stop(); sworker.join(10)
ssrv.close()

# checkpointless recovery (ISSUE 17): one push/rebuild pair over real
# loopback RPC in the installed process; the rebuilt frame must be
# bit-identical and the hvd_recovery_* families must carry samples on
# the same /metrics scrape every other plane rides
from horovod_tpu.elastic import recovery as hvrec
rec_a = hvrec.RecoveryAgent(rank=0, size=2, mode="neighbor", every=1,
                            pull_deadline_s=5.0, register=False)
rec_b = hvrec.RecoveryAgent(rank=1, size=2, mode="neighbor", every=1,
                            pull_deadline_s=5.0, register=False)
rsrvA = JsonRpcServer(rec_a.worker_handlers(), secret=None)
rsrvB = JsonRpcServer(rec_b.worker_handlers(), secret=None)
rpeers = {0: ("127.0.0.1", rsrvA.port), 1: ("127.0.0.1", rsrvB.port)}
rec_a.update_plan(0, rpeers)
rec_b.update_plan(0, rpeers)
rstate = np.arange(512, dtype=np.float32)
assert rec_b.note_boundary(0, {"tiles": rstate})
# worker 1 'dies'; a fresh agent (empty store) rebuilds from worker 0
rec_b2 = hvrec.RecoveryAgent(rank=1, size=2, mode="neighbor", every=1,
                             pull_deadline_s=5.0, register=False)
rec_b2.update_plan(0, {0: ("127.0.0.1", rsrvA.port)}, size=2)
rgot = rec_b2.rebuild(min_epoch=0)
assert rgot["tiles"].tobytes() == rstate.tobytes(), "rebuild not bit-exact"
rsrvA.close(); rsrvB.close()

fams = aggregate.parse_prometheus(aggregate.scrape("127.0.0.1", srv.port))
def _family_count(fam, **want):
    return sum(v for _, lbl, v in fams[fam]["samples"]
               if all(lbl.get(k) == w for k, w in want.items()))
overlap_buckets = _family_count("hvd_overlap_buckets_dispatched_total",
                                phase="bwd")
assert overlap_buckets >= 1, \
    fams["hvd_overlap_buckets_dispatched_total"]["samples"]
watch_rounds = _family_count("hvd_negotiation_rounds_total", kind="watch")
assert watch_rounds >= 2, fams["hvd_negotiation_rounds_total"]["samples"]
reuse_hits = _family_count("hvd_rpc_conn_reuse_total", result="hit")
assert reuse_hits >= 1, fams["hvd_rpc_conn_reuse_total"]["samples"]
tail_rounds = _family_count("hvd_tail_rounds_total", policy="bounded")
assert tail_rounds >= 1, fams["hvd_tail_rounds_total"]["samples"]
straggler = _family_count("hvd_straggler_score", process="1")
assert straggler > 0, fams["hvd_straggler_score"]["samples"]
rec_rebuilds = _family_count("hvd_recovery_rebuilds_total",
                             source="neighbor")
assert rec_rebuilds >= 1, fams["hvd_recovery_rebuilds_total"]["samples"]
rec_time = sum(v for nm, _, v
               in fams["hvd_recovery_time_seconds"]["samples"]
               if nm.endswith("_count"))
assert rec_time >= 1, fams["hvd_recovery_time_seconds"]["samples"]
assert _family_count("hvd_recovery_snapshots_total",
                     mode="neighbor") >= 1
# eager numerics taps fed the health gauge family on this process
assert "hvd_health_grad_norm" in fams, sorted(fams)
srv.close()

hvd.shutdown()
print(f"dist smoke OK (incl. /metrics + /healthz + /trace/job + "
      f"/health/job scrape, {int(watch_rounds)} watch rounds, "
      f"{int(reuse_hits)} keep-alive hits, {int(overlap_buckets)} "
      f"overlap buckets, {len(host_pids)} trace host pids, job health "
      f"{hjob['verdict']}, {int(sreq)} served requests @ p99<="
      f"{sp99:g}s, {int(rec_rebuilds)} fleet rebuild(s)), imported from",
      os.path.dirname(hvd.__file__))
PYEOF
  )
}

dist_smoke dist/*.whl
if [ "${1:-}" != "--quick" ]; then
  echo "== 3/11 sdist install smoke (builds from source) =="
  dist_smoke dist/*.tar.gz
fi

echo "== 4/11 native core build + parity tests =="
python setup.py build_ext --inplace > /tmp/ci_native.log 2>&1 \
  || { tail -30 /tmp/ci_native.log; exit 1; }
python -m pytest tests/test_native_core.py -q

echo "== 5/11 pure-python fallback (native core disabled) =="
HOROVOD_TPU_NATIVE_CORE=0 python -m pytest \
  tests/test_basics.py tests/test_fusion.py -q

echo "== 6/11 controller disabled (single-process semantics) =="
HOROVOD_TPU_CONTROLLER=0 python -m pytest tests/test_basics.py -q

if [ "${1:-}" != "--quick" ]; then
  echo "== 7/11 full suite =="
  python -m pytest tests/ -q
fi

echo "== 8/11 hvdlint static analysis =="
# all six engines (user rules, lock-order, guarded-by race detector,
# HVD200–HVD205 SPMD divergence dataflow, HVD400–HVD407 concurrency
# lifecycle, HVD300–HVD307 cross-layer contracts); --baseline: fail
# only on NEW findings vs the checked-in ratchet (EMPTY by policy, and
# refused outright if its analyzer_version is stale — docs/analysis.md
# "Baseline workflow").  One parse per file feeds every engine (the
# repo-wide contracts pass rides the same AST cache); the wall-time
# assert pins the whole run under 25 s (2x the ~12.3 s six-engine
# measurement on the CI runner, PR-16 convention) — so engine 6 can
# never quietly double the lint stage.
t_lint0=$(date +%s%N)
python -m horovod_tpu.analysis \
  --baseline tools/hvdlint_baseline.json horovod_tpu/ examples/
t_lint_ms=$(( ($(date +%s%N) - t_lint0) / 1000000 ))
echo "hvdlint wall: ${t_lint_ms} ms"
if [ "${t_lint_ms}" -gt 25000 ]; then
  echo "FAIL: hvdlint took ${t_lint_ms} ms (> 25000 ms budget)"; exit 1
fi
# SARIF export must stay wired for CI diff annotation: smoke-run it on
# the teaching fixture (findings guaranteed, exit 1 expected) and
# validate the log parses as SARIF 2.1.0 with results present.
python -m horovod_tpu.analysis --engine lifecycle --include-skipped \
  --sarif /tmp/ci_hvdlint.sarif examples/antipatterns.py >/dev/null || true
python - <<'PYEOF'
import json
log = json.load(open("/tmp/ci_hvdlint.sarif"))
assert log["version"] == "2.1.0", log.get("version")
results = log["runs"][0]["results"]
assert results, "SARIF smoke produced no results"
rules = {r["id"] for r in log["runs"][0]["tool"]["driver"]["rules"]}
assert {f"HVD{n}" for n in range(400, 408)} <= rules
print(f"hvdlint SARIF: {len(results)} result(s), schema ok")
PYEOF

echo "== 9/11 chaos smoke: elastic join under fixed fault seeds =="
python -m pytest tests/test_chaos.py -q \
  -k "converges_under_fault_seed or leader_join"

echo "== 10/11 control-plane bench smoke (watch transport invariants) =="
# fast correctness run of tools/bench_control.py: real multi-process
# negotiation over the RPC KV; asserts ZERO polled dir-gets and one
# set + one watch per steady-state round (docs/performance.md)
python tools/bench_control.py --smoke > /tmp/ci_bench_control.log 2>&1 \
  || { tail -30 /tmp/ci_bench_control.log; exit 1; }
tail -1 /tmp/ci_bench_control.log
# ZeRO sharded-update A/B: per-worker optimizer state must be 1/N-sized,
# the sharded schedule must contain NO full-gradient psum, and sharded
# and replicated steps must land on the same weights (docs/performance.md
# "Sharded weight update")
python tools/bench_zero.py --smoke > /tmp/ci_bench_zero.log 2>&1 \
  || { tail -30 /tmp/ci_bench_zero.log; exit 1; }
tail -1 /tmp/ci_bench_zero.log
# quantized collectives: the DCN-stage wire-bytes ratio must hold
# (>=3.5x for fp32 gradients at int8), a quantized SUM far outside int8
# range must not overflow, and error-feedback training must keep every
# replica bit-identical with final loss at parity (docs/performance.md
# "Quantized collectives")
python tools/bench_compression.py --smoke > /tmp/ci_bench_comp.log 2>&1 \
  || { tail -30 /tmp/ci_bench_comp.log; exit 1; }
tail -1 /tmp/ci_bench_comp.log
# overlapped dispatch: every per-layer fusion bucket must sit INSIDE
# the backward scan of the armed step (boundary step: none), the
# updates all-gather stays at the step boundary, and the one-program
# fire-gated A/B must land on bit-identical weights for plain /
# sharded / int8 / int8+sharded (docs/performance.md "Overlapped
# dispatch")
python tools/bench_overlap.py --smoke > /tmp/ci_bench_overlap.log 2>&1 \
  || { tail -30 /tmp/ci_bench_overlap.log; exit 1; }
tail -1 /tmp/ci_bench_overlap.log
# tail-tolerant collectives: under the fixed collective.dcn 800ms delay
# seed, bounded-policy round p99 must stay <= deadline + eps while
# strict p99 tracks the injected delay; strict/bounded one-program A/B
# bit-identical across plain/sharded/int8 with no deadline firing; the
# bounded/stale toy-training rel-loss delta inside the documented gate;
# ring bytes conserved up to the pmin agreement round (strict
# accounting — unmodeled prims fail loudly).  (docs/performance.md
# "Tail-tolerant collectives")
python tools/bench_tail.py --smoke > /tmp/ci_bench_tail.log 2>&1 \
  || { tail -30 /tmp/ci_bench_tail.log; exit 1; }
tail -1 /tmp/ci_bench_tail.log
# mesh-axis-aware gradient plane: on the 2x2 (data x model) CPU mesh,
# per-chip param+opt-state bytes must sit at the EXACT model-shard
# fraction (tree_nbytes vs the planner's tile layout), the data-hop
# wire bytes must shrink with shard operands and >=3.5x further under
# int8 (strict ring accounting), the one-program fire-gated A/B must
# land on bit-identical weights across plain/zero/int8/int8+zero, and
# the spec-aware trajectory must match the flat replicated reference
# (docs/performance.md "Mesh-axis-aware sharding")
python tools/bench_fsdp.py --smoke > /tmp/ci_bench_fsdp.log 2>&1 \
  || { tail -30 /tmp/ci_bench_fsdp.log; exit 1; }
tail -1 /tmp/ci_bench_fsdp.log
# merged-trace critical path: replay the recorded chaos-seeded 4-host
# fixture (collective.dcn group=1 every=3 delay:0.8) through
# tools/hvdtrace — the injected straggler host must come out as the top
# critical-path contributor (docs/observability.md "Distributed trace")
bash tools/hvdtrace --smoke > /tmp/ci_hvdtrace.log 2>&1 \
  || { tail -30 /tmp/ci_hvdtrace.log; exit 1; }
tail -1 /tmp/ci_hvdtrace.log
# training-health doctor: under the pinned collective.corrupt seed on a
# 4-way CPU mesh, the evaluator must name the injected (rank, bucket),
# the verdict must surface through a driver-shaped GET /health/job
# scrape, and the clean run must stay verdict-free
# (docs/observability.md "Training health")
bash tools/hvddoctor --smoke > /tmp/ci_hvddoctor.log 2>&1 \
  || { tail -30 /tmp/ci_hvddoctor.log; exit 1; }
tail -1 /tmp/ci_hvddoctor.log
# SLO watchdog + hvdtop: under the pinned serve.batch delay seed the
# watchdog must name the injected serve_p99_s breach within one window
# over a real loopback serving plane and surface it through a
# driver-shaped GET /timeseries/job; the clean run must stay
# breach-free and the seed must be proven non-inert
# (docs/metrics.md "Time series")
bash tools/hvdtop --smoke > /tmp/ci_hvdtop.log 2>&1 \
  || { tail -30 /tmp/ci_hvdtop.log; exit 1; }
tail -1 /tmp/ci_hvdtop.log
# serving plane: real worker processes against a real ServingPlane on
# loopback — all four tail-latency gates must hold every run (batched
# >= 3x sequential at equal p50, chaos straggler rotated with p99
# bounded, SIGKILL-mid-lease loses zero requests, zero post-warmup
# recompiles), plus the paged-KV phase (allocator bytes == tree_nbytes
# exactly, per-row blocks beat bucket-max, prefix reuse cuts blocks,
# paged == dense outputs) and the model-parallel phase (per-chip param
# bytes == the exact 1/mp fraction on the 2x2 CPU mesh).
# (docs/serving.md)
python tools/bench_serve.py --smoke --paged --mp \
  > /tmp/ci_bench_serve.log 2>&1 \
  || { tail -30 /tmp/ci_bench_serve.log; exit 1; }
tail -1 /tmp/ci_bench_serve.log
# checkpointless recovery: a lost worker's ZeRO frame rebuilt from its
# surviving replica must be bit-identical AND faster than the pinned
# blob-store re-read model, steady-state redundancy bytes must stay
# under the gradient-wire fraction gate, and the pinned recovery.push
# chaos seed must prove itself live (injections + requeue counters on a
# driver-shaped GET /metrics/job).  (docs/elastic.md "Checkpointless
# recovery")
python tools/bench_recovery.py --smoke > /tmp/ci_bench_recovery.log 2>&1 \
  || { tail -30 /tmp/ci_bench_recovery.log; exit 1; }
tail -1 /tmp/ci_bench_recovery.log

echo "== 11/11 hvdsched: collective-schedule snapshots + consistency =="
# re-trace every builtin step entry to a jaxpr on CPU, diff against the
# committed tests/schedules/*.json (HVD211 — any fusion-plan change is
# an explicit `tools/hvdsched --update` in review) and require identical
# canonical schedules across mesh sizes (HVD210); incl. the
# overlapped_distopt_step entry whose per-layer collectives must sit
# inside the backward-scan sub-jaxpr, the health_distopt_step entry
# whose ONLY delta vs distopt_step is the divergence sentinel's
# checksum all_gather under its cadence cond, and the fsdp_distopt_step
# entry whose model-sharded buckets reduce-scatter shard-sized operands
# over the data axis alone (HVD210 sweeps the data axis: mesh shapes
# 2x2 and 4x2), and the serve_mp_forward_step entry whose schedule must
# be ONLY the spec all_gather hops over the serving model axis (the
# serve_forward_step empty-schedule pin, generalized).  The explicit
# entry-count assertion pins snapshot coverage: a deleted
# tests/schedules/*.json would otherwise let --check pass vacuously on
# the entries that remain.
n_sched=$(ls tests/schedules/*.json | wc -l)
if [ "${n_sched}" -ne 11 ]; then
  echo "FAIL: expected 11 schedule snapshots, found ${n_sched}"; exit 1
fi
sched_out=$(bash tools/hvdsched --check)
echo "${sched_out}"
case "${sched_out}" in
  *"11 entries clean"*) ;;
  *) echo "FAIL: hvdsched --check did not trace all 11 pinned entries"
     exit 1 ;;
esac
bash tools/hvdsched --check --consistency

echo "CI matrix: all stages green"
