#!/bin/bash
# Build/test matrix (reference: the superbuild's framework x feature CI
# matrix, SURVEY.md §2.1 "Build system" + §4 test strategy).
#
#   bash tools/ci.sh [--quick]
#
# Stages:
#   1. package: wheel + sdist build (no isolation - deps are baked in)
#   2. native:  build the C++ core in place, run its parity tests
#   3. purepy:  the HOROVOD_TPU_NATIVE_CORE=0 fallback paths
#   4. noctl:   single-process semantics with the controller disabled
#   5. full:    the whole suite (skipped with --quick)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/5 package: wheel + sdist =="
rm -rf dist/
python -m build --no-isolation --outdir dist/ . > /tmp/ci_build.log 2>&1 \
  || { tail -30 /tmp/ci_build.log; exit 1; }
ls -l dist/

echo "== 2/5 native core build + parity tests =="
python setup.py build_ext --inplace > /tmp/ci_native.log 2>&1 \
  || { tail -30 /tmp/ci_native.log; exit 1; }
python -m pytest tests/test_native_core.py -q

echo "== 3/5 pure-python fallback (native core disabled) =="
HOROVOD_TPU_NATIVE_CORE=0 python -m pytest \
  tests/test_basics.py tests/test_fusion.py -q

echo "== 4/5 controller disabled (single-process semantics) =="
HOROVOD_TPU_CONTROLLER=0 python -m pytest tests/test_basics.py -q

if [ "${1:-}" != "--quick" ]; then
  echo "== 5/5 full suite =="
  python -m pytest tests/ -q
fi
echo "CI matrix: all stages green"
