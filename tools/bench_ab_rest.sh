#!/bin/bash
# Round-5 remainder sweep: only the variants the 00:59 sweep did not get
# to before the re-wedge (BENCH_NOTE_r05.md).  Same discipline as
# bench_ab.sh: serial, no timeout wrappers, never kill a mid-claim client.
set -u
cd "$(dirname "$0")/.."
run() {
  echo "=== $* ==="
  env "$@" python bench.py 2>&1 | grep -E '^\{' || echo FAILED
}
run HOROVOD_BENCH_FUSED_XENT=1 HOROVOD_BENCH_LOSS_CHUNK=0 HOROVOD_BENCH_OPT=std HOROVOD_BENCH_REMAT_SKIP=0
run HOROVOD_BENCH_FUSED_XENT=1
run HOROVOD_BENCH_FUSED_XENT=1 HOROVOD_BENCH_REMAT_SKIP=1
run HOROVOD_BENCH_MODEL=bert
run HOROVOD_BENCH_MODEL=longctx
run HOROVOD_BENCH_MODEL=resnet
