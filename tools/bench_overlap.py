#!/usr/bin/env python
"""Overlapped-dispatch microbench: schedule position, bit-exact parity,
and step-time A/B on the CPU mesh.

Measures what ROADMAP item 3 changes — WHERE the in-jit gradient
collectives sit relative to the backward pass — on the virtual CPU mesh
(``pmap`` over ``--xla_force_host_platform_device_count`` devices).
Three readings per configuration (plain / sharded_update / int8 wire /
int8 × sharded):

  * **schedule position**: the traced collective schedule
    (``analysis/schedule.py``) of the armed step must carry every
    per-layer fusion bucket INSIDE the backward scan's sub-jaxpr (the
    overlap claim), with only the root buckets — and, sharded, the
    updates all-gather — at the step boundary; the un-armed step's
    schedule must have NO collective inside the scan.  Ring-model wire
    bytes (``analysis/wire.py``) of the two schedules must match:
    overlap moves the bytes earlier, it does not change them.
  * **bit-exact weight parity**: the A/B runs ONE compiled program with
    a runtime ``fire`` gate (``overlapped_backprop(tx, fire=...)``) —
    overlapped dispatch in the true branch, the identical layer-aware
    plan at the boundary in the false branch — so after ``--steps``
    adam steps the weights must be BIT-IDENTICAL, including under
    sharded_update and the int8 wire format where block partitioning
    decides the bits.  (Two separately compiled programs differ by XLA
    fusion ulps in the optimizer arithmetic — outside this rewrite's
    surface — which is exactly why the gate is a runtime input.)
  * **step time**: median over ``--repeats`` of the same program with
    the gate on vs off (CPU collectives are memcpys, so this is a
    regression canary, not a DCN claim; the real-chip A/B is
    ``examples/llama_benchmark.py --overlap``).

    python tools/bench_overlap.py               # 4-way mesh
    python tools/bench_overlap.py --smoke       # CI: fast, asserts only

Results print as JSON; see docs/performance.md "Overlapped dispatch".
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup_jax(n_devices: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _make_params(jax, n_layers: int, width: int):
    """A scanned-model param tree: stacked layers (the lax.scan stack
    the taps cover) plus non-scanned root leaves (tied embed + norm)."""
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(0)

    def r(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.1, jnp.float32)

    return {
        "embed": r(width // 2 + 3, width),
        "layers": {
            "w_in": r(n_layers, width, width),
            "w_out": r(n_layers, width, width),
            "b": jnp.zeros((n_layers, width), jnp.float32),
        },
        "final_norm": jnp.ones((width,), jnp.float32),
    }


def _model_loss(ov, params, x):
    import jax
    import jax.numpy as jnp
    params = ov.tap_root(params)
    h = x @ params["embed"]

    def body(h, lp):
        lp = ov.grad_tap(lp)
        h = jnp.tanh(h @ lp["w_in"] + lp["b"]) @ lp["w_out"]
        return h, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return ((h * params["final_norm"]) ** 2).sum()


def _trace_schedules(jax, tx, params, axis, n):
    """(overlapped, boundary) schedules of the same step — armed vs
    un-armed context."""
    import functools
    import horovod_tpu as hvd
    from horovod_tpu.analysis.schedule import trace_schedule
    from horovod_tpu.optim import overlap as ov
    spec = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    x = jax.ShapeDtypeStruct((2, params["embed"].shape[0]), params[
        "embed"].dtype)
    loss_fn = functools.partial(_model_loss, ov)

    def step_armed(p, xb):
        s = tx.init(p)
        with hvd.overlapped_backprop(tx):
            _l, g = jax.value_and_grad(loss_fn)(p, xb)
        u, _ = tx.update(g, s, p)
        return u

    def step_boundary(p, xb):
        s = tx.init(p)
        _l, g = jax.value_and_grad(loss_fn)(p, xb)
        u, _ = tx.update(g, s, p)
        return u

    env = [(axis, n)]
    return (trace_schedule(step_armed, (spec, x), axis_env=env,
                           entry="bench_overlap"),
            trace_schedule(step_boundary, (spec, x), axis_env=env,
                           entry="bench_overlap_boundary"))


def _check_schedules(sched_ov, sched_bd, sharded: bool, n_layers: int):
    """The schedule-position invariants — the overlap claim itself."""
    from horovod_tpu.analysis.wire import (ring_transmit_bytes,
                                           schedule_prim_counts,
                                           schedule_transmit_bytes)
    in_scan = [r for r in sched_ov.records if "scan" in r.path]
    at_top = [r for r in sched_ov.records if "scan" not in r.path]
    # every per-layer bucket dispatches inside the backward scan; the
    # scan body is traced once, so the records are per-bucket-per-layer
    # templates (reverse layer order is the scan's execution order)
    assert in_scan, "no collective inside the backward scan"
    assert all(r.bucket is not None for r in in_scan), in_scan
    # sharded: only the reduce-scatter side ever enters the scan (the
    # quantized staging exchanges tiles with all_to_all); non-sharded
    # allreduce may stage its own RS+AG (quantized) or one psum
    allowed = (("reduce_scatter", "all_to_all") if sharded
               else ("psum", "all_to_all", "all_gather"))
    assert all(r.prim in allowed for r in in_scan), \
        [r.prim for r in in_scan]
    if sharded:
        # the updates all-gather stays at the step boundary
        gathers = [r for r in sched_ov.records if r.prim == "all_gather"]
        assert gathers and all("scan" not in r.path for r in gathers), \
            [(r.prim, r.path) for r in gathers]
    # every scan-resident record precedes every boundary record of the
    # gradient reduction (the root taps + updates path run after the
    # backward scan completes)
    first_top = min((r.index for r in at_top), default=len(
        sched_ov.records))
    assert all(r.index < first_top for r in in_scan), \
        "scan records after boundary records"
    # the un-armed step keeps ALL collectives out of the scan (one
    # fused block after backprop — the exposed-latency baseline)
    assert all("scan" not in r.path for r in sched_bd.records), \
        [(r.prim, r.path) for r in sched_bd.records]
    # overlap moves bytes, it does not change them: the backward scan's
    # records are per-layer TEMPLATES executed n_layers times at
    # runtime, so runtime ring bytes = boundary-resident bytes +
    # n_layers x scan-resident bytes — and that must equal the un-armed
    # step's schedule exactly (same plan, different positions)
    sizes = dict(sched_ov.axis_env)
    scan_bytes = sum(ring_transmit_bytes(r, sizes) for r in in_scan)
    top_bytes = sum(ring_transmit_bytes(r, sizes) for r in at_top)
    ov_bytes = top_bytes + n_layers * scan_bytes
    bd_bytes = schedule_transmit_bytes(sched_bd)
    assert ov_bytes == bd_bytes, (ov_bytes, bd_bytes)
    counts_ov = schedule_prim_counts(sched_ov)
    counts_bd = schedule_prim_counts(sched_bd)
    return {
        "collectives_in_backward_scan": len(in_scan),
        "collectives_at_boundary": len(at_top),
        "overlapped_prims": counts_ov,
        "boundary_prims": counts_bd,
        "overlapped_wire_bytes": ov_bytes,
        "boundary_wire_bytes": bd_bytes,
    }


def _run_ab(jax, tx, params, axis, n, steps, repeats):
    """One compiled program, fire on/off: bit-exact weights + timing."""
    import functools
    import numpy as np
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.optim import overlap as ov
    loss_fn = functools.partial(_model_loss, ov)
    rng = np.random.default_rng(1)
    X = jax.numpy.asarray(
        rng.standard_normal((n, 4, params["embed"].shape[0])),
        jax.numpy.float32)

    def step(p, s, xb, fire):
        with hvd.overlapped_backprop(tx, fire=fire):
            _l, g = jax.value_and_grad(loss_fn)(p, xb)
        u, ns = tx.update(g, s, p)
        return optax.apply_updates(p, u), ns

    f = jax.pmap(step, axis_name=axis, in_axes=(None, 0, 0, None))
    state0 = jax.pmap(lambda p, _: tx.init(p), axis_name=axis,
                      in_axes=(None, 0))(params, np.zeros(n))

    def trajectory(fire):
        p, s = params, state0
        for _ in range(steps):
            pk, s = f(p, s, X, jax.numpy.asarray(fire))
            for leaf in jax.tree_util.tree_leaves(pk):
                a = np.asarray(leaf)
                assert (a[0] == a[-1]).all(), \
                    "replicas diverged under overlapped dispatch"
            p = jax.tree_util.tree_map(lambda a: a[0], pk)
        return p

    p_on = trajectory(True)
    p_off = trajectory(False)
    for a, b in zip(jax.tree_util.tree_leaves(p_on),
                    jax.tree_util.tree_leaves(p_off)):
        a, b = np.asarray(a), np.asarray(b)
        assert (a == b).all(), \
            f"weights not bit-identical: max delta {np.abs(a - b).max()}"

    def timed(fire):
        fire = jax.numpy.asarray(fire)
        times = []
        for _ in range(repeats):
            p, s = params, state0
            t0 = time.perf_counter()
            for _ in range(steps):
                pk, s = f(p, s, X, fire)
                p = jax.tree_util.tree_map(lambda a: a[0], pk)
            jax.block_until_ready(pk)
            times.append((time.perf_counter() - t0) / steps)
        return round(statistics.median(times) * 1e3, 3)

    return {"steps": steps, "weights_bit_identical": True,
            "step_ms_overlapped": timed(True),
            "step_ms_boundary": timed(False)}


def bench_config(jax, tag, params, axis, n, threshold, steps, repeats,
                 **tx_kwargs):
    import optax
    from horovod_tpu.optim.distributed import DistributedOptimizer
    tx = DistributedOptimizer(optax.adam(1e-2), axis_name=axis,
                              threshold_bytes=threshold, overlap=True,
                              **tx_kwargs)
    sched_ov, sched_bd = _trace_schedules(jax, tx, params, axis, n)
    n_layers = int(params["layers"]["b"].shape[0])
    out = _check_schedules(sched_ov, sched_bd,
                           bool(tx_kwargs.get("sharded_update")),
                           n_layers)
    out.update(_run_ab(jax, tx, params, axis, n, steps, repeats))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=4,
                    help="CPU mesh size (default 4)")
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--width", type=int, default=128)
    ap.add_argument("--threshold", type=int, default=32 << 10,
                    help="fusion threshold bytes (default 32 KiB: "
                         "multiple buckets per layer)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--smoke", action="store_true",
                    help="CI: tiny model, assert invariants, fast")
    args = ap.parse_args()

    if args.smoke:
        args.layers, args.width = 3, 32
        args.threshold = 2 << 10
        args.steps, args.repeats = 4, 1

    jax = _setup_jax(args.devices)
    sys.path.insert(0, REPO)

    axis, n = "ow", args.devices
    params = _make_params(jax, args.layers, args.width)
    total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    result = {"devices": n, "params": total,
              "threshold_bytes": args.threshold}
    configs = [
        ("plain", {}),
        ("sharded", {"sharded_update": True}),
        ("int8", {"wire_format": "int8", "wire_block_size": 16}),
        ("int8_sharded", {"sharded_update": True, "wire_format": "int8",
                          "wire_block_size": 16}),
    ]
    for tag, kw in configs:
        result[tag] = bench_config(jax, tag, params, axis, n,
                                   args.threshold, args.steps,
                                   args.repeats, **kw)
    print(json.dumps(result, indent=2, sort_keys=True))
    if args.smoke:
        print("bench_overlap smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
