#!/bin/bash
# Third-wedge watcher (wedge ~10:52-11:02 UTC during the bert b2048
# OOM + four timeout-killed claim clients that had silently routed to
# the TPU because JAX_PLATFORMS=cpu alone does NOT override the axon
# sitecustomize — use HOROVOD_TPU_FORCE_PLATFORM=cpu for CPU-only
# bench runs).  When the tunnel frees: one defaults-confirm run (the
# driver-shape number for the flipped winners), the unmeasured longctx
# b3, and the remat-policy=dots failure diagnostic (stderr captured).
# Same discipline as bench_watch.sh: probes are never killed, at most
# MAX_PENDING of this watcher's probes live at once, sweeps run
# serially after a probe answers.
set -u
cd "$(dirname "$0")/.."
PROBE_DIR=${PROBE_DIR:-/tmp/bench_probes_r05c}
MAX_PENDING=${MAX_PENDING:-2}
SLEEP=${SLEEP:-300}
mkdir -p "$PROBE_DIR"

run() {
  echo "=== $* ==="
  local out
  out=$(env "$@" python bench.py 2>&1 | grep -E '^\{' || echo FAILED)
  echo "$out"
  case "$out" in *'"error"'*) return 1;; esac
  return 0
}

sweep() {
  echo "=== confirm sweep via watcher ($(date -u +%T)) ==="
  run || return                       # flipped defaults, driver shape
  run HOROVOD_BENCH_MODEL=longctx HOROVOD_BENCH_BATCH=3 || return
  # the dots diagnostic is EXPECTED to fail — keep its output (incl.
  # any probe-guard error JSON) out of the completion check's log so a
  # mid-diagnostic re-wedge can't force an eternal full-sweep retry
  echo "=== dots diagnostic -> bench_dots_diag.log ==="
  env HOROVOD_BENCH_REMAT_POLICY=dots python bench.py \
    > bench_dots_diag.log 2>&1 || true
}

launch_probe() {
  local tag="$PROBE_DIR/probe_$(date +%s)"
  setsid nohup python -c "import jax; jax.devices(); print('ok', flush=True)" \
    > "$tag.out" 2> "$tag.err" < /dev/null &
  echo "$!" > "$tag.pid"
  echo "$(date -u +%T) launched probe $tag (pid $!)" >> "$PROBE_DIR/watch.log"
}

chip_free() {
  grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null | head -1
}

pending_probes() {
  local n=0
  for pidf in "$PROBE_DIR"/probe_*.pid; do
    [ -f "$pidf" ] || continue
    local pid out
    pid=$(cat "$pidf"); out="${pidf%.pid}.out"
    if kill -0 "$pid" 2>/dev/null && ! grep -q "^ok" "$out" 2>/dev/null; then
      n=$((n + 1))
    fi
  done
  echo "$n"
}

while true; do
  if [ -n "$(chip_free)" ]; then
    SWEEP_OUT=$(mktemp)
    sweep > "$SWEEP_OUT" 2>&1
    cat "$SWEEP_OUT" >> bench_ab_r05_rest.log
    if ! grep '^{' "$SWEEP_OUT" | grep -q '"error"' \
        && grep '^{' "$SWEEP_OUT" | grep -q '"value"'; then
      rm -f "$SWEEP_OUT"
      echo "$(date -u +%T) confirm sweep complete — watcher done" \
        >> "$PROBE_DIR/watch.log"
      exit 0
    fi
    rm -f "$SWEEP_OUT"
    for okf in $(grep -l "^ok" "$PROBE_DIR"/probe_*.out 2>/dev/null); do
      base="${okf%.out}"
      rm -f "$base.out" "$base.pid" "$base.err"
    done
  fi
  if [ "$(pending_probes)" -lt "$MAX_PENDING" ]; then
    launch_probe
  fi
  sleep "$SLEEP"
done
