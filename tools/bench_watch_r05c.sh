#!/bin/bash
# Fourth-wedge watcher (wedge ~10:52-11:02 UTC: the bert b2048 OOM
# held the chip through an 18-min compile while four timeout-killed
# claim clients — launched with JAX_PLATFORMS=cpu, which the axon
# sitecustomize overrides; HOROVOD_TPU_FORCE_PLATFORM=cpu is the
# correct knob — queued and died mid-claim).  When the tunnel frees:
# one defaults-confirm run (the driver-shape number for the flipped
# winners), the unmeasured longctx b3 (retried once so a transient
# relay drop is not recorded as a variant property), and the
# remat-policy=dots failure diagnostic with stderr captured to
# bench_dots_diag.log (expected to fail -> kept out of the completion
# check's log).
set -u
cd "$(dirname "$0")/.."
PROBE_DIR=${PROBE_DIR:-/tmp/bench_probes_r05c}
SWEEP_LOG=bench_ab_r05_rest.log
. tools/bench_watch_lib.sh

b3() {
  env HOROVOD_BENCH_MODEL=longctx HOROVOD_BENCH_BATCH=3 \
    python bench.py 2>&1 | grep -E '^\{' || echo FAILED
}

sweep() {
  echo "=== confirm sweep via watcher ($(date -u +%T)) ==="
  run || return                       # flipped defaults, driver shape
  echo "=== longctx b3 ==="
  local o
  o=$(b3); echo "$o"
  case "$o" in *'"error"'*) return 1;; esac
  if [ "$o" = FAILED ]; then
    echo "=== longctx b3 (retry: transient vs variant property) ==="
    o=$(b3); echo "$o"
    case "$o" in *'"error"'*) return 1;; esac
  fi
  echo "=== dots diagnostic -> bench_dots_diag.log ==="
  env HOROVOD_BENCH_REMAT_POLICY=dots python bench.py \
    > bench_dots_diag.log 2>&1 || true
}

watch_loop
