#!/usr/bin/env python
"""Control-plane microbench: steady-state negotiation latency, CPU-only.

Measures what ISSUE 5 changes — the coordination tail between "every
process has announced its tensors" and "every process knows" — with no
TPU, no XLA dispatch, and no jax.distributed: N real OS processes run
real ``Controller.negotiate`` rounds against the launcher-hosted RPC KV
(``runner/kv.py``) on loopback, with a seeded per-(rank, round) arrival
jitter standing in for compute skew.

Per round, every member publishes its wall-clock call time as the
round's ``aux`` payload; the **wake lag** is ``t_return − max(aux ts)``
— how long after the last member arrived this member learned the
round's outcome.  Long-poll watch bounds that by ~one RTT; the polled
transport bounds it by the exponential-backoff poll tick (capped at
250 ms), which is the gap this bench exists to show:

    python tools/bench_control.py              # watch vs poll, 4 procs
    python tools/bench_control.py --smoke      # CI: fast correctness run

Results (rounds/s, wake-lag p50/p99, controller KV-op stats proving
zero polled dir-gets under watch) print as JSON; see
docs/performance.md "Control plane".
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TOKEN = json.dumps(
    {"s": [["bench.grad", "allreduce", "sum", "float32", [1024], 0,
            False, -1, 1.0, 1.0]], "r": -1, "sp": None},
    separators=(",", ":"), sort_keys=True)


def _percentile(sorted_vals, q):
    # lazy: sys.path gains the repo inside run_worker/_spawn_and_collect
    from horovod_tpu.metrics.aggregate import percentile
    return percentile(sorted_vals, q)


# -- worker -------------------------------------------------------------------

def run_worker(args) -> int:
    sys.path.insert(0, REPO)
    from horovod_tpu.ops import controller as ctl_mod

    rank, nprocs = args.rank, args.np
    ctl_mod.jax.process_index = lambda: rank
    ctl_mod.jax.process_count = lambda: nprocs
    client = ctl_mod._client()           # the RPC KV via HOROVOD_KV_ADDR
    ctl = ctl_mod.Controller(namespace=args.namespace)
    procs = tuple(range(nprocs))

    # rendezvous through the store itself: everyone is up before round 0,
    # so spawn skew doesn't pollute the first samples
    client.key_value_set(f"bench/{args.namespace}/ready/{rank}", "1")
    deadline = time.monotonic() + 60
    while len(client.key_value_dir_get(
            f"bench/{args.namespace}/ready/")) < nprocs:
        if time.monotonic() > deadline:
            raise TimeoutError("bench rendezvous timed out")
        time.sleep(0.005)

    rng = random.Random(args.seed * 10007 + rank)
    samples = []
    t_start = time.monotonic()
    for r in range(args.rounds):
        if args.jitter_ms > 0:
            time.sleep(rng.uniform(0.0, args.jitter_ms / 1000.0))
        t_call = time.time()
        res = ctl.negotiate([_TOKEN], procs, aux={"ts": t_call})
        t_ret = time.time()
        assert res.counts[_TOKEN] == 1, (rank, r, dict(res.counts))
        last_arrival = max(res.aux[p]["ts"] for p in procs)
        samples.append({"lag": max(0.0, t_ret - last_arrival),
                        "waiter": t_call < last_arrival})
    wall = time.monotonic() - t_start
    with open(args.out, "w") as f:
        json.dump({"rank": rank, "wall_s": wall, "samples": samples,
                   "stats": ctl.stats()}, f)
    return 0


# -- driver -------------------------------------------------------------------

def _spawn_and_collect(transport: str, args) -> dict:
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from horovod_tpu.runner.kv import KV_ADDR_ENV, KV_WATCH_ENV, KvServer
    from horovod_tpu.runner.spawn import ensure_job_secret

    ensure_job_secret()
    server = KvServer()
    ns = f"{transport}{args.seed}"
    try:
        with tempfile.TemporaryDirectory(prefix="bench_ctl_") as tmp:
            workers = []
            for rank in range(args.np):
                env = dict(os.environ)
                env.update({
                    KV_ADDR_ENV: f"127.0.0.1:{server.port}",
                    KV_WATCH_ENV: "1" if transport == "watch" else "0",
                    "JAX_PLATFORMS": "cpu",
                    "HOROVOD_TPU_FORCE_PLATFORM": "cpu",
                    "PYTHONPATH": REPO + os.pathsep
                    + env.get("PYTHONPATH", ""),
                })
                out = os.path.join(tmp, f"r{rank}.json")
                cmd = [sys.executable, os.path.abspath(__file__),
                       "--worker", "--rank", str(rank), "--np",
                       str(args.np), "--rounds", str(args.rounds),
                       "--jitter-ms", str(args.jitter_ms), "--seed",
                       str(args.seed), "--namespace", ns, "--out", out]
                workers.append((subprocess.Popen(cmd, env=env), out))
            results = []
            for proc, out in workers:
                rc = proc.wait(timeout=300)
                if rc != 0:
                    raise RuntimeError(
                        f"bench worker exited {rc} (transport="
                        f"{transport})")
                with open(out) as f:
                    results.append(json.load(f))
    finally:
        server.close()

    # wake lag per round = the slowest member's lag that round (when the
    # whole CYCLE can proceed); notify lag = the first WAITER's lag (the
    # transport's pure wake-up latency — a waiter parked on the watch
    # wakes ~one RTT after the last arrival, a polling waiter wakes at
    # its next backoff tick).  The last arriver itself is excluded from
    # notify lag: it never waits, on either transport.
    per_round = [max(w["samples"][r]["lag"] for w in results)
                 for r in range(args.rounds)]
    notify = [min((w["samples"][r]["lag"] for w in results
                   if w["samples"][r]["waiter"]), default=0.0)
              for r in range(args.rounds)]
    lags = sorted(per_round)
    notify = sorted(notify)
    wall = max(w["wall_s"] for w in results)
    stats = {k: sum(w["stats"][k] for w in results)
             for k in ("rounds", "kv_sets", "kv_dir_gets",
                       "kv_dir_watches", "kv_left_gets",
                       "kv_blocking_gets", "watch_fallbacks")}
    return {
        "transport": transport,
        "np": args.np,
        "rounds": args.rounds,
        "jitter_ms": args.jitter_ms,
        "rounds_per_s": round(args.rounds / wall, 1),
        "wake_lag_p50_ms": round(_percentile(lags, 0.50) * 1e3, 3),
        "wake_lag_p99_ms": round(_percentile(lags, 0.99) * 1e3, 3),
        "notify_lag_p50_ms": round(_percentile(notify, 0.50) * 1e3, 3),
        "notify_lag_p99_ms": round(_percentile(notify, 0.99) * 1e3, 3),
        "kv_ops": stats,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--np", type=int, default=4)
    p.add_argument("--rounds", type=int, default=150)
    p.add_argument("--jitter-ms", type=float, default=150.0,
                   help="per-(rank, round) seeded uniform arrival skew "
                        "(stands in for per-step compute/straggler skew; "
                        "the polled transport's backoff overshoot grows "
                        "with it, the watch transport's RTT does not)")
    p.add_argument("--seed", type=int, default=5)
    p.add_argument("--repeat", type=int, default=1,
                   help="interleaved repetitions per transport; the "
                        "MEDIAN-p50 run is reported (damps scheduler "
                        "noise on small shared machines)")
    p.add_argument("--transport", choices=("watch", "poll", "both"),
                   default="both")
    p.add_argument("--smoke", action="store_true",
                   help="fast CI run: small matrix + invariant asserts")
    # internal: worker mode
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--rank", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--namespace", default="b", help=argparse.SUPPRESS)
    p.add_argument("--out", default="", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.worker:
        return run_worker(args)

    if args.smoke:
        args.np, args.rounds, args.jitter_ms = 2, 25, 2.0

    transports = (["watch", "poll"] if args.transport == "both"
                  else [args.transport])
    runs = {t: [] for t in transports}
    base_seed = args.seed
    for rep in range(max(1, args.repeat)):   # interleaved: noise bursts
        for t in transports:                 # hit both transports alike
            args.seed = base_seed + rep
            runs[t].append(_spawn_and_collect(t, args))
    args.seed = base_seed
    report = {}
    for t in transports:
        ordered = sorted(runs[t], key=lambda r: r["wake_lag_p50_ms"])
        report[t] = ordered[len(ordered) // 2]
        report[t]["runs_p50_ms"] = [r["wake_lag_p50_ms"] for r in runs[t]]
    if "watch" in report:
        w = report["watch"]["kv_ops"]
        # the event-driven invariants the docs and CI lean on
        assert w["kv_dir_gets"] == 0, w       # ZERO polled dir-gets
        assert w["kv_blocking_gets"] == 0, w
        assert w["watch_fallbacks"] == 0, w
        assert w["kv_dir_watches"] >= args.rounds, w
        assert w["kv_sets"] == args.np * args.rounds, w
    if len(report) == 2:
        report["speedup"] = {
            k: round(report["poll"][f"{k}_ms"]
                     / max(report["watch"][f"{k}_ms"], 1e-6), 1)
            for k in ("wake_lag_p50", "wake_lag_p99",
                      "notify_lag_p50", "notify_lag_p99")}
    print(json.dumps(report, indent=2))
    if args.smoke:
        print("bench_control smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
