#!/usr/bin/env python
"""Mesh-axis-aware gradient-plane microbench: the 2-D (data x model)
composition of ZeRO tiles, quantized wire, and overlap taps (ISSUE 14).

Measures what the spec-aware refactor changes on a virtual 2-D CPU mesh
(nested ``pmap`` over ``--xla_force_host_platform_device_count``
devices: outer axis ``data``, inner axis ``model``).  Params are
model-sharded (`PartitionSpec("model")` on the stacked layer weights,
replicated norms/embed); gradients w.r.t. the LOCAL shards arrive
pre-reduced over the model axis (the in-program gather's transpose),
and ``DistributedGradientTransform(param_specs=...)`` does the rest.
Four gates, all asserted every run:

  * **per-chip bytes at the model-shard fraction (exact)**:
    ``tree_nbytes`` of one chip's params == the leaf-wise sharded
    fraction, and the ZeRO config's inner optimizer state == the exact
    tile bytes of ``optim.distributed.sharded_tile_layout`` —
    ``total/(model x data)`` + padding, not an approximation.
  * **DCN (data-hop) wire bytes**: priced from traced schedules under
    ``analysis/wire.py`` STRICT accounting — the spec-aware schedule's
    data hop must carry the model-shard fraction of the replicated
    plan's bytes, and int8 on top must shrink it >= 3.5x further.
  * **one-program A/B bit-identical weights**: for each of
    plain / zero / int8 / int8+zero, ONE compiled program with a
    runtime ``fire`` gate (``overlapped_backprop(tx, fire=...)``) runs
    overlapped dispatch in the true branch and the identical boundary
    plan in the false branch — weights must be BIT-identical, on the
    2-D mesh, spec-aware plans included.
  * **spec-aware == replicated parity**: the same trajectory on a flat
    1-D mesh of data*model devices with full replicated params lands
    on the same weights (allclose: the reduction tree differs, so ulps
    may).

    python tools/bench_fsdp.py               # 2x2 mesh
    python tools/bench_fsdp.py --smoke       # CI: fast, asserts only

Results print as JSON; see docs/performance.md "Mesh-axis-aware
sharding".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup_jax(n_devices: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _make_params(jax, n_layers: int, width: int):
    """Scanned-model tree: stacked layer weights (model-sharded on the
    per-layer row dim) + replicated root leaves; odd embed rows so
    bucket padding is exercised."""
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(0)

    def r(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.1, jnp.float32)

    return {
        "embed": r(width // 2 + 3, width),
        "layers": {
            "w": r(n_layers, width, width),
            "b": jnp.zeros((n_layers, width), jnp.float32),
        },
        "final_norm": jnp.ones((width,), jnp.float32),
    }


def _specs(jax):
    from jax.sharding import PartitionSpec as P
    return {
        "embed": P(),
        # stacked [L, W, W]: per-layer rows shard over model
        "layers": {"w": P(None, "model"), "b": P()},
        "final_norm": P(),
    }


def _carve(jax, params, M):
    """The (model-rank-local) param shards, inside the mapped program."""
    from jax import lax
    idx = lax.axis_index("model")
    W = params["layers"]["w"].shape[1]
    out = dict(params)
    out["layers"] = dict(params["layers"])
    out["layers"]["w"] = lax.dynamic_slice_in_dim(
        params["layers"]["w"], idx * (W // M), W // M, axis=1)
    return out


def _model_loss(jax, ov, params_local, x):
    """Toy scanned model computing with gathered-full layer weights:
    the gather's transpose is what delivers shard-shaped, model-reduced
    gradients to the taps/transform — the FSDP gradient contract."""
    import jax.numpy as jnp
    from jax import lax
    params_local = ov.tap_root(params_local)
    h = x @ params_local["embed"]

    def body(h, lp):
        lp = ov.grad_tap(lp)
        w_full = lax.all_gather(lp["w"], "model", axis=0, tiled=True)
        h = jnp.tanh(h @ w_full + lp["b"])
        return h, None

    h, _ = lax.scan(body, h, params_local["layers"])
    return ((h * params_local["final_norm"]) ** 2).sum()


def _tx(sharded, wire, specs, threshold, axis="data", model_axes=("model",),
        overlap=True, block=16):
    import optax
    from horovod_tpu.optim.distributed import DistributedOptimizer
    return DistributedOptimizer(
        optax.adam(1e-2), axis_name=axis, threshold_bytes=threshold,
        overlap=overlap, sharded_update=sharded,
        wire_format=wire or "none", wire_block_size=block if wire else None,
        param_specs=specs, model_axes=model_axes if specs else None)


def _run_ab(jax, tx, params, D, M, steps):
    """One compiled program, fire on/off: bit-exact weights on the 2-D
    mesh; returns the fire-on weights (replica 0,0)."""
    import functools
    import numpy as np
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.optim import overlap as ov
    loss_fn = functools.partial(_model_loss, jax, ov)
    rng = np.random.default_rng(1)
    X = jax.numpy.asarray(
        rng.standard_normal((D, M, 2, params["embed"].shape[0])),
        jax.numpy.float32)

    def prog(x, fire):
        p = _carve(jax, params, M)
        s = tx.init(p)
        for _ in range(steps):
            with hvd.overlapped_backprop(tx, fire=fire):
                _l, g = jax.value_and_grad(loss_fn)(p, x)
            u, s = tx.update(g, s, p)
            p = optax.apply_updates(p, u)
        return p, s

    f = jax.pmap(jax.pmap(prog, axis_name="model", in_axes=(0, None)),
                 axis_name="data", in_axes=(0, None))
    p_on, s_on = f(X, jax.numpy.asarray(True))
    p_off, _ = f(X, jax.numpy.asarray(False))
    for a, b in zip(jax.tree_util.tree_leaves(p_on),
                    jax.tree_util.tree_leaves(p_off)):
        a, b = np.asarray(a), np.asarray(b)
        assert (a == b).all(), \
            f"weights not bit-identical: max delta {np.abs(a - b).max()}"
    for leaf in jax.tree_util.tree_leaves(p_on):
        leaf = np.asarray(leaf)
        # data-replicas must agree (model shards legitimately differ)
        assert (leaf[0] == leaf[-1]).all(), "data replicas diverged"
    return p_on, s_on


def _local_shapes(jax, params, M):
    """ShapeDtypeStructs of one model-rank's param shards (M=1: the
    full replicated shapes)."""
    import jax.numpy as jnp
    sds = jax.ShapeDtypeStruct
    L, W = params["layers"]["b"].shape
    return {
        "embed": sds(params["embed"].shape, jnp.float32),
        "layers": {"w": sds((L, W // M, W), jnp.float32),
                   "b": sds((L, W), jnp.float32)},
        "final_norm": sds((W,), jnp.float32),
    }


def _trace_wire(jax, tx, params, D, M, sharded_operands: bool):
    """Per-worker DATA-hop (DCN analog) ring bytes of the traced step,
    strict accounting.  ``sharded_operands=False`` traces the
    replicated baseline: the same step over FULL-width buffers — the
    bytes the data hop paid before the gradient plane was mesh-aware."""
    from horovod_tpu.analysis.schedule import trace_schedule
    from horovod_tpu.analysis.wire import schedule_transmit_bytes
    local = _local_shapes(jax, params, M if sharded_operands else 1)

    def step(g, p):
        u, _ = tx.update(g, tx.init(p), p)
        return u

    sched = trace_schedule(step, (local, local),
                           axis_env=[("data", D), ("model", M)],
                           entry="bench_fsdp")
    return schedule_transmit_bytes(sched, axis_filter="data", strict=True)


def _replicated_reference(jax, params, n, threshold, steps):
    """The same trajectory on a flat 1-D replicated mesh of n devices."""
    import functools
    import numpy as np
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.optim import overlap as ov
    tx = _tx(False, None, None, threshold, axis="flat", model_axes=None)
    loss_fn = functools.partial(_model_loss_flat, jax, ov)
    rng = np.random.default_rng(1)
    X = jax.numpy.asarray(
        rng.standard_normal((n, 2, params["embed"].shape[0])),
        jax.numpy.float32)

    def prog(x):
        p = params
        s = tx.init(p)
        for _ in range(steps):
            with hvd.overlapped_backprop(tx, fire=jax.numpy.asarray(
                    False)):
                _l, g = jax.value_and_grad(loss_fn)(p, x)
            u, s = tx.update(g, s, p)
            p = optax.apply_updates(p, u)
        return p

    f = jax.pmap(prog, axis_name="flat", in_axes=0)
    pk = f(X)
    return jax.tree_util.tree_map(lambda a: a[0], pk)


def _model_loss_flat(jax, ov, params, x):
    """The replicated-reference form of the toy model (full weights,
    no gathers) — same math, flat mesh."""
    import jax.numpy as jnp
    from jax import lax
    params = ov.tap_root(params)
    h = x @ params["embed"]

    def body(h, lp):
        lp = ov.grad_tap(lp)
        h = jnp.tanh(h @ lp["w"] + lp["b"])
        return h, None

    h, _ = lax.scan(body, h, params["layers"])
    return ((h * params["final_norm"]) ** 2).sum()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--data", type=int, default=2,
                    help="data-axis size (default 2)")
    ap.add_argument("--model", type=int, default=2,
                    help="model-axis size (default 2)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--threshold", type=int, default=8 << 10)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--smoke", action="store_true",
                    help="CI: tiny model, assert invariants, fast")
    args = ap.parse_args()
    if args.smoke:
        args.layers, args.width = 3, 32
        args.threshold = 2 << 10
        args.steps = 3

    D, M = args.data, args.model
    jax = _setup_jax(D * M)
    sys.path.insert(0, REPO)
    import numpy as np
    from horovod_tpu.ops.fusion import dtype_nbytes
    from horovod_tpu.optim.distributed import (make_spec_plan,
                                               sharded_tile_layout)
    from horovod_tpu.optim.precision import tree_nbytes

    params = _make_params(jax, args.layers, args.width)
    specs = _specs(jax)
    total_bytes = tree_nbytes(params)
    result = {"mesh": {"data": D, "model": M},
              "params_bytes_full": total_bytes,
              "threshold_bytes": args.threshold}

    # --- gate 1: per-chip bytes at the model-shard fraction (exact) ---
    sharded_leaf_bytes = (
        tree_nbytes(params["layers"]["w"]) // M
        + tree_nbytes(params["layers"]["b"])
        + tree_nbytes(params["embed"]) + tree_nbytes(params["final_norm"]))
    p_zero, s_zero = _run_ab(
        jax, _tx(True, None, specs, args.threshold), params, D, M,
        args.steps)
    chip_params = jax.tree_util.tree_map(
        lambda a: a[0, 0], p_zero)
    assert tree_nbytes(chip_params) == sharded_leaf_bytes, (
        tree_nbytes(chip_params), sharded_leaf_bytes)
    # exact ZeRO tile accounting: inner state == 2 adam moments on the
    # data-axis tiles of the LOCAL (model-shard) buckets + the int32
    # step count — total/(model*data) + planner padding, priced by the
    # same layout the transform tiles with
    local_shapes = _local_shapes(jax, params, M)
    plan = make_spec_plan(specs, "data", ("model",))
    layout = sharded_tile_layout(local_shapes, D,
                                 threshold_bytes=args.threshold,
                                 spec_plan=plan)
    leaves = sorted(jax.tree_util.tree_leaves_with_path(local_shapes),
                    key=lambda kv: jax.tree_util.keystr(kv[0]))
    tile_bytes = sum(
        bl.shard_numel * dtype_nbytes(str(leaves[bl.indices[0]][1].dtype))
        for bl in layout.buckets)
    chip_state = jax.tree_util.tree_map(lambda a: a[0, 0], s_zero.inner)
    expect_state = 2 * tile_bytes + 4          # adam mu+nu tiles + count
    assert tree_nbytes(chip_state) == expect_state, (
        tree_nbytes(chip_state), expect_state)
    result["per_chip"] = {
        "params_bytes": int(tree_nbytes(chip_params)),
        "inner_state_bytes": int(tree_nbytes(chip_state)),
        "state_fraction_of_full": round(
            tree_nbytes(chip_state) / (2 * total_bytes), 4),
    }

    # --- gate 2: DCN (data-hop) wire bytes, strict ring accounting ---
    # shard-fraction claim: the sharded spec-aware schedule's data hop
    # vs the same plan over full-width (replicated) operands
    wire_zero = _trace_wire(jax, _tx(True, None, specs, args.threshold,
                                     overlap=False),
                            params, D, M, True)
    wire_repl = _trace_wire(jax, _tx(True, None, None, args.threshold,
                                     overlap=False, model_axes=None),
                            params, D, M, False)
    # int8 claim on the fully-quantized staging (plain spec path: both
    # the scatter and the gather ride int8 lanes + block scales; the
    # sharded config's updates gather deliberately stays fp32, see
    # fused_reduce_scatter_tree).  Block 64: 4B/elem -> 1B + 4/64
    # scale overhead, and the n*block alignment padding stays small
    # against this bench's bucket sizes
    wire_fp32 = _trace_wire(jax, _tx(False, None, specs, args.threshold,
                                     overlap=False),
                            params, D, M, True)
    wire_int8 = _trace_wire(jax, _tx(False, "int8", specs,
                                     args.threshold, overlap=False,
                                     block=64),
                            params, D, M, True)
    result["data_hop_wire_bytes"] = {
        "replicated_fp32": wire_repl, "zero_spec_fp32": wire_zero,
        "spec_fp32": wire_fp32, "spec_int8": wire_int8,
        "int8_ratio": round(wire_fp32 / max(1, wire_int8), 2),
    }
    # the spec-aware schedule's data hop carries ~the model-shard
    # fraction of the replicated plan's bytes (replicated leaves keep
    # full width, so the bound is fractional, not exactly 1/M)
    assert wire_zero < wire_repl, result["data_hop_wire_bytes"]
    # the CI gate (docs/performance.md): >= 3.5x on the documented 2x2
    # mesh.  Other shapes keep a looser floor — the n*block alignment
    # padding grows with the data degree against this bench's small
    # buckets, which is a bench-geometry artifact, not a wire property
    assert wire_fp32 / wire_int8 >= (3.5 if (D, M) == (2, 2) else 3.0), \
        result["data_hop_wire_bytes"]

    # --- gate 3: one-program fire-gated A/B, all four configs ---
    ab = {}
    weights = {"zero": p_zero}
    for tag, kw in (("plain", dict(sharded=False, wire=None)),
                    ("int8", dict(sharded=False, wire="int8")),
                    ("int8_zero", dict(sharded=True, wire="int8"))):
        p_on, _ = _run_ab(jax, _tx(kw["sharded"], kw["wire"], specs,
                                   args.threshold), params, D, M,
                          args.steps)
        weights[tag] = p_on
        ab[tag] = "bit-identical"
    ab["zero"] = "bit-identical"
    result["fire_ab"] = ab

    # --- gate 4: spec-aware == replicated parity (allclose) ---
    p_ref = _replicated_reference(jax, params, D * M, args.threshold,
                                  args.steps)
    p_spec = jax.tree_util.tree_map(lambda a: a[0, 0],
                                    weights["plain"])
    ref_carved = {
        "embed": p_ref["embed"],
        "layers": {"w": p_ref["layers"]["w"][:, : args.width // M, :],
                   "b": p_ref["layers"]["b"]},
        "final_norm": p_ref["final_norm"],
    }
    for a, b in zip(jax.tree_util.tree_leaves(p_spec),
                    jax.tree_util.tree_leaves(ref_carved)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    result["replicated_parity"] = "allclose"

    print(json.dumps(result, indent=2, sort_keys=True))
    if args.smoke:
        print("bench_fsdp smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
