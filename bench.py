"""Benchmark: flagship Llama train-step throughput on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured quantity is training tokens/sec/chip for a ~1B-param
Llama-family model (bf16 compute, fp32 master params, adamw with bf16
momentum, fused DP train step — BASELINE config 4 scaled to a single
chip).  ``vs_baseline`` reports measured MFU divided by 0.40 — i.e.
≥1.0 means the compiled step meets or beats the ~40% model-FLOPs
utilization a well-tuned reference (NCCL/GPU) training stack achieves
on its own headline benchmarks.

The hot attention op runs the framework's own Pallas flash-attention
kernel (horovod_tpu/ops/flash_attention.py); the trunk weights are
bulk-cast to bf16 once per step (models/llama.py _layer_stack).
"""

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Peak bf16 TFLOP/s per chip by generation (for MFU).
PEAK_TFLOPS = {"v5e": 197.0, "v5p": 459.0, "v4": 275.0, "cpu": 0.5}


def detect_peak() -> float:
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    plat = jax.devices()[0].platform
    if plat == "cpu":
        return PEAK_TFLOPS["cpu"]
    return PEAK_TFLOPS.get(gen, PEAK_TFLOPS["v5e"])


def _env_batch(default: int) -> int:
    """HOROVOD_BENCH_BATCH: per-chip batch override for the secondary
    bench modes (the reference's synthetic benchmarks expose
    --batch-size the same way; TPU conv/attention utilization is
    batch-hungry, so the A/B sweep tunes this per mode)."""
    import os
    return int(os.environ.get("HOROVOD_BENCH_BATCH", default))


def _env_scan(default: int = 1) -> int:
    """HOROVOD_BENCH_SCAN: drive K train steps per device dispatch via
    ``lax.scan`` (1 = eager loop).  Steps whose compute time is tens of
    ms are otherwise dominated by the axon tunnel's per-dispatch RPC
    latency, which measures the relay, not the chip; multi-step scan is
    how real long-running TPU loops amortize host dispatch anyway.
    Per-mode defaults = the measured round-5 winners."""
    import os
    return max(1, int(os.environ.get("HOROVOD_BENCH_SCAN", str(default))))


def _scan_wrap(step_fn, n_carry: int, loss_idx: int, k: int):
    """jit(scan) of ``k`` chained ``step_fn`` calls.

    ``step_fn``'s first ``n_carry`` outputs feed its first ``n_carry``
    inputs on the next step; remaining inputs repeat (synthetic data).
    Returns a callable with step_fn's signature yielding
    (carry..., last_loss)."""
    from jax import lax

    def multi(carry, *inputs):
        def body(c, _):
            out = step_fn(*c, *inputs)
            return tuple(out[:n_carry]), out[loss_idx]
        c2, losses = lax.scan(body, carry, None, length=k)
        return c2, losses[-1]

    jitted = jax.jit(multi, donate_argnums=(0,))

    def run(*args):
        carry, rest = tuple(args[:n_carry]), args[n_carry:]
        c2, loss = jitted(carry, *rest)
        return (*c2, loss)

    return run


def bench_bert():
    """Secondary bench entry (HOROVOD_BENCH_MODEL=bert): BERT fine-tune
    throughput, BASELINE config 3.  The default metric stays llama_1b so
    round-over-round numbers remain comparable."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import bert

    import os

    on_cpu = jax.devices()[0].platform == "cpu"
    cfg = bert.bert_base(num_labels=4) if not on_cpu else bert.tiny()
    # batch 512 = the round-5 measured knee (608.4 seq/s vs 328.4 at
    # b256; b1024 fails to fit) — BENCH_NOTE_r05.md sweeps 4-5
    batch, seq, steps = (_env_batch(512), 128, 40) if not on_cpu \
        else (4, 32, 3)
    cfg = dataclasses.replace(
        cfg, max_seq_len=max(cfg.max_seq_len, seq),
        # remat is REQUIRED at the b512 default: the b512 (and b256)
        # remat-off variants OOM HBM (bench_ab_r05_rest.log); only at
        # b<=128 do activations fit without recompute
        remat=os.environ.get("HOROVOD_BENCH_REMAT", "1") != "0")
    n_chips = jax.local_device_count()
    mesh = jax.make_mesh((n_chips,), ("dp",))
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adamw(5e-5)
    opt_state = jax.jit(opt.init)(params)
    step = bert.make_dp_finetune_step(cfg, mesh, "dp", opt,
                                      reduce_grads=True)
    k = _env_scan(10) if not on_cpu else _env_scan()
    if k > 1:
        step = _scan_wrap(step, 2, 2, k)

    rng = np.random.RandomState(0)
    sh = NamedSharding(mesh, P("dp"))
    toks = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch * n_chips, seq)), jnp.int32),
        sh)
    labs = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.num_labels, (batch * n_chips,)), jnp.int32), sh)
    params, opt_state, loss = step(params, opt_state, toks, labs)
    float(loss)
    outer = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(outer):
        params, opt_state, loss = step(params, opt_state, toks, labs)
    float(loss)
    dt = time.perf_counter() - t0
    seq_per_sec_chip = batch * outer * k / dt
    mfu = (seq_per_sec_chip * seq * 6 * bert.count_params(cfg)
           ) / (detect_peak() * 1e12)
    print(json.dumps({
        "metric": "bert_base_finetune_sequences_per_sec_per_chip",
        "value": round(seq_per_sec_chip, 1),
        "unit": "sequences/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }))


def bench_resnet():
    """ResNet-50 synthetic entry (HOROVOD_BENCH_MODEL=resnet): img/sec
    through the data-parallel classifier step — BASELINE config 2, the
    reference's pytorch_synthetic_benchmark.py.  The default metric
    stays llama_1b so round-over-round numbers remain comparable."""
    import optax
    from jax.sharding import NamedSharding

    from horovod_tpu import training
    from horovod_tpu.models import resnet
    from horovod_tpu.parallel.mesh import MeshConfig, ParallelMesh

    on_cpu = jax.devices()[0].platform == "cpu"
    # batch 256 = the round-5 measured knee (2,571 img/s vs 2,541 at
    # b128; 2,426 at b512) — BENCH_NOTE_r05.md sweeps 3-4
    variant, img, batch, steps = (50, 224, _env_batch(256), 40) \
        if not on_cpu else (18, 32, 2, 3)
    cfg = resnet.ResNetConfig(variant=variant, dtype=jnp.bfloat16)
    n_chips = jax.local_device_count()
    pmesh = ParallelMesh(MeshConfig(dp=n_chips))
    ts = training.make_classifier_train_step(
        lambda p, s, x, train, axis_name: resnet.forward(
            p, s, x, cfg, train=train, axis_name=axis_name),
        lambda rng: resnet.init(cfg, rng), pmesh,
        optimizer=optax.sgd(0.01, momentum=0.9), sync_bn=True)
    params, state, opt_state = ts.init_fn(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    B = batch * n_chips
    sh = NamedSharding(ts.mesh, ts.data_spec)
    x = jax.device_put(jnp.asarray(rng.rand(B, img, img, 3), jnp.float32),
                       sh)
    y = jax.device_put(jnp.asarray(rng.randint(0, 1000, B), jnp.int32), sh)

    k = _env_scan(10) if not on_cpu else _env_scan()
    sf = ts.step_fn if k == 1 else _scan_wrap(ts.step_fn, 3, 3, k)
    out = sf(params, state, opt_state, x, y)
    params, state, opt_state, loss = out[0], out[1], out[2], out[3]
    float(loss)
    outer = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(outer):
        out = sf(params, state, opt_state, x, y)
        params, state, opt_state, loss = out[0], out[1], out[2], out[3]
    float(loss)
    dt = time.perf_counter() - t0
    img_per_sec_chip = batch * outer * k / dt
    # ResNet-50 fwd ~4.09 GFLOPs/image at 224^2; train ~3x fwd
    flops_per_img = 3 * 4.089e9 if variant == 50 else 0.0
    mfu = (img_per_sec_chip * flops_per_img) / (detect_peak() * 1e12)
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_per_chip",
        "value": round(img_per_sec_chip, 1),
        "unit": "img/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }))


def bench_longctx():
    """Long-context entry (HOROVOD_BENCH_MODEL=longctx): training
    throughput at 8k sequence length, where the flash-attention kernel's
    O(T·blk) memory is what makes the step fit at all.  The default
    metric stays llama_1b for round-over-round comparability."""
    import os

    import optax

    from horovod_tpu import training
    from horovod_tpu.models import llama
    from horovod_tpu.parallel.mesh import MeshConfig, ParallelMesh

    on_cpu = jax.devices()[0].platform == "cpu"
    cfg = llama.LlamaConfig(
        vocab_size=32768, d_model=1024, n_layers=8, n_heads=16,
        n_kv_heads=8, d_ff=4096, max_seq_len=8192,
        # ~100M params: 8k-seq activations fit HBM without remat —
        # round-5 measured: remat OFF is +9.4%, and batch 2 another +5%
        # (50,355 t/s vs 43,760 at the b1+remat r4 configuration; b4
        # fails to fit) — BENCH_NOTE_r05.md sweeps 3-4
        remat=os.environ.get("HOROVOD_BENCH_REMAT", "0") != "0",
        remat_policy="full", loss_chunk=1024)
    batch, seq, steps = _env_batch(2), 8192, 10
    if on_cpu:
        cfg = dataclasses.replace(cfg, d_model=256, n_layers=2, n_heads=8,
                                  n_kv_heads=4, d_ff=1024, vocab_size=4096,
                                  max_seq_len=1024)
        batch, seq, steps = 1, 1024, 2

    n_chips = jax.local_device_count()
    pmesh = ParallelMesh(MeshConfig(dp=n_chips, pp=1, sp=1, tp=1))
    opt = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    ts = training.make_llama_train_step(cfg, pmesh, optimizer=opt)
    params, opt_state = ts.init_fn(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    sh = training.make_data_sharding(ts)
    toks = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch * n_chips, seq)), jnp.int32),
        sh)
    k = _env_scan()
    sf = ts.step_fn if k == 1 else _scan_wrap(ts.step_fn, 2, 2, k)
    params, opt_state, loss = sf(params, opt_state, toks, toks)
    float(loss)
    outer = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(outer):
        params, opt_state, loss = sf(params, opt_state, toks, toks)
    float(loss)
    dt = time.perf_counter() - t0
    tok_per_sec_chip = batch * seq * outer * k / dt
    # attention FLOPs matter at 8k: 6·N·params + 12·L·H·Dh·T per token
    n_params = llama.count_params(cfg)
    attn_flops_tok = 12 * cfg.n_layers * cfg.d_model * seq / 2
    mfu = (tok_per_sec_chip * (6 * n_params + attn_flops_tok)
           ) / (detect_peak() * 1e12)
    print(json.dumps({
        "metric": "llama_longctx8k_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }))


def bench_llama8b_dp():
    """BASELINE config 4 — the north star (HOROVOD_BENCH_MODEL=
    llama8b_dp): Llama-3-8B data-parallel on a v5p-128 slice.

    On >= 64 chips: measure tokens/s/chip on the full dp x tp4 mesh AND
    on a tp4-only reference slice (the smallest mesh that fits 8B);
    scaling efficiency = full-mesh per-chip throughput / reference
    per-chip throughput, and ``vs_baseline`` = efficiency / 0.90
    (BASELINE: >= 90% linear scaling).

    Below 64 chips (the tunneled single chip / CPU): AOT-rehearse the
    REAL 8B step over 64 virtual devices in a subprocess
    (tools/rehearse_8b.py — trace + StableHLO + per-chip HBM from the
    actual shardings) and emit the same metric shape with value 0.0 and
    the rehearsal payload attached.

    HOROVOD_BENCH_8B_FORCE=1 runs the measurement path on a scaled-down
    config over the devices present, validating the efficiency math
    end-to-end (tests use this on the 8-device CPU mesh).
    """
    import os
    import subprocess

    from horovod_tpu import training
    from horovod_tpu.models import llama
    from horovod_tpu.optim.precision import adamw_lp
    from horovod_tpu.parallel.mesh import MeshConfig, ParallelMesh

    metric = "llama3_8b_dp_scaling_efficiency"
    force = os.environ.get("HOROVOD_BENCH_8B_FORCE") == "1"
    n = jax.device_count()
    on_cpu = jax.devices()[0].platform == "cpu"
    if not force and (on_cpu or n < 64):
        here = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # rehearse sets its own 64-dev flag
        env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "tools",
                                              "rehearse_8b.py")],
                capture_output=True, text=True, timeout=1800, env=env)
            line = next((ln for ln in proc.stdout.splitlines()
                         if ln.startswith("{")), None)
            if line is None:
                # crashed before emitting: carry the diagnosis in the
                # metric line — it may be all that gets collected
                reh = {"ok": False,
                       "error": f"no JSON line, rc={proc.returncode}",
                       "stderr_tail": proc.stderr[-400:]}
            else:
                reh = json.loads(line)
        except (subprocess.TimeoutExpired, ValueError) as exc:
            # the metric line must come out even when the rehearsal
            # hangs or emits garbage (same posture as the probe guard)
            reh = {"ok": False, "error": str(exc)[:200]}
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "fraction",
            "vs_baseline": 0.0,
            "rehearsal": reh,
            "note": (f"{n} device(s) available; the measurement needs a "
                     f">=64-chip v5p slice — AOT rehearsal "
                     + ("ok" if reh.get("ok") else "FAILED")),
        }))
        return

    if force and n < 64:
        tp = 2 if n >= 4 else 1
        cfg = dataclasses.replace(
            llama.LlamaConfig(
                vocab_size=4096, d_model=256, n_layers=2, n_heads=8,
                n_kv_heads=4, d_ff=1024, max_seq_len=256, remat=True),
            vocab_parallel=tp > 1)
        seq, steps = 256, 3
    else:
        # the SAME configuration the rehearsal lowers (shared helper —
        # rehearsal and measurement cannot drift apart)
        tp = llama.LLAMA8B_TP
        cfg = llama.llama3_8b_train_cfg(seq=4096)
        seq, steps = 4096, 10
    dp_full = n // tp

    def measure(dp: int) -> float:
        """tokens/s/chip of the real train step on a dp x tp submesh."""
        pmesh = ParallelMesh(MeshConfig(dp=dp, tp=tp),
                             devices=jax.devices()[:dp * tp])
        ts = training.make_llama_train_step(
            cfg, pmesh, optimizer=adamw_lp(3e-4), zero1=dp > 1)
        params, opt_state = ts.init_fn(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        sh = training.make_data_sharding(ts)
        toks = jax.device_put(jnp.asarray(
            rng.randint(0, cfg.vocab_size, (dp, seq)), jnp.int32), sh)
        params, opt_state, loss = ts.step_fn(params, opt_state, toks,
                                             toks)
        float(loss)  # sync
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = ts.step_fn(params, opt_state,
                                                 toks, toks)
        float(loss)
        return dp * seq * steps / (time.perf_counter() - t0) / (dp * tp)

    ref = measure(1)           # tp-only slice: the smallest 8B fit
    full = measure(dp_full)    # the whole slice
    eff = full / ref
    print(json.dumps({
        "metric": metric, "value": round(eff, 3), "unit": "fraction",
        "vs_baseline": round(eff / 0.90, 3),
        "tokens_per_sec_per_chip": round(full, 1),
        "reference_tokens_per_sec_per_chip": round(ref, 1),
        "mesh": {"dp": dp_full, "tp": tp, "chips": dp_full * tp},
        "seq": seq,
    }))


def main():
    import os

    import optax

    from horovod_tpu import training
    from horovod_tpu.models import llama
    from horovod_tpu.parallel.mesh import MeshConfig, ParallelMesh

    if os.environ.get("HOROVOD_BENCH_MODEL") == "bert":
        return bench_bert()
    if os.environ.get("HOROVOD_BENCH_MODEL") == "longctx":
        return bench_longctx()
    if os.environ.get("HOROVOD_BENCH_MODEL") == "resnet":
        return bench_resnet()
    if os.environ.get("HOROVOD_BENCH_MODEL") == "llama8b_dp":
        return bench_llama8b_dp()

    on_cpu = jax.devices()[0].platform == "cpu"
    # ~1B-param geometry: head_dim 128 keeps the flash kernel's score
    # matmuls at the MXU's full 128-wide contraction; full remat trades
    # recompute FLOPs for the HBM that lets adamw master state fit.
    # Env knobs (defaults = the round-5 measured A/B winner on the real
    # v5e chip, BENCH_NOTE_r05.md: chunk-2048 xent + bf16-moment AdamW +
    # last-2-layers un-remat'd + scan10 -> 16,690 t/s, vs 16,518 at
    # chunk-1024 and 15,895 at the r2-era defaults):
    #   HOROVOD_BENCH_LOSS_CHUNK  chunked vocab cross-entropy
    #   HOROVOD_BENCH_REMAT_SKIP  last-k layers un-remat'd
    #   HOROVOD_BENCH_OPT=lp      bf16-moment AdamW
    #   HOROVOD_BENCH_FUSED_XENT  fused Pallas cross-entropy kernel
    #     (hardware-measured round 5: 16,148 t/s with the default knobs
    #      vs 16,518 for the chunked-XLA loss — no win at this 1B
    #      geometry, stays opt-in; see BENCH_NOTE_r05.md)
    cfg = llama.LlamaConfig(
        vocab_size=32768, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=8, d_ff=8192, max_seq_len=1024, remat=True,
        # "dots" saves matmul outputs and recomputes only elementwise in
        # the backward pass (A/B knob; "full" = max memory savings)
        remat_policy=os.environ.get("HOROVOD_BENCH_REMAT_POLICY", "full"),
        loss_chunk=int(os.environ.get("HOROVOD_BENCH_LOSS_CHUNK", "2048")),
        remat_skip_layers=int(
            os.environ.get("HOROVOD_BENCH_REMAT_SKIP", "2")),
        fused_xent=os.environ.get("HOROVOD_BENCH_FUSED_XENT") == "1")
    batch, seq, steps = _env_batch(8), 1024, 30
    if on_cpu:  # keep the CPU fallback path quick
        cfg = dataclasses.replace(
            cfg, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
            d_ff=1024, vocab_size=4096,
            # keep the default chunking active at the smaller seq len
            loss_chunk=min(cfg.loss_chunk, 128) if cfg.loss_chunk else 0)
        batch, seq, steps = 2, 256, 3

    n_chips = jax.local_device_count()
    pmesh = ParallelMesh(MeshConfig(dp=n_chips, pp=1, sp=1, tp=1))
    if os.environ.get("HOROVOD_BENCH_OPT", "lp") == "lp":
        from horovod_tpu.optim.precision import adamw_lp
        opt = adamw_lp(3e-4)
    else:
        opt = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    ts = training.make_llama_train_step(cfg, pmesh, optimizer=opt)
    params, opt_state = ts.init_fn(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    sh = training.make_data_sharding(ts)
    toks = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch * n_chips, seq)), jnp.int32),
        sh)
    tgts = jax.device_put(jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch * n_chips, seq)), jnp.int32),
        sh)

    # warmup (compile).  scan10 = the round-5 measured winner (16,690
    # t/s vs 16,569 eager, two agreeing runs; scan20 16,641) — real TPU
    # loops amortize host dispatch the same way (BENCH_NOTE_r05.md).
    k = _env_scan(10) if not on_cpu else _env_scan()
    sf = ts.step_fn if k == 1 else _scan_wrap(ts.step_fn, 2, 2, k)
    params, opt_state, loss = sf(params, opt_state, toks, tgts)
    float(loss)  # device→host transfer is the reliable sync point

    outer = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(outer):
        params, opt_state, loss = sf(params, opt_state, toks, tgts)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = batch * n_chips * seq
    tok_per_sec = tokens_per_step * outer * k / dt
    tok_per_sec_chip = tok_per_sec / n_chips

    # model FLOPs: ~6 * params * tokens per train step (fwd+bwd)
    n_params = llama.count_params(cfg)
    flops_per_tok = 6 * n_params
    mfu = (tok_per_sec_chip * flops_per_tok) / (detect_peak() * 1e12)

    print(json.dumps({
        "metric": "llama_1b_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }))


def _device_probe_guard(timeout_s: float) -> None:
    """Fail fast (parseable) when the TPU tunnel is wedged.

    A wedged axon terminal session lock makes the first device touch
    block indefinitely in the claim loop (BENCH_NOTE_r03.md).  Probe
    device init in a SUBPROCESS with a deadline; on timeout the probe is
    left running — killing a mid-claim PJRT client is exactly what
    wedges the tunnel, and the orphan exits cleanly on its own if the
    terminal ever recovers — and this process prints an error JSON line
    and exits nonzero so the driver records a failure instead of
    hanging (and instead of SIGKILLing a mid-claim client itself).
    """
    import os
    import subprocess

    if os.environ.get("HOROVOD_BENCH_SKIP_PROBE") == "1":
        return
    # report the failure against the metric+unit this run would have
    # produced (same HOROVOD_BENCH_MODEL mapping main() dispatches on)
    metric, unit = {
        "bert": ("bert_base_finetune_sequences_per_sec_per_chip",
                 "sequences/s/chip"),
        "longctx": ("llama_longctx8k_train_tokens_per_sec_per_chip",
                    "tokens/s/chip"),
        "resnet": ("resnet50_train_img_per_sec_per_chip", "img/s/chip"),
        "llama8b_dp": ("llama3_8b_dp_scaling_efficiency", "fraction"),
    }.get(os.environ.get("HOROVOD_BENCH_MODEL", ""),
          ("llama_1b_train_tokens_per_sec_per_chip", "tokens/s/chip"))
    # honor HOROVOD_TPU_FORCE_PLATFORM like runner/run_task.py — the
    # axon sitecustomize overrides JAX_PLATFORMS programmatically, so a
    # CPU-forced bench must not send its probe to the TPU claim queue
    probe_src = (
        "import os, jax\n"
        "plat = os.environ.get('HOROVOD_TPU_FORCE_PLATFORM')\n"
        "if plat:\n"
        "    jax.config.update('jax_platforms', plat)\n"
        "jax.devices(); print('ok')\n")
    probe = subprocess.Popen(
        [sys.executable, "-c", probe_src],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        start_new_session=True)
    try:
        out, _ = probe.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0,
            "error": f"device init did not complete within {timeout_s:.0f}s "
                     "(wedged TPU tunnel? see BENCH_NOTE_r03.md); probe "
                     "left running to avoid a mid-claim kill",
        }))
        sys.exit(1)
    if b"ok" not in out:
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0,
            "error": f"device probe exited rc={probe.returncode}",
        }))
        sys.exit(1)


if __name__ == "__main__":
    import os as _os
    # CPU-forced runs (CI, smoke tests) must never enter the TPU claim
    # queue: the axon sitecustomize sets jax_platforms programmatically,
    # so the env var alone is not enough (runner/run_task.py does the
    # same for launched workers).
    _plat = _os.environ.get("HOROVOD_TPU_FORCE_PLATFORM")
    if _plat:
        jax.config.update("jax_platforms", _plat)
    _device_probe_guard(float(_os.environ.get(
        "HOROVOD_BENCH_PROBE_TIMEOUT", "300")))
    sys.exit(main())
