"""Build for horovod_tpu, including the native core extension.

The reference builds its C++ core per-framework via a CMake superbuild
(reference: setup.py + CMakeLists.txt, SURVEY.md §2.1 "Build system").  On
TPU there is exactly one framework ABI (CPython), so a single setuptools
Extension suffices: ``horovod_tpu.native._hvd_core`` holds the control-plane
hot paths (fusion planner, response cache, timeline writer, stall tracker).

Build in place with::

    python setup.py build_ext --inplace

or let ``horovod_tpu.native.loader`` build it on first use.
"""

import os

from setuptools import Extension, find_packages, setup

ext = Extension(
    "horovod_tpu.native._hvd_core",
    sources=["horovod_tpu/native/core.cpp"],
    language="c++",
    extra_compile_args=["-std=c++17", "-O2", "-fvisibility=hidden"],
)

# Feature-flag matrix (reference: HOROVOD_WITH_*/HOROVOD_WITHOUT_* in
# the reference's setup.py): one flag suffices here — frameworks are
# pure-Python adapters over the shared engine, so only the native core
# is a build-time choice.  `hvdrun --check-build` prints what was built.
exts = [] if os.environ.get("HOROVOD_WITHOUT_NATIVE_CORE") == "1" else [ext]

setup(
    name="horovod_tpu",
    version="0.1.0",
    description="TPU-native distributed training framework "
                "(capability rebuild of Horovod)",
    packages=find_packages(exclude=("tests", "tests.*")),
    # native sources ride the wheel: the TF XLA op bridge (and the
    # pure-python-install fallback of the core) compile on demand from
    # the installed tree
    package_data={"horovod_tpu.native": ["*.cc", "*.cpp"]},
    ext_modules=exts,
    entry_points={
        "console_scripts": [
            "hvdrun = horovod_tpu.runner.launch:main",
        ],
    },
    python_requires=">=3.10",
)
