"""Build for horovod_tpu, including the native core extension.

The reference builds its C++ core per-framework via a CMake superbuild
(reference: setup.py + CMakeLists.txt, SURVEY.md §2.1 "Build system").  On
TPU there is exactly one framework ABI (CPython), so a single setuptools
Extension suffices: ``horovod_tpu.native._hvd_core`` holds the control-plane
hot paths (fusion planner, response cache, timeline writer, stall tracker).

Build in place with::

    python setup.py build_ext --inplace

or let ``horovod_tpu.native.loader`` build it on first use.
"""

from setuptools import Extension, find_packages, setup

ext = Extension(
    "horovod_tpu.native._hvd_core",
    sources=["horovod_tpu/native/core.cpp"],
    language="c++",
    extra_compile_args=["-std=c++17", "-O2", "-fvisibility=hidden"],
)

setup(
    name="horovod_tpu",
    version="0.1.0",
    description="TPU-native distributed training framework "
                "(capability rebuild of Horovod)",
    packages=find_packages(exclude=("tests", "tests.*")),
    ext_modules=[ext],
    entry_points={
        "console_scripts": [
            "hvdrun = horovod_tpu.runner.launch:main",
        ],
    },
    python_requires=">=3.10",
)
