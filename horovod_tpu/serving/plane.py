"""Driver-side serving plane: admission, leases, results, stragglers.

The control plane built for elastic training (keep-alive RPC, epoch
re-forms, blacklists, merged metrics) is already a serving fleet
manager — this module adds the data path.  Clients POST
``serve_submit``; the admission queue micro-batches
(:mod:`.admission`); workers long-poll ``serve_pull`` and report with
``serve_push``; clients long-poll ``serve_result``.  Every hop rides
:func:`~horovod_tpu.runner.rpc.json_request` — the same HMAC-signed
keep-alive connection pool as the rest of the control plane.

Loss-free elasticity: every dispatched batch holds a LEASE.  A lease is
released by the worker's push, requeued by ``worker_gone`` (the elastic
driver's reaper and re-form path call it — docs/serving.md), or
requeued by the lease reaper at ``lease_s`` (the backstop for silent
worker death when no driver is watching).  Requeued requests keep
their admission ordinal, so they rejoin the FRONT of their shape
class: kill-worker-mid-traffic loses zero requests, the
``tools/bench_serve.py`` gate.

Tail protection: per-worker service-time EWMAs (fed by every push)
rotate a chronic straggler out of the pull rotation once its EWMA
crosses ``straggler_factor`` x the median of its peers — the serving
analog of the gradient plane's straggler blacklist (OptiReduce's
prescription applied to the product metric itself).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import metrics as _metrics
from ..config import Config
from .admission import AdmissionQueue, Batch, ServeRequest
from .shapes import ShapeBuckets

logger = logging.getLogger("horovod_tpu")

# -- metric families (docs/metrics.md; sites guard on _metrics.ACTIVE) --------
# serve-latency histograms use lo=-13 (≈0.12 ms): the 2^-10 floor of
# hvd_tail_lateness_seconds cannot separate a 0.3 ms from a 0.9 ms
# request (both land under the ~0.98 ms edge) — pinned in
# tests/test_serving.py
_m_requests = _metrics.counter(
    "hvd_serve_requests_total",
    "Serving requests by outcome (completed / expired / rejected)",
    labels=("outcome",))
_m_requeued = _metrics.counter(
    "hvd_serve_requeued_total",
    "Dispatched requests returned to the admission queue, by cause",
    labels=("reason",))
_m_batches = _metrics.counter(
    "hvd_serve_batches_total",
    "Micro-batches dispatched, by padded shape bucket",
    labels=("bucket",))
_m_fill = _metrics.histogram(
    "hvd_serve_batch_fill_ratio",
    "Live rows / padded batch capacity of each dispatched micro-batch",
    lo=-4, hi=0)
_m_depth = _metrics.gauge(
    "hvd_serve_queue_depth", "Requests waiting in the admission queue")
_m_admission = _metrics.histogram(
    "hvd_serve_admission_latency_seconds",
    "Submit -> micro-batch dispatch wait (the batching window cost)",
    lo=-13, hi=7)
_m_e2e = _metrics.histogram(
    "hvd_serve_e2e_latency_seconds",
    "Submit -> result completion, driver-side clock", lo=-13, hi=7)
_m_workers = _metrics.gauge(
    "hvd_serve_workers", "Serving workers by pull-rotation state",
    labels=("state",))

#: Completed-but-unfetched results kept before dropping the oldest (a
#: client that never fetches must not grow driver memory forever).
_RESULT_CACHE = 4096

#: Completed-request ids remembered for requeue/late-push dedup.  The
#: dedup window only has to outlive a lease (the longest a stale
#: sibling can still push), so an LRU bound keeps a job-lifetime plane
#: at constant memory — like _RESULT_CACHE beside it.
_COMPLETED_CACHE = 4 * _RESULT_CACHE

#: Cap on one serve_pull/serve_result long-poll hold.
_MAX_HOLD_S = 30.0


class _Lease:
    __slots__ = ("batch", "worker", "t_dispatch", "expires")

    def __init__(self, batch: Batch, worker: str, t_dispatch: float,
                 expires: float):
        self.batch = batch
        self.worker = worker
        self.t_dispatch = t_dispatch
        self.expires = expires


class _WorkerState:
    __slots__ = ("ewma", "observations", "rotated", "rotated_at",
                 "metrics_port", "last_pull", "kv")

    def __init__(self):
        self.ewma = 0.0
        self.observations = 0
        self.rotated = False
        self.rotated_at: Optional[float] = None
        self.metrics_port: Optional[int] = None
        self.last_pull = 0.0
        # last paged-KV ledger the worker rode along on serve_push
        # (None for dense-cache workers)
        self.kv: Optional[dict] = None


#: Rotation noise floor (seconds): a worker is never rotated while its
#: service EWMA sits under this, however fast its peers are — on a
#: lightly loaded fleet the peer median approaches zero and scheduler
#: jitter alone would otherwise evict healthy workers.
_STRAGGLER_MIN_S = 0.05


class ServingPlane:
    """The driver-side serving data plane (one per job).

    Construction defaults resolve from the validated ``HOROVOD_SERVE_*``
    environment contract (config.py / docs/env.md); keyword arguments
    override per instance.  ``start()`` is implicit; ``close()`` stops
    the admission tick and the lease reaper and makes every parked
    ``serve_pull`` return ``{"stop": true}`` so workers drain.
    """

    def __init__(self, cfg: Optional[Config] = None,
                 tick_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 seq_buckets: Optional[str] = None,
                 batch_buckets: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 lease_s: Optional[float] = None,
                 straggler_factor: Optional[float] = None):
        cfg = cfg or Config.from_env()
        from .shapes import parse_buckets, parse_mp_axes
        seq = parse_buckets(seq_buckets or cfg.serve_seq_buckets,
                            "HOROVOD_SERVE_SEQ_BUCKETS")
        cap = int(max_batch if max_batch is not None
                  else cfg.serve_max_batch)
        batches = parse_buckets(
            batch_buckets or cfg.serve_batch_buckets
            or ",".join(str(b) for b in _default_batch_buckets(cap)),
            "HOROVOD_SERVE_BATCH_BUCKETS")
        if batches[-1] < cap:
            raise ValueError(
                f"largest batch bucket {batches[-1]} < batch cap {cap}: "
                f"the cap must be a servable shape")
        self.mp_axis, mp_degree = parse_mp_axes(cfg.serve_mp_axes)
        self.buckets = ShapeBuckets(
            batches, seq,
            mp_degrees=(1,) if mp_degree == 1 else (1, mp_degree))
        self.deadline_s = (deadline_ms if deadline_ms is not None
                           else cfg.serve_deadline_ms) / 1000.0
        self.lease_s = float(lease_s if lease_s is not None
                             else cfg.serve_lease_s)
        self.straggler_factor = float(
            straggler_factor if straggler_factor is not None
            else cfg.serve_straggler_factor)
        self._cv = threading.Condition()
        self._leases: Dict[int, _Lease] = {}
        self._workers: Dict[str, _WorkerState] = {}
        self._done: "OrderedDict[str, dict]" = OrderedDict()
        self._completed_ids: "OrderedDict[str, None]" = OrderedDict()
        self._closed = False
        self.completed = 0
        self.rotations = 0
        # the queue shares the plane's Condition: a submit wakes parked
        # serve_pull long-polls directly, and batches bind at pull time
        # (late binding — see admission.py)
        self._queue = AdmissionQueue(
            self.buckets,
            tick_s=(tick_ms if tick_ms is not None
                    else cfg.serve_tick_ms) / 1000.0,
            on_expired=self._on_expired, max_batch=cap, cv=self._cv)
        self._reaper = threading.Thread(
            target=self._reap_leases, name="hvd-serve-leases", daemon=True)
        self._reaper.start()
        from . import register as _register
        _register("plane", self)

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        from . import unregister as _unregister
        _unregister(self)

    def set_max_batch(self, max_batch: int):
        """Runtime batch-cap change (the sequential-baseline switch the
        bench A/B uses; cap 1 = one request per forward)."""
        self._queue.set_max_batch(max_batch)

    # -- admission ----------------------------------------------------------
    def submit(self, tokens, request_id: Optional[str] = None,
               deadline_s: Optional[float] = None) -> str:
        """Admit one request; returns its id.  Raises ValueError when
        the request cannot be served inside the shape buckets."""
        rid = request_id or uuid.uuid4().hex
        arr = np.asarray(tokens, dtype=np.int32).reshape(-1)
        now = time.monotonic()
        dl = deadline_s if deadline_s is not None else self.deadline_s
        req = ServeRequest(
            id=rid, tokens=arr, arrival=now,
            deadline=(now + dl) if dl and dl > 0 else None,
            seq_bucket=0)
        try:
            self._queue.submit(req)
        except ValueError:
            if _metrics.ACTIVE:
                _m_requests.inc(outcome="rejected")
            raise
        if _metrics.ACTIVE:
            _m_depth.set(self._queue.depth())
        return rid

    def _on_expired(self, req: ServeRequest):
        if _metrics.ACTIVE:
            _m_requests.inc(outcome="expired")
            _m_depth.set(self._queue.depth())
        self._finish(req.id, {"done": True, "expired": True,
                              "latency_s": round(
                                  time.monotonic() - req.arrival, 6)})

    # -- worker data path ---------------------------------------------------
    def _worker(self, wid: str) -> _WorkerState:
        w = self._workers.get(wid)
        if w is None:
            w = self._workers[wid] = _WorkerState()
            self._update_worker_gauges()
        return w

    def pull(self, worker: str, wait_s: float = 5.0,
             metrics_port: Optional[int] = None) -> dict:
        """One worker long-poll: parks up to ``wait_s`` for a ready
        micro-batch.  Rotated workers get ``{"empty", "rotated"}`` so a
        straggler drains its in-flight work but receives no more."""
        deadline = time.monotonic() + min(max(wait_s, 0.0), _MAX_HOLD_S)
        with self._cv:
            w = self._worker(worker)
            w.last_pull = time.monotonic()
            if metrics_port is not None:
                w.metrics_port = int(metrics_port)
            while True:
                if self._closed:
                    return {"stop": True}
                if w.rotated:
                    return {"empty": True, "rotated": True}
                batch = self._queue.take()
                if batch is not None:
                    now = time.monotonic()
                    self._leases[batch.batch_id] = _Lease(
                        batch, worker, now, now + self.lease_s)
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"empty": True}
                # pending-but-inside-its-tick: re-check at the tick so
                # an aging partial batch dispatches on time; otherwise
                # park until a submit notifies
                self._cv.wait(min(remaining, self._queue.tick_s or
                                  remaining)
                              if self._queue.has_pending()
                              else remaining)
        rows = [r.tokens for r in batch.requests]
        tokens, lengths = self.buckets.pad_batch(rows, batch.seq_bucket)
        now = time.monotonic()
        if _metrics.ACTIVE:
            shape = self.buckets.bucket(len(rows), batch.seq_bucket)
            _m_batches.inc(bucket=shape.key)
            _m_fill.observe(len(rows) / shape.batch)
            for r in batch.requests:
                _m_admission.observe(now - r.arrival)
            _m_depth.set(self._queue.depth())
        return {
            "batch_id": batch.batch_id,
            "seq": batch.seq_bucket,
            "rows": len(rows),
            "tokens": tokens.tolist(),
            "lengths": lengths.tolist(),
            "ids": [r.id for r in batch.requests],
            # per-request age at dispatch: the worker adds its service
            # time so the per-worker latency histogram (merged at
            # /metrics/job) covers the queue wait without sharing a
            # clock with the driver
            "age_s": [round(now - r.arrival, 6) for r in batch.requests],
        }

    def push(self, worker: str, batch_id: int, outputs: List,
             service_s: float = 0.0, kv: Optional[dict] = None) -> dict:
        """Worker batch completion.  A push for an unknown lease (the
        batch was requeued after this worker was declared gone, and a
        sibling already served it) is acknowledged and dropped —
        first completion wins."""
        with self._cv:
            lease = self._leases.pop(int(batch_id), None)
            if kv is not None and worker in self._workers:
                # KV ledger ride-along: stored even on a stale push
                # (the residency snapshot is real either way)
                self._workers[worker].kv = dict(kv)
        if lease is None:
            return {"ok": True, "stale": True}
        now = time.monotonic()
        for i, req in enumerate(lease.batch.requests):
            out = outputs[i] if i < len(outputs) else None
            latency = now - req.arrival
            if _metrics.ACTIVE:
                _m_requests.inc(outcome="completed")
                _m_e2e.observe(latency)
            self._finish(req.id, {"done": True, "output": out,
                                  "worker": worker,
                                  "latency_s": round(latency, 6)})
        with self._cv:
            self.completed += len(lease.batch.requests)
        # scored on the DRIVER-side dispatch->push wall, not the
        # worker's self-reported service time: the score feeds an
        # eviction decision, so it must not trust the evictee's clock
        self._score_worker(worker, now - lease.t_dispatch)
        return {"ok": True}

    def _finish(self, rid: str, result: dict):
        with self._cv:
            if rid in self._completed_ids:
                return   # first completion won (requeue + late sibling)
            self._completed_ids[rid] = None
            while len(self._completed_ids) > _COMPLETED_CACHE:
                self._completed_ids.popitem(last=False)
            self._done[rid] = result
            while len(self._done) > _RESULT_CACHE:
                self._done.popitem(last=False)
            self._cv.notify_all()

    def result(self, rid: str, wait_s: float = 0.0) -> dict:
        """Client result fetch (long-poll).  Fetch consumes the result."""
        deadline = time.monotonic() + min(max(wait_s, 0.0), _MAX_HOLD_S)
        with self._cv:
            while True:
                res = self._done.pop(rid, None)
                if res is not None:
                    return res
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return {"done": False}
                self._cv.wait(remaining)

    def drain(self, wait_s: float = 0.0) -> dict:
        """Fan-in result fetch: long-poll until ANY results are ready,
        then consume and return all of them — one parked call instead
        of one per request, for clients tracking many ids (the bench's
        open-loop collector; a gateway multiplexing users)."""
        deadline = time.monotonic() + min(max(wait_s, 0.0), _MAX_HOLD_S)
        with self._cv:
            while True:
                if self._done:
                    out, self._done = dict(self._done), OrderedDict()
                    return {"results": out}
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return {"results": {}}
                self._cv.wait(remaining)

    # -- elasticity ---------------------------------------------------------
    def worker_gone(self, worker) -> int:
        """Requeue every lease held by ``worker`` (elastic reaper /
        re-form hook) and drop its pull-rotation state — a dead
        worker's stale EWMA must not drag the straggler peer median,
        and churn must not accrete ghost worker entries.  Returns the
        number of requests requeued."""
        with self._cv:
            if self._workers.pop(str(worker), None) is not None:
                self._update_worker_gauges()
        return self._requeue_leases(
            lambda lease: lease.worker == str(worker), "worker_gone")

    def retain_workers(self, live) -> int:
        """Re-form hook: requeue leases of every worker NOT in ``live``
        (the new epoch's membership) — in-flight requests of preempted
        workers are re-queued, not dropped — and drop departed
        workers' rotation state (see :meth:`worker_gone`)."""
        keep = {str(w) for w in live}
        with self._cv:
            gone = [wid for wid in self._workers if wid not in keep]
            for wid in gone:
                del self._workers[wid]
            if gone:
                self._update_worker_gauges()
        return self._requeue_leases(
            lambda lease: lease.worker not in keep, "reform")

    def _requeue_leases(self, pred, reason: str) -> int:
        with self._cv:
            gone = [bid for bid, lease in self._leases.items()
                    if pred(lease)]
            requests: List[ServeRequest] = []
            for bid in gone:
                requests.extend(self._leases.pop(bid).batch.requests)
            requests = [r for r in requests
                        if r.id not in self._completed_ids]
        if requests:
            self._queue.requeue(requests)
            if _metrics.ACTIVE:
                _m_requeued.inc(len(requests), reason=reason)
                _m_depth.set(self._queue.depth())
            logger.warning("serving: requeued %d in-flight requests "
                           "(%s)", len(requests), reason)
        return len(requests)

    def _reap_leases(self):
        while True:
            with self._cv:
                if self._closed:
                    return
                self._cv.wait(timeout=max(self.lease_s / 4, 0.05))
                if self._closed:
                    return
            now = time.monotonic()
            n = self._requeue_leases(
                lambda lease: now > lease.expires, "lease_expired")
            if n and _metrics.RECORDING:
                _metrics.event("serve.lease_expired", requeued=n)
            # deadlines must fire even with no worker pulling
            self._queue.sweep_expired(now)

    # -- straggler rotation -------------------------------------------------
    def _score_worker(self, wid: str, service_s: float):
        """EWMA the worker's batch service time; rotate it out of the
        pull rotation when it is ``straggler_factor`` x slower than the
        median of its active peers (>= 3 observations, >= 2 active
        workers, and never the last active worker)."""
        if service_s <= 0:
            return
        rotated = None
        with self._cv:
            w = self._worker(wid)
            w.ewma = (service_s if w.observations == 0
                      else 0.7 * w.ewma + 0.3 * service_s)
            w.observations += 1
            if (self.straggler_factor > 0 and not w.rotated
                    and w.observations >= 3
                    and w.ewma > _STRAGGLER_MIN_S):
                peers = sorted(
                    p.ewma for k, p in self._workers.items()
                    if k != wid and not p.rotated and p.observations >= 1)
                if peers and w.ewma > (self.straggler_factor
                                       * peers[len(peers) // 2]):
                    w.rotated = True
                    w.rotated_at = time.monotonic()
                    self.rotations += 1
                    rotated = (w.ewma, peers[len(peers) // 2])
                    self._update_worker_gauges()
                    self._cv.notify_all()   # wake its parked pull
        if rotated is not None:
            logger.warning(
                "serving: worker %s rotated out as straggler (ewma "
                "%.3fs vs peer median %.3fs x factor %.1f)", wid,
                rotated[0], rotated[1], self.straggler_factor)
            if _metrics.RECORDING:
                _metrics.event("serve.straggler_rotated", worker=wid,
                               ewma=round(rotated[0], 4))

    def _update_worker_gauges(self):
        if _metrics.ACTIVE:
            _m_workers.set(sum(1 for w in self._workers.values()
                               if not w.rotated), state="active")
            _m_workers.set(sum(1 for w in self._workers.values()
                               if w.rotated), state="rotated")

    def worker_endpoints(self, addr: str = "127.0.0.1"
                         ) -> Dict[str, Tuple[str, int]]:
        """``{worker: (addr, metrics_port)}`` of workers that announced
        a metrics port on pull — the /metrics/job-shaped merge input."""
        with self._cv:
            return {wid: (addr, w.metrics_port)
                    for wid, w in self._workers.items()
                    if w.metrics_port}

    # -- RPC surface --------------------------------------------------------
    def rpc_handlers(self) -> Dict[str, "callable"]:
        """The serving data path as JsonRpcServer handlers — attach to
        the elastic driver's control server
        (``ElasticDriver.attach_serving``) or host standalone."""
        def serve_submit(payload):
            reqs = payload.get("requests")
            if reqs is None:
                reqs = [payload]
            ids = []
            for r in reqs:
                try:
                    ids.append(self.submit(
                        r["tokens"], request_id=r.get("id"),
                        deadline_s=(r["deadline_ms"] / 1000.0
                                    if r.get("deadline_ms") is not None
                                    else None)))
                except ValueError as e:
                    ids.append(None)
                    logger.warning("serving: rejected request: %s", e)
            return {"ok": True, "ids": ids}

        def serve_pull(payload):
            return self.pull(str(payload["worker"]),
                             wait_s=float(payload.get("wait_s", 5.0)),
                             metrics_port=payload.get("metrics_port"))

        def serve_push(payload):
            return self.push(str(payload["worker"]),
                             int(payload["batch_id"]),
                             payload.get("outputs") or [],
                             service_s=float(
                                 payload.get("service_s", 0.0)),
                             kv=payload.get("kv"))

        def serve_result(payload):
            return self.result(str(payload["id"]),
                               wait_s=float(payload.get("wait_s", 0.0)))

        def serve_drain(payload):
            return self.drain(wait_s=float(payload.get("wait_s", 0.0)))

        return {"serve_submit": serve_submit, "serve_pull": serve_pull,
                "serve_push": serve_push, "serve_result": serve_result,
                "serve_drain": serve_drain}

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        q = self._queue.stats()
        with self._cv:
            workers = {
                wid: {"ewma_s": round(w.ewma, 6),
                      "observations": w.observations,
                      "rotated": w.rotated,
                      "rotated_at": w.rotated_at,
                      "kv": w.kv}
                for wid, w in sorted(self._workers.items())}
            kv_totals = None
            ledgers = [w.kv for w in self._workers.values() if w.kv]
            if ledgers:
                kv_totals = {
                    k: sum(int(led.get(k, 0)) for led in ledgers)
                    for k in ("in_use", "cached", "free", "reuse_hits",
                              "bytes_in_use", "bytes_capacity")}
            return {
                "queue": q,
                "completed": self.completed,
                "in_flight": sum(len(le.batch.requests)
                                 for le in self._leases.values()),
                "leases": len(self._leases),
                "leased_workers": sorted({le.worker for le
                                          in self._leases.values()}),
                "rotations": self.rotations,
                "workers": workers,
                "kv": kv_totals,
                "buckets": {
                    "batch": list(self.buckets.batch_buckets),
                    "seq": list(self.buckets.seq_buckets),
                    "mp": list(getattr(self.buckets, "mp_degrees", (1,)))},
            }


def _default_batch_buckets(cap: int) -> List[int]:
    """Powers of two up to ``cap`` (cap itself always included)."""
    out = []
    b = 1
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out
