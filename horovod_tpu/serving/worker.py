"""Worker-side serving loop: pull a micro-batch, run the jit'd forward,
push the outputs.

Pure data parallelism: every worker owns a full replica and serves its
batches independently — the forward NEVER negotiates a collective (the
``serve_forward_step`` hvdsched snapshot pins that structurally: its
collective schedule is EMPTY), so a straggling or dying worker stalls
only its own leases, which the plane requeues.  The pull is a long-poll
over the keep-alive RPC pool (one parked request, not a poll tick —
the control-plane watch transport's shape applied to the data path).

Per-request latency is observed HERE, per worker: the pulled batch
carries each request's age at dispatch (driver clock) and the worker
adds its own service time — no cross-host clock needed — feeding
``hvd_serve_request_latency_seconds`` on this worker's ``GET /metrics``,
which the driver's ``GET /metrics/job`` merges bucket-wise into the
job-level p50/p99.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from .. import chaos as _chaos
from .. import metrics as _metrics
from ..runner.rpc import json_request

logger = logging.getLogger("horovod_tpu")

# -- metric families (docs/metrics.md; sites guard on _metrics.ACTIVE) --------
_m_latency = _metrics.histogram(
    "hvd_serve_request_latency_seconds",
    "Per-request serving latency (queue age at dispatch + worker "
    "service time).  lo=-13: sub-ms requests must resolve — the "
    "2^-10 tail-lateness floor cannot (tests/test_serving.py)",
    lo=-13, hi=7)
_m_forward = _metrics.histogram(
    "hvd_serve_forward_seconds",
    "Wall time of one jit'd batched forward", lo=-13, hi=4)
_m_recompiles = _metrics.counter(
    "hvd_serve_recompiles_total",
    "Forward compilations for an ALREADY-SEEN shape bucket after "
    "warmup — steady-state serving must keep this at 0 (gated by "
    "tools/bench_serve.py)")
_m_cache_size = _metrics.gauge(
    "hvd_serve_compile_cache_size",
    "Distinct compiled entries in the serving forward's jit cache")


class BucketedForward:
    """A jit'd forward restricted to the admitted shape buckets.

    Wraps ``fn(tokens [B, S] int32, lengths [B] int32) -> array`` with
    the no-recompile discipline: calls outside the bucket set raise,
    and compilations are counted — a compile for a shape seen before
    (cache eviction, a static-arg leak) increments
    ``hvd_serve_recompiles_total``, the gated steady-state invariant.
    """

    def __init__(self, fn: Callable, buckets=None, donate_argnums=(),
                 compiled: bool = False):
        import jax
        # compiled=True: fn is already a staged callable (the
        # mesh-sliced subclass hands in a pmap — jit-of-pmap would
        # add a dispatch layer and hide the pmap's cache)
        self._jit = (fn if compiled
                     else jax.jit(fn, donate_argnums=tuple(donate_argnums)))
        self._buckets = buckets
        self._seen: set = set()
        self.calls = 0
        self.compiles = 0
        self.recompiles = 0

    def _cache_size(self) -> Optional[int]:
        size = getattr(self._jit, "_cache_size", None)
        try:
            return int(size()) if callable(size) else None
        except Exception:  # noqa: BLE001 - jax-version dependent
            return None

    def _check_bucket(self, shape):
        if self._buckets is None:
            return
        b, s = shape
        if (b not in self._buckets.batch_buckets
                or s not in self._buckets.seq_buckets):
            raise ValueError(
                f"forward called outside the shape buckets: {shape} "
                f"not in {self._buckets.batch_buckets} x "
                f"{self._buckets.seq_buckets} (every recompile is a "
                f"p99 outlier)")

    def _run(self, shape, *jit_args):
        """The jit call wrapped in compile bookkeeping (shared by the
        paged and mesh-sliced subclasses, whose signatures differ)."""
        before = self._cache_size()
        out = self._jit(*jit_args)
        after = self._cache_size()
        self.calls += 1
        if after is None:
            # no jit cache introspection on this jax: distinct shapes
            # stand in for compiles (jit retraces exactly per shape)
            compiled = shape not in self._seen
        else:
            compiled = after > (before or 0)
        if compiled:
            self.compiles += 1
            if shape in self._seen:
                self.recompiles += 1
                if _metrics.ACTIVE:
                    _m_recompiles.inc()
                logger.warning("serving: recompiled already-seen shape "
                               "%s", shape)
        self._seen.add(shape)
        if _metrics.ACTIVE:
            # distinct-shapes fallback when introspection is absent:
            # the gauge must move on EVERY jax, or the zero-recompile
            # gate goes blind exactly where it cannot introspect
            _m_cache_size.set(after if after is not None
                              else len(self._seen))
        return out

    def __call__(self, tokens: np.ndarray, lengths: np.ndarray):
        import jax.numpy as jnp
        shape = tuple(tokens.shape)
        self._check_bucket(shape)
        return np.asarray(self._run(shape,
                                    jnp.asarray(tokens, jnp.int32),
                                    jnp.asarray(lengths, jnp.int32)))

    def warmup(self) -> int:
        """Compile every admitted shape bucket up front (the deploy-time
        pre-compile real serving does): after this, a steady-state
        compile is by definition a recompile — the gated invariant.
        Returns the number of shapes compiled."""
        if self._buckets is None:
            return 0
        n = 0
        for b in self._buckets.batch_buckets:
            for s in self._buckets.seq_buckets:
                if (b, s) not in self._seen:
                    self(np.zeros((b, s), np.int32),
                         np.ones((b,), np.int32))
                    n += 1
        return n

    def stats(self) -> Dict[str, int]:
        return {"calls": self.calls, "compiles": self.compiles,
                "recompiles": self.recompiles,
                "shapes_seen": len(self._seen)}


class MeshSlicedForward(BucketedForward):
    """Llama decode over a model-parallel mesh slice: params that don't
    fit one chip live SHARDED across ``mp`` local devices.

    Storage is the point: each device holds ``1/mp`` of every
    mp-divisible parameter (``fsdp_param_specs`` picks the axis — the
    same planner training's FSDP path uses, so serving and training
    agree on what "a shard" is) and only the small norms replicated.
    The forward is a ``pmap`` over the model axis that
    ``spec_all_gather``s the shards leaf-by-leaf and runs the standard
    batched decode on the gathered weights — the fused
    computation-collective shape from PR 14, applied to serving.  The
    collective schedule of this step is pinned by the
    ``serve_mp_forward_step`` hvdsched entry: ONLY the spec gather hops
    may appear (a gradient collective is an HVD211 failure — the
    ``serve_forward_step`` empty-schedule pin, generalized).

    Gather-per-call trades bandwidth for HBM: transient full weights
    during the forward, ``1/mp`` at rest — the resident footprint is
    what caps how many models a serving chip can hold, and
    ``per_chip_param_nbytes`` prices it exactly (gated against the live
    buffers by ``tools/bench_serve.py --mp``).
    """

    def __init__(self, params, cfg, max_new_tokens: int, buckets,
                 mp: int = 2, axis: str = "hvd_serve_mp", devices=None):
        import jax
        import jax.numpy as jnp
        from ..models.generate import batched_greedy_decode
        from ..training import fsdp_param_specs, spec_all_gather
        if mp < 2:
            raise ValueError(f"mp must be >= 2 (use BucketedForward for "
                             f"single-chip serving), got {mp}")
        devices = list(devices if devices is not None
                       else jax.local_devices())
        if len(devices) < mp:
            raise ValueError(f"mp={mp} needs {mp} local devices, have "
                             f"{len(devices)}")
        devices = devices[:mp]
        self.mp = mp
        self.axis = axis
        shapes = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), params)
        specs = fsdp_param_specs(shapes, mp, axis=axis)

        def _sharded_dim(spec):
            for dim, entry in enumerate(spec):
                axes = entry if isinstance(entry, tuple) else (entry,)
                if axis in axes:
                    return dim
            return None

        from jax.sharding import PartitionSpec as _P
        _is_spec = lambda x: isinstance(x, _P)  # noqa: E731

        def shard_for(d):
            def pick(spec, leaf):
                dim = _sharded_dim(spec)
                leaf = np.asarray(leaf)
                if dim is None:
                    return leaf           # replicated (norms)
                size = leaf.shape[dim] // mp
                idx = [slice(None)] * leaf.ndim
                idx[dim] = slice(d * size, (d + 1) * size)
                return leaf[tuple(idx)]
            return jax.tree_util.tree_map(pick, specs, params,
                                          is_leaf=_is_spec)

        # per-chip residency, priced exactly from the specs (replicated
        # leaves count whole, sharded leaves 1/mp — the ZeRO fractional
        # accounting precedent, applied to serving weights)
        spec_leaves = jax.tree_util.tree_leaves(specs, is_leaf=_is_spec)
        param_leaves = jax.tree_util.tree_leaves(params)
        self.replica_param_nbytes = 0
        self.per_chip_param_nbytes = 0
        for spec, leaf in zip(spec_leaves, param_leaves):
            n = int(np.asarray(leaf).nbytes)
            self.replica_param_nbytes += n
            self.per_chip_param_nbytes += (n if _sharded_dim(spec) is None
                                           else n // mp)
        self._shards = jax.device_put_sharded(
            [shard_for(d) for d in range(mp)], devices)

        def fn(shards, tokens, lengths):
            full = spec_all_gather(shards, specs, axis)
            return batched_greedy_decode(full, cfg, tokens, lengths,
                                         max_new_tokens)

        super().__init__(
            jax.pmap(fn, axis_name=axis, in_axes=(0, None, None),
                     devices=devices),
            buckets, compiled=True)

    def __call__(self, tokens: np.ndarray, lengths: np.ndarray):
        import jax.numpy as jnp
        shape = tuple(tokens.shape)
        self._check_bucket(shape)
        out = self._run(shape, self._shards,
                        jnp.asarray(tokens, jnp.int32),
                        jnp.asarray(lengths, jnp.int32))
        # every mesh slice computes the same replicated output
        return np.asarray(out[0])

    def stats(self) -> Dict[str, int]:
        out = super().stats()
        out["mp"] = self.mp
        out["per_chip_param_nbytes"] = self.per_chip_param_nbytes
        out["replica_param_nbytes"] = self.replica_param_nbytes
        return out


class ServingWorker:
    """Pull-loop worker: ``serve_pull`` -> forward -> ``serve_push``.

    ``forward`` maps ``(tokens [B, S] int32, lengths [B] int32)`` to an
    output array whose leading dim is B (a :class:`BucketedForward` or
    any callable).  Runs on a daemon thread (``start()``); exits when
    the plane replies ``{"stop"}`` or ``stop()`` is called.  Transport
    failures back off and retry — mid-re-form the driver is briefly
    unreachable and the worker must ride it out, not die.
    """

    def __init__(self, addr: str, port: int, forward: Callable,
                 worker_id: str = "0", wait_s: float = 5.0,
                 secret=None, metrics_port: Optional[int] = None,
                 warmup: bool = False):
        self.addr = addr
        self.port = port
        self.forward = forward
        self.worker_id = str(worker_id)
        self.wait_s = float(wait_s)
        self._secret = secret
        self._metrics_port = metrics_port
        self._warmup = warmup
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batches = 0
        self.rows = 0
        self.pulls = 0
        from . import register as _register
        _register("worker", self)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self.run, name=f"hvd-serve-worker-{self.worker_id}",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    def join(self, timeout: Optional[float] = None):
        if self._thread is not None:
            self._thread.join(timeout)

    def run(self):
        try:
            if self._warmup:
                # pre-compile the shape set BEFORE the first pull:
                # compile latency must never ride a request (it would
                # both blow that request's p99 and pollute this
                # worker's straggler score with a one-time cost)
                wu = getattr(self.forward, "warmup", None)
                if callable(wu):
                    n = wu()
                    logger.info("serving worker %s warmed %d shapes",
                                self.worker_id, n)
            while not self._stop.is_set():
                if not self._serve_once():
                    break
        finally:
            from . import unregister as _unregister
            _unregister(self)

    # -- one pull/forward/push round ----------------------------------------
    def _serve_once(self) -> bool:
        try:
            payload = {"worker": self.worker_id, "wait_s": self.wait_s}
            if self._metrics_port:
                payload["metrics_port"] = self._metrics_port
            batch = json_request(
                self.addr, self.port, "serve_pull", payload,
                timeout=self.wait_s + 10.0, secret=self._secret,
                retries=0)
        except Exception:  # noqa: BLE001 - driver mid-re-form/gone
            logger.debug("serve_pull failed; backing off", exc_info=True)
            if self._stop.wait(0.2):
                return False
            return True
        if batch.get("stop"):
            return False
        if batch.get("empty"):
            if batch.get("rotated"):
                # rotated out of the pull rotation: stay alive (the
                # operator may clear the rotation) but stop hammering
                self._stop.wait(0.5)
            return True
        self.pulls += 1
        tokens = np.asarray(batch["tokens"], np.int32)
        lengths = np.asarray(batch["lengths"], np.int32)
        n_rows = int(batch["rows"])
        t0 = time.monotonic()
        if _chaos.ACTIVE:
            # serve.batch: deterministic per-worker service faults
            # (delay = a straggling replica the rotation must catch;
            # error/crash = a dying worker whose lease must requeue).
            # Inside the service clock: an injected slow forward must
            # look slow to the latency histogram and the plane's
            # straggler score, exactly like a real one
            _chaos.fire("serve.batch", worker=self.worker_id,
                        batch=batch["batch_id"], rows=n_rows)
        if getattr(self.forward, "wants_rows", False):
            # paged forward: pad rows must not allocate KV blocks
            out = self.forward(tokens, lengths, n_rows=n_rows)
        else:
            out = self.forward(tokens, lengths)
        service = time.monotonic() - t0
        self.batches += 1
        self.rows += n_rows
        if _metrics.ACTIVE:
            _m_forward.observe(service)
            for age in batch["age_s"][:n_rows]:
                _m_latency.observe(float(age) + service)
        outputs = np.asarray(out)[:n_rows].tolist()
        push = {"worker": self.worker_id,
                "batch_id": batch["batch_id"],
                "outputs": outputs,
                "service_s": round(service, 6)}
        kv = getattr(self.forward, "kv_summary", None)
        if callable(kv):
            # paged-KV ledger rides the push: the plane's
            # GET /serve/stats shows per-worker block residency without
            # a second scrape path
            push["kv"] = kv()
        try:
            json_request(
                self.addr, self.port, "serve_push", push,
                timeout=10.0, secret=self._secret, idempotent=False)
        except Exception:  # noqa: BLE001 - lease reaper covers the loss
            logger.warning("serve_push failed; plane will requeue the "
                           "lease", exc_info=True)
        return True

    def stats(self) -> dict:
        out = {"worker": self.worker_id, "pulls": self.pulls,
               "batches": self.batches, "rows": self.rows}
        fwd_stats = getattr(self.forward, "stats", None)
        if callable(fwd_stats):
            out["forward"] = fwd_stats()
        return out
