"""Continuous micro-batching: the admission queue of the serving plane.

Requests are tensors with deadlines, so coalescing IS the engine's
fusion problem restated: the training cycle loop batches asynchronously
submitted gradients into deterministic fused buckets under a byte cap
and a cycle-time window; the admission queue batches asynchronously
submitted requests into micro-batches under a batch cap and an
admission tick.  The mapping is literal — each pending request becomes
an :class:`~horovod_tpu.ops.fusion.EntrySig` (one unit-sized entry, its
seq-length bucket riding the ``layer`` key so shape classes never mix)
and the SAME ``plan_fusion`` planner the engine dispatches with decides
the batches: the byte threshold becomes the batch cap
(``unit_bytes * max_batch``), and the cycle tick becomes the admission
tick.

Batches bind LATE: requests stay pending until a worker pull calls
:meth:`AdmissionQueue.take`, which plans the pending set THEN and hands
out one dispatchable bucket — full, or aged past one tick.  Binding at
submit/tick time instead would freeze batch composition before the
worker is ready and fragment a backlog into stale under-filled batches
(the first bench run measured exactly that: 1-row batches at 3x load).

Deadline semantics (docs/serving.md): a request whose deadline expires
while still queued is failed at admission (outcome ``expired``) instead
of wasting a batch slot on an answer nobody is waiting for; dispatched
requests always complete (a late answer is still an answer — the
latency histograms, not a drop, record the miss).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..ops.fusion import EntrySig, plan_fusion
from .shapes import ShapeBuckets

#: Planner unit: every request is one unit-sized EntrySig, so the
#: engine's byte threshold maps exactly onto the batch cap.
_UNIT_BYTES = 4  # one int32 "element" per request


@dataclasses.dataclass(eq=False)
class ServeRequest:
    """One admitted request: a token row plus its latency contract.

    Identity semantics (``eq=False``): dataclass equality would compare
    the ndarray field — ambiguous-truth ValueError for two same-id
    requests (an idempotent client resubmit) — and no caller wants
    value equality on a request."""
    id: str
    tokens: np.ndarray            # 1-D int32
    arrival: float                # time.monotonic at submit
    deadline: Optional[float]     # absolute monotonic, None = no bound
    seq_bucket: int               # padded seq class (shapes.seq_bucket)
    seq: int = 0                  # admission ordinal (FIFO identity)


@dataclasses.dataclass
class Batch:
    """One planned micro-batch, bound at pull time."""
    batch_id: int
    seq_bucket: int
    requests: List[ServeRequest]
    planned_at: float


class AdmissionQueue:
    """Thread-safe pending set + pull-time micro-batch planner.

    Synchronization is EXTERNAL: the owner (the serving plane) passes
    its own Condition so a ``submit`` wakes parked ``serve_pull``
    long-polls directly; standalone (unit tests) the queue makes its
    own.  ``max_batch`` is mutable (``set_max_batch``) so one plane can
    run the sequential baseline (cap 1) and the batched path through
    the same code — the cap is read once per plan.
    """

    def __init__(self, buckets: ShapeBuckets, tick_s: float,
                 on_expired: Optional[Callable[[ServeRequest], None]]
                 = None,
                 max_batch: Optional[int] = None,
                 cv: Optional[threading.Condition] = None):
        self.buckets = buckets
        self.tick_s = max(float(tick_s), 0.0)
        self._on_expired = on_expired
        self._max_batch = int(max_batch or buckets.max_batch)
        if self._max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got "
                             f"{self._max_batch}")
        self._cv = cv if cv is not None else threading.Condition()
        self._pending: List[ServeRequest] = []
        self._seq = 0
        self._batch_id = 0
        # counters (plane stats / hvd_serve_* families)
        self.submitted = 0
        self.requeued = 0
        self.expired = 0
        self.batches_planned = 0

    def set_max_batch(self, max_batch: int):
        if max_batch < 1 or max_batch > self.buckets.max_batch:
            raise ValueError(
                f"max_batch must be in [1, {self.buckets.max_batch}], "
                f"got {max_batch}")
        with self._cv:
            self._max_batch = int(max_batch)

    # -- submission ---------------------------------------------------------
    def submit(self, req: ServeRequest):
        """Admit one request (seq-bucket overflow raises ValueError —
        the caller rejects the request, never grows the shape set)."""
        req.seq_bucket = self.buckets.seq_bucket(int(req.tokens.size))
        with self._cv:
            req.seq = self._seq
            self._seq += 1
            self._pending.append(req)
            self.submitted += 1
            self._cv.notify_all()

    def requeue(self, requests: Sequence[ServeRequest]):
        """Return dispatched-but-unserved requests to the queue (worker
        loss / elastic re-form).  They keep their original admission
        ordinal, so the planner's FIFO order puts them back at the
        FRONT of their shape class — re-queued, not demoted."""
        if not requests:
            return
        with self._cv:
            self._pending.extend(requests)
            self.requeued += len(requests)
            self._cv.notify_all()

    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def has_pending(self) -> bool:
        with self._cv:
            return bool(self._pending)

    # -- planning -----------------------------------------------------------
    def _plan(self, pending: List[ServeRequest]) -> List[List[int]]:
        """The engine's planner over the pending requests.

        One unit-sized allreduce-shaped EntrySig per request: the seq
        bucket rides ``layer`` (entries with different layer keys never
        fuse — the same never-mix-shapes property the overlapped
        dispatch path bought with it), the zero-padded admission
        ordinal rides ``name`` (plan_fusion sorts by name within a
        bucket key, so planning order IS arrival order), and the byte
        threshold ``_UNIT_BYTES * max_batch`` caps every batch at
        ``max_batch`` rows.
        """
        entries = [EntrySig(
            name=f"{r.seq:012d}", op_type="allreduce", reduce_op="sum",
            dtype="int32", shape=(1,), process_set_id=0, stacked=False,
            layer=r.seq_bucket) for r in pending]
        return plan_fusion(entries, _UNIT_BYTES * self._max_batch)

    def sweep_expired(self, now: Optional[float] = None) -> int:
        """Fail queued requests whose deadline passed (the plane's
        reaper calls this so deadlines fire even with no worker
        pulling)."""
        now = time.monotonic() if now is None else now
        with self._cv:
            dead = [r for r in self._pending
                    if r.deadline is not None and now > r.deadline]
            if not dead:
                return 0
            dead_ids = {id(r) for r in dead}   # object identity, never
            self._pending = [r for r in self._pending  # ndarray __eq__
                             if id(r) not in dead_ids]
            self.expired += len(dead)
        if self._on_expired is not None:
            for r in dead:
                self._on_expired(r)
        return len(dead)

    def take(self, now: Optional[float] = None) -> Optional[Batch]:
        """Bind and return ONE dispatchable micro-batch, or None.

        Plans the CURRENT pending set and picks, among buckets that are
        full or whose oldest member has aged one tick, the one with the
        oldest member — FIFO across shape classes, so a busy class
        cannot starve a quiet one.  Everything else stays pending and
        re-plans on the next take (late binding)."""
        now = time.monotonic() if now is None else now
        self.sweep_expired(now)
        with self._cv:
            if not self._pending:
                return None
            cap = self._max_batch
            plan = self._plan(self._pending)
            best = None
            best_oldest = None
            for bucket in plan:
                oldest = min(self._pending[i].arrival for i in bucket)
                if len(bucket) < cap and now - oldest < self.tick_s:
                    continue   # partial and still inside its window
                if best is None or oldest < best_oldest:
                    best, best_oldest = bucket, oldest
            if best is None:
                return None
            picked = [self._pending[i] for i in best]
            taken = set(best)
            self._pending = [r for i, r in enumerate(self._pending)
                             if i not in taken]
            self._batch_id += 1
            self.batches_planned += 1
            return Batch(batch_id=self._batch_id,
                         seq_bucket=picked[0].seq_bucket,
                         requests=picked, planned_at=now)

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"submitted": self.submitted,
                    "requeued": self.requeued,
                    "expired": self.expired,
                    "batches_planned": self.batches_planned,
                    "depth": len(self._pending)}
