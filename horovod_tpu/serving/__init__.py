"""hvdserve: the elastic inference serving plane (ROADMAP item 1).

Data-parallel batched inference under the existing control plane: a
driver-side admission queue continuously micro-batches incoming
requests (the engine's ``plan_fusion``/cycle-tick machinery with batch
caps for byte caps and the admission tick for the cycle time —
:mod:`.admission`), pads them to a small fixed set of shape buckets so
steady-state serving never recompiles (:mod:`.shapes`), and hands them
to workers over a ``serve_submit``/``serve_pull``/``serve_push`` RPC
data path on the keep-alive pool (:mod:`.plane`, :mod:`.worker`).
Elastic re-form requeues in-flight requests instead of dropping them,
and per-worker service-time EWMAs rotate chronic stragglers out of the
pull rotation — p99 under churn is the product metric (OptiReduce,
arXiv:2310.06993, applied to serving itself).

Observability: ``hvd_serve_*`` metric families (docs/metrics.md) with
per-worker request-latency histograms merged bucket-wise at the
driver's ``GET /metrics/job``; ``engine.stats()["serving"]``
(docs/observability.md) summarizes whatever serving components live in
this process.  Docs: docs/serving.md; env contract: docs/env.md
``HOROVOD_SERVE_*``; gates: ``tools/bench_serve.py``.

This module stays import-light (``engine.stats()`` probes it on every
call): heavy submodules load lazily via attribute access.
"""

from __future__ import annotations

import threading
from typing import Dict, List

_lock = threading.Lock()
_components: Dict[str, List] = {"plane": [], "worker": []}

__all__ = [
    "AdmissionQueue", "Batch", "BucketedForward", "ServeRequest",
    "ServingPlane", "ServingWorker", "ShapeBucket", "ShapeBuckets",
    "register", "stats", "unregister",
]

_LAZY = {
    "AdmissionQueue": ("admission", "AdmissionQueue"),
    "Batch": ("admission", "Batch"),
    "ServeRequest": ("admission", "ServeRequest"),
    "ServingPlane": ("plane", "ServingPlane"),
    "ServingWorker": ("worker", "ServingWorker"),
    "BucketedForward": ("worker", "BucketedForward"),
    "ShapeBucket": ("shapes", "ShapeBucket"),
    "ShapeBuckets": ("shapes", "ShapeBuckets"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    mod = importlib.import_module(f".{target[0]}", __name__)
    return getattr(mod, target[1])


def register(kind: str, component) -> None:
    """Track a live plane/worker so ``stats()`` (and through it
    ``engine.stats()["serving"]``) can see it."""
    with _lock:
        _components.setdefault(kind, []).append(component)


def unregister(component) -> None:
    with _lock:
        for comps in _components.values():
            if component in comps:
                comps.remove(component)


def stats() -> dict:
    """Serving stats of THIS process: the plane's queue/lease/worker
    view when a driver-side plane lives here, per-worker pull/forward
    counters when serving workers do.  ``{}`` when neither — the shape
    ``engine.stats()`` keys ``"serving"`` on."""
    with _lock:
        planes = list(_components.get("plane", ()))
        workers = list(_components.get("worker", ()))
    out: dict = {}
    if planes:
        out["plane"] = planes[-1].stats()
    if workers:
        out["workers"] = [w.stats() for w in workers]
    return out
