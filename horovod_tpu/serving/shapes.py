"""Bucketed batch/sequence shapes: the no-recompile contract of serving.

A jit'd forward recompiles per input shape, and a recompile in the
serving hot path is a multi-second p99 outlier — worse than any network
tail.  Serving therefore admits only a SMALL FIXED SET of shapes: every
micro-batch is padded up to the smallest ``(batch, seq)`` bucket that
fits, so after one warmup pass over the buckets the XLA compile cache
absorbs every request forever (``hvd_serve_recompiles_total`` staying 0
is a gated invariant of ``tools/bench_serve.py``).

The cost of padding is wasted FLOPs (padding ratio rides
``hvd_serve_batch_fill_ratio``); the buckets are the knob trading that
waste against compile-cache size (``HOROVOD_SERVE_SEQ_BUCKETS``,
``HOROVOD_SERVE_BATCH_BUCKETS`` — docs/env.md).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np


def parse_buckets(spec: str, name: str) -> Tuple[int, ...]:
    """Parse a comma-separated ascending positive int list (the
    HOROVOD_SERVE_*_BUCKETS grammar).  Raises ValueError on anything
    else — a typo'd bucket table must fail at config time, not pad
    every request to a nonsense shape."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            v = int(part)
        except ValueError:
            raise ValueError(
                f"{name} must be comma-separated integers, got "
                f"{spec!r}") from None
        if v <= 0:
            raise ValueError(f"{name} entries must be positive, got {v}")
        if out and v <= out[-1]:
            raise ValueError(
                f"{name} must be strictly ascending, got {spec!r}")
        out.append(v)
    if not out:
        raise ValueError(f"{name} must name at least one bucket, "
                         f"got {spec!r}")
    return tuple(out)


def parse_mp_axes(spec: str) -> Tuple[str, int]:
    """Parse the ``HOROVOD_SERVE_MP_AXES`` grammar: ``""`` (DP-only,
    returns ``("", 1)``) or ``name:degree`` with degree >= 2 — e.g.
    ``model:2``.  One axis for now; the grammar leaves room for a
    comma list when serving grows a second mesh dimension."""
    spec = (spec or "").strip()
    if not spec:
        return "", 1
    if "," in spec:
        raise ValueError(
            f"HOROVOD_SERVE_MP_AXES supports a single axis for now, "
            f"got {spec!r}")
    name, sep, degree = spec.partition(":")
    name = name.strip()
    if not sep or not name:
        raise ValueError(
            f"HOROVOD_SERVE_MP_AXES must be 'name:degree' (e.g. "
            f"'model:2') or empty, got {spec!r}")
    try:
        d = int(degree.strip())
    except ValueError:
        raise ValueError(
            f"HOROVOD_SERVE_MP_AXES degree must be an integer, got "
            f"{spec!r}") from None
    if d < 2:
        raise ValueError(
            f"HOROVOD_SERVE_MP_AXES degree must be >= 2 (omit the "
            f"variable for DP-only serving), got {spec!r}")
    return name, d


@dataclasses.dataclass(frozen=True)
class ShapeBucket:
    """One compiled shape: ``batch`` padded rows of ``seq`` tokens,
    served over an ``mp``-way model-parallel mesh slice (``mp=1`` is
    the single-chip/DP-only case — the default everywhere)."""
    batch: int
    seq: int
    mp: int = 1

    @property
    def key(self) -> str:
        """Bounded metric-label form (``b4xs64``; ``b4xs64xm2`` when
        model-parallel — the unsliced form stays byte-stable so
        existing dashboards keep their labels)."""
        base = f"b{self.batch}xs{self.seq}"
        return base if self.mp == 1 else f"{base}xm{self.mp}"


class ShapeBuckets:
    """The admitted shape set: ``batch_buckets`` x ``seq_buckets``,
    optionally x ``mp_degrees`` — the mesh dimension of the bucket
    table.  The mesh degree is a COMPILE-TIME shape exactly like batch
    and seq: a pmap over a different device count is a different
    executable, so admitting it must be as deliberate as admitting a
    new sequence bucket (``HOROVOD_SERVE_MP_AXES`` — docs/env.md)."""

    def __init__(self, batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 seq_buckets: Sequence[int] = (32, 64, 128),
                 mp_degrees: Sequence[int] = (1,)):
        self.batch_buckets = parse_buckets(
            ",".join(str(b) for b in batch_buckets), "batch buckets")
        self.seq_buckets = parse_buckets(
            ",".join(str(s) for s in seq_buckets), "seq buckets")
        self.mp_degrees = parse_buckets(
            ",".join(str(m) for m in mp_degrees), "mp degrees")

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    @property
    def max_seq(self) -> int:
        return self.seq_buckets[-1]

    @property
    def max_mp(self) -> int:
        return self.mp_degrees[-1]

    def __len__(self) -> int:
        return (len(self.batch_buckets) * len(self.seq_buckets)
                * len(self.mp_degrees))

    def seq_bucket(self, seq_len: int) -> int:
        """Smallest seq bucket holding ``seq_len`` tokens.  Raises on
        overflow: an over-long request is REJECTED at admission (the
        alternative — compiling a fresh shape for it — is exactly the
        recompile tail this module exists to prevent)."""
        for s in self.seq_buckets:
            if seq_len <= s:
                return s
        raise ValueError(
            f"request length {seq_len} exceeds the largest seq bucket "
            f"{self.seq_buckets[-1]}; widen HOROVOD_SERVE_SEQ_BUCKETS")

    def batch_bucket(self, n_rows: int) -> int:
        """Smallest batch bucket holding ``n_rows`` rows (n_rows must
        not exceed the cap — the admission queue's batch cap is
        ``max_batch``)."""
        for b in self.batch_buckets:
            if n_rows <= b:
                return b
        raise ValueError(
            f"batch of {n_rows} exceeds the largest batch bucket "
            f"{self.batch_buckets[-1]} (admission cap bug)")

    def bucket(self, n_rows: int, seq_len: int,
               mp: int = 1) -> ShapeBucket:
        if mp not in self.mp_degrees:
            raise ValueError(
                f"mp degree {mp} not in the admitted mesh dimension "
                f"{self.mp_degrees}; widen HOROVOD_SERVE_MP_AXES")
        return ShapeBucket(self.batch_bucket(n_rows),
                           self.seq_bucket(seq_len), mp)

    def pad_batch(self, rows: Sequence[np.ndarray], seq: int,
                  pad_id: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        """Right-pad ``rows`` (1-D int arrays, each <= ``seq`` long) into
        the ``(batch_bucket(len(rows)), seq)`` shape.  Returns
        ``(tokens [B, seq], lengths [B])`` with pad rows' length 0 rows
        present as all-pad (length clamped to 1 so downstream per-row
        gathers at ``length - 1`` stay in bounds; pad-row outputs are
        discarded by the dispatcher)."""
        b = self.batch_bucket(len(rows))
        tokens = np.full((b, seq), pad_id, dtype=np.int32)
        lengths = np.ones((b,), dtype=np.int32)
        for i, row in enumerate(rows):
            row = np.asarray(row, dtype=np.int32).reshape(-1)
            if row.size > seq:
                raise ValueError(
                    f"row {i} length {row.size} > seq bucket {seq}")
            n = max(int(row.size), 1)
            tokens[i, :row.size] = row
            lengths[i] = n
        return tokens, lengths
