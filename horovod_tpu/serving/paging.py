"""Paged KV cache: block-allocated serving memory with prefix reuse.

The dense serving decode pays ``batch x bucket_max`` KV bytes for every
micro-batch regardless of actual prompt lengths — a 5-token request in
a 128-token bucket holds 128 slots of HBM hostage for its whole decode.
This module prices KV by what rows actually use: a fixed pool of
``block_size``-token blocks (``models/generate.py PagedKVCache``), a
host-side :class:`BlockAllocator` handing block ids to rows, and a
per-row block-index table the device-side forward reads/writes through.
Row ``b`` holds ``ceil((length_b + max_new) / block)`` REAL blocks;
table entries past that point at a shared trash block whose logical
positions exceed every query position the row ever attends.

Prefix reuse: blocks fully covered by a request's PROMPT are immutable
after prefill (decode writes start past the prompt), so the allocator
content-addresses them — a chained digest per block position — and a
request whose prompt head matches a cached chain shares those blocks
instead of allocating fresh ones.  Divergence is copy-on-write at the
first divergent block: since the serving prefill rewrites every private
block wholly from the row's own tokens, the "copy" is free — the
diverging row simply gets a fresh block there (shared blocks receive
only value-identical duplicate writes: same tokens, same absolute
positions, same weights).  Completed requests release their refcounts;
zero-ref prefix blocks stay CACHED (reusable across completed
requests) until allocation pressure evicts them LRU-first — so
:meth:`BlockAllocator.assign` only raises :class:`BlocksExhausted`
when live references truly exceed the pool, which
:class:`PagedDecodeForward`'s constructor sizing guard makes
impossible mid-batch (exhaustion surfaces at admission, never as a
device OOM).

Accounting is EXACT, the ``sharded_tile_layout`` precedent: one block's
bytes are ``pool_nbytes / n_blocks`` with zero remainder, and
``tools/bench_serve.py --paged`` gates the allocator's ledger against
``tree_nbytes`` of the live pool.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .. import metrics as _metrics
from .worker import BucketedForward

# -- metric families (docs/metrics.md; sites guard on _metrics.ACTIVE) --------
_m_kv_blocks = _metrics.gauge(
    "hvd_serve_kv_blocks",
    "Paged-KV pool blocks by state (allocated = live request refs, "
    "cached = zero-ref prefix blocks kept for reuse, free = never "
    "written or evicted)", labels=("state",))
_m_kv_bytes = _metrics.gauge(
    "hvd_serve_kv_bytes",
    "Paged-KV pool bytes: allocated = blocks live requests reference "
    "x exact per-block bytes; capacity = the whole pool",
    labels=("kind",))
_m_kv_reuse = _metrics.counter(
    "hvd_serve_kv_reuse_total",
    "Prompt-head blocks served from the prefix cache instead of a "
    "fresh allocation (the shared-prompt memory win bench_serve's "
    "--paged reuse gate measures)")


class BlocksExhausted(RuntimeError):
    """The pool cannot cover an allocation even after evicting every
    zero-ref cached prefix block.  Admission-level: the caller rejects
    the request; a dispatched batch never sees this (the forward's
    constructor guarantees worst-case batch coverage)."""


def row_blocks(length: int, max_new_tokens: int, block_size: int) -> int:
    """REAL blocks a row of true prompt ``length`` needs to decode
    ``max_new_tokens`` — the per-row paged cost, vs the dense path's
    unconditional ``bucket_max``."""
    return -(-(int(length) + int(max_new_tokens)) // int(block_size))


def kv_block_nbytes(cfg, block_size: int, dtype=None) -> int:
    """Exact bytes of ONE pool block across all layers (k + v)."""
    import jax.numpy as jnp
    itemsize = jnp.dtype(dtype or cfg.dtype).itemsize
    return (2 * cfg.n_layers * int(block_size) * cfg.n_kv_heads
            * cfg.head_dim * itemsize)


def dense_kv_nbytes(cfg, batch: int, max_len: int, dtype=None) -> int:
    """Exact bytes of the dense ``[batch, max_len]`` KV cache the paged
    pool replaces (``init_kv_cache``'s k + v buffers)."""
    import jax.numpy as jnp
    itemsize = jnp.dtype(dtype or cfg.dtype).itemsize
    return (2 * cfg.n_layers * int(batch) * int(max_len)
            * cfg.n_kv_heads * cfg.head_dim * itemsize)


class BlockHandle:
    """One request's block grant: the ordered REAL block ids (logical
    block ``j`` of the row lives in pool block ``blocks[j]``) and how
    many of them came from the prefix cache."""

    __slots__ = ("blocks", "shared")

    def __init__(self, blocks: Tuple[int, ...], shared: int):
        self.blocks = blocks
        self.shared = shared


class BlockAllocator:
    """Host-side pool bookkeeping: refcounts, prefix cache, free list.

    Block 0 is the reserved TRASH block — never granted, the sink for
    pad rows and per-row table tails (garbage lands there; no real
    row's mask ever lets it be read).  All mutable state is guarded by
    ``_lock`` (``stats()`` is read from RPC threads while the worker
    thread assigns/releases).
    """

    def __init__(self, n_blocks: int, block_size: int,
                 block_nbytes: int = 0):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is the reserved trash "
                f"block), got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.block_nbytes = int(block_nbytes)
        self._lock = threading.Lock()
        # pop() takes from the end: keep ids ascending for determinism
        self._free = list(range(self.n_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}
        self._digest_of: Dict[int, bytes] = {}
        self._cache: Dict[bytes, int] = {}
        self._evictable: "OrderedDict[bytes, int]" = OrderedDict()
        self.reuse_hits = 0
        self.fresh = 0
        self.evictions = 0
        self.releases = 0
        self.peak_in_use = 0

    @property
    def capacity(self) -> int:
        """Grantable blocks (the pool minus the trash block)."""
        return self.n_blocks - 1

    def can_admit(self, n_blocks_needed: int) -> bool:
        """Admission guard: can this request EVER be granted?  Cached
        prefix blocks are evictable, so only live references bound an
        allocation — but a request needing more than the whole pool
        must be rejected up front, never retried."""
        return int(n_blocks_needed) <= self.capacity

    def _alloc_one_locked(self) -> int:
        if self._free:
            return self._free.pop()
        if self._evictable:
            digest, blk = self._evictable.popitem(last=False)  # LRU
            del self._cache[digest]
            del self._digest_of[blk]
            self.evictions += 1
            return blk
        raise BlocksExhausted(
            f"paged KV pool exhausted: {len(self._refs)} blocks live "
            f"of {self.capacity} grantable and nothing left to evict")

    def _release_locked(self, blocks):
        for blk in blocks:
            r = self._refs.get(blk, 0) - 1
            if r > 0:
                self._refs[blk] = r
                continue
            self._refs.pop(blk, None)
            digest = self._digest_of.get(blk)
            if digest is not None:
                # cached prefix block: keep the content mapping so an
                # identical future prompt head reuses it (evicted only
                # under allocation pressure, LRU)
                self._evictable[digest] = blk
                self._evictable.move_to_end(digest)
            else:
                self._free.append(blk)

    def assign(self, tokens, n_blocks_needed: int) -> BlockHandle:
        """Grant ``n_blocks_needed`` REAL blocks for a row whose true
        (unpadded) prompt is ``tokens``.  Blocks fully covered by the
        prompt are matched against the prefix cache by chained content
        digest; the rest (the first divergent block, the partial prompt
        tail, the decode tail) are fresh and private.  Atomic: on
        exhaustion every block taken so far is returned before
        :class:`BlocksExhausted` propagates."""
        tokens = np.ascontiguousarray(tokens, dtype=np.int64).reshape(-1)
        n_blocks_needed = int(n_blocks_needed)
        bs = self.block_size
        # only COMPLETE prompt blocks are immutable after prefill
        # (decode writes start at position len(prompt), which lands in
        # the first incomplete block) — those are the shareable ones
        full = min(tokens.size // bs, n_blocks_needed)
        with self._lock:
            taken, shared = [], 0
            fresh_taken = set()
            try:
                digest = b""
                for j in range(full):
                    digest = hashlib.sha1(
                        digest + tokens[j * bs:(j + 1) * bs].tobytes()
                    ).digest()
                    blk = self._cache.get(digest)
                    if blk is not None:
                        self._refs[blk] = self._refs.get(blk, 0) + 1
                        self._evictable.pop(digest, None)
                        shared += 1
                    else:
                        blk = self._alloc_one_locked()
                        self._refs[blk] = 1
                        self._cache[digest] = blk
                        self._digest_of[blk] = digest
                        fresh_taken.add(blk)
                    taken.append(blk)
                for _ in range(n_blocks_needed - full):
                    blk = self._alloc_one_locked()
                    self._refs[blk] = 1
                    fresh_taken.add(blk)
                    taken.append(blk)
            except BlocksExhausted:
                # atomic rollback.  Fresh blocks were NEVER written
                # (prefill runs only after a successful grant), so any
                # digest recorded for them this call must be purged —
                # caching them would hand garbage to a future identical
                # prompt.  Cache-hit blocks just drop the added ref.
                for blk in taken:
                    if blk in fresh_taken:
                        d = self._digest_of.pop(blk, None)
                        if d is not None:
                            self._cache.pop(d, None)
                        self._refs.pop(blk, None)
                        self._free.append(blk)
                    else:
                        self._release_locked([blk])
                raise
            self.fresh += len(fresh_taken)
            self.reuse_hits += shared
            self.peak_in_use = max(self.peak_in_use, len(self._refs))
            return BlockHandle(tuple(taken), shared)

    def release(self, handle: BlockHandle):
        """Request completion: drop one reference per granted block.
        Private blocks return to the free list; prefix blocks move to
        the evictable cache."""
        with self._lock:
            self._release_locked(handle.blocks)
            self.releases += 1

    def stats(self) -> dict:
        with self._lock:
            in_use = len(self._refs)
            return {
                "capacity": self.capacity,
                "block_size": self.block_size,
                "block_nbytes": self.block_nbytes,
                "in_use": in_use,
                "cached": len(self._evictable),
                "free": len(self._free),
                "peak_in_use": self.peak_in_use,
                "reuse_hits": self.reuse_hits,
                "fresh": self.fresh,
                "evictions": self.evictions,
                "releases": self.releases,
                "bytes_in_use": in_use * self.block_nbytes,
                "bytes_capacity": self.capacity * self.block_nbytes,
            }


class PagedDecodeForward(BucketedForward):
    """Bucketed llama decode through a persistent paged KV pool.

    Same serving contract as ``models.llama_decode_forward`` (padded
    ``(tokens, lengths)`` in, ``[B, max_new_tokens]`` ids out, one
    compile per shape bucket) but the cache is a pool that outlives the
    call: real rows get allocator-granted block tables, pad rows and
    table tails point at the trash block, and completed rows release
    their blocks — prefix blocks staying cached for reuse across
    requests.  ``wants_rows`` makes the serving worker pass ``n_rows``
    so pad rows never allocate.

    Sizing guard: the pool must cover the WORST admitted batch
    (``max_batch`` rows of ``max_seq``) so a dispatched batch can never
    exhaust mid-flight — over-long requests were already rejected at
    admission by the seq buckets, making :class:`BlocksExhausted` an
    admission-time error by construction.
    """

    wants_rows = True

    def __init__(self, params, cfg, max_new_tokens: int, buckets,
                 block_size: int = 16, n_blocks: Optional[int] = None,
                 dtype=None):
        import jax.numpy as jnp
        from ..models.generate import (init_paged_kv_cache,
                                       paged_greedy_decode, PagedKVCache)
        if buckets.max_seq + max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"largest seq bucket {buckets.max_seq} + max_new_tokens "
                f"{max_new_tokens} exceeds the model's max_seq_len "
                f"{cfg.max_seq_len}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self._cfg = cfg
        self._new = int(max_new_tokens)
        self._bs = int(block_size)
        worst = buckets.max_batch * row_blocks(buckets.max_seq,
                                              max_new_tokens, block_size)
        min_blocks = 1 + worst   # + the trash block
        if n_blocks is None:
            # default headroom: one worst-case batch again, as prefix
            # cache residency (reuse needs blocks that SURVIVE release)
            n_blocks = min_blocks + worst
        if n_blocks < min_blocks:
            raise ValueError(
                f"n_blocks={n_blocks} cannot cover the worst admitted "
                f"batch ({buckets.max_batch} rows x "
                f"{row_blocks(buckets.max_seq, max_new_tokens, block_size)}"
                f" blocks + 1 trash = {min_blocks}): a dispatched batch "
                f"would OOM — reject at admission instead")
        pool = init_paged_kv_cache(cfg, int(n_blocks), self._bs,
                                   dtype=dtype)
        self._pool = (pool.k, pool.v)
        self.pool_nbytes = int(pool.k.nbytes) + int(pool.v.nbytes)
        blk_bytes, rem = divmod(self.pool_nbytes, int(n_blocks))
        assert rem == 0, (self.pool_nbytes, n_blocks)
        self.allocator = BlockAllocator(int(n_blocks), self._bs,
                                        block_nbytes=blk_bytes)
        self._last: dict = {}

        def fn(tokens, lengths, tables, pk, pv):
            out, pool = paged_greedy_decode(
                params, cfg, tokens, lengths, tables,
                PagedKVCache(pk, pv), max_new_tokens)
            return out, pool.k, pool.v

        # donate the pool buffers: the updated pool reuses their memory
        # (a per-call pool copy would double the paged footprint and
        # void the byte accounting this class exists for)
        super().__init__(fn, buckets, donate_argnums=(3, 4))

    def max_blocks(self, seq: int) -> int:
        """Block-table width for a ``seq``-bucket batch (static per
        bucket: part of the compiled shape)."""
        return row_blocks(seq, self._new, self._bs)

    def __call__(self, tokens: np.ndarray, lengths: np.ndarray,
                 n_rows: Optional[int] = None):
        import jax.numpy as jnp
        shape = tuple(tokens.shape)
        self._check_bucket(shape)
        B, S = shape
        n_rows = B if n_rows is None else int(n_rows)
        M = self.max_blocks(S)
        tables = np.zeros((B, M), np.int32)   # trash block everywhere
        handles = []
        try:
            for i in range(n_rows):
                ln = int(lengths[i])
                need = row_blocks(ln, self._new, self._bs)
                h = self.allocator.assign(
                    np.asarray(tokens[i, :ln]), need)
                handles.append(h)
                tables[i, :need] = h.blocks
        except BlocksExhausted:
            for h in handles:
                self.allocator.release(h)
            raise
        try:
            out, pk, pv = self._run(
                shape, jnp.asarray(tokens, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(tables, jnp.int32), *self._pool)
            self._pool = (pk, pv)
            out = np.asarray(out)
        finally:
            # ledger BEFORE release: the batch's live working set is
            # what the byte gate compares against the dense equivalent
            st = self.allocator.stats()
            self._last = {
                "rows": n_rows,
                "blocks": sum(len(h.blocks) for h in handles),
                "shared": sum(h.shared for h in handles),
                "in_use": st["in_use"],
                "bytes_in_use": st["bytes_in_use"],
            }
            if _metrics.ACTIVE:
                _m_kv_blocks.set(st["in_use"], state="allocated")
                _m_kv_blocks.set(st["cached"], state="cached")
                _m_kv_blocks.set(st["free"], state="free")
                _m_kv_bytes.set(st["bytes_in_use"], kind="allocated")
                _m_kv_bytes.set(st["bytes_capacity"], kind="capacity")
                reused = sum(h.shared for h in handles)
                if reused:
                    _m_kv_reuse.inc(reused)
            for h in handles:
                self.allocator.release(h)
        return out

    def kv_summary(self) -> dict:
        """Compact KV ledger the worker rides along on ``serve_push``
        (surfaces on the plane's ``GET /serve/stats``)."""
        st = self.allocator.stats()
        return {"block_size": st["block_size"],
                "block_nbytes": st["block_nbytes"],
                "in_use": st["in_use"], "cached": st["cached"],
                "free": st["free"], "peak_in_use": st["peak_in_use"],
                "reuse_hits": st["reuse_hits"],
                "bytes_in_use": st["bytes_in_use"],
                "bytes_capacity": st["bytes_capacity"]}

    def stats(self) -> dict:
        out = super().stats()
        out["kv"] = self.allocator.stats()
        out["kv"]["pool_nbytes"] = self.pool_nbytes
        out["kv"]["last"] = dict(self._last)
        return out
