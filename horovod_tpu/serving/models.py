"""Model adapters: the zoo's forwards as bucketed serving functions.

Each builder returns a :class:`~horovod_tpu.serving.worker.
BucketedForward` mapping a padded micro-batch ``(tokens [B, S] int32,
lengths [B] int32)`` to a per-row output array — the one signature the
serving worker speaks:

* ``llama_decode_forward`` — the KV-cache ragged batched greedy decode
  (``models/generate.py batched_greedy_decode``): each row continues
  its own prompt; per-row bit-parity with sequential
  ``greedy_generate`` is the micro-batching correctness floor.
* ``classifier_forward`` — plain forwards (bert, mnist, anything
  ``fn(params, x) -> logits``): rows are flat feature/token vectors,
  output is the argmax label (pad rows discarded by the plane).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from .shapes import ShapeBuckets
from .worker import BucketedForward


def llama_decode_forward(params, cfg, max_new_tokens: int,
                         buckets: ShapeBuckets) -> BucketedForward:
    """Greedy KV-cache decode over a padded ragged micro-batch.

    Output rows are ``[max_new_tokens]`` generated ids.  ``max_len`` is
    derived from the (static) padded seq, so each shape bucket compiles
    exactly one program — prefill + decode scan end to end.
    """
    from ..models.generate import batched_greedy_decode
    if buckets.max_seq + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"largest seq bucket {buckets.max_seq} + max_new_tokens "
            f"{max_new_tokens} exceeds the model's max_seq_len "
            f"{cfg.max_seq_len}")

    def fn(tokens, lengths):
        return batched_greedy_decode(
            params, cfg, tokens, lengths, max_new_tokens,
            max_len=tokens.shape[1] + max_new_tokens)

    return BucketedForward(fn, buckets)


def paged_llama_decode_forward(params, cfg, max_new_tokens: int,
                               buckets: ShapeBuckets,
                               block_size: int = 16,
                               n_blocks=None):
    """:func:`llama_decode_forward` through the paged KV pool: same
    signature and per-row bit-parity, but cache bytes are priced per
    row (``ceil((len+new)/block)`` blocks) instead of bucket-max, with
    prompt-head blocks shared across requests (serving/paging.py)."""
    from .paging import PagedDecodeForward
    return PagedDecodeForward(params, cfg, max_new_tokens, buckets,
                              block_size=block_size, n_blocks=n_blocks)


def mp_llama_decode_forward(params, cfg, max_new_tokens: int,
                            buckets: ShapeBuckets, mp: int = 2,
                            axis: str = "hvd_serve_mp", devices=None):
    """:func:`llama_decode_forward` over a model-parallel mesh slice:
    params rest sharded ``mp``-ways across local devices and are
    spec-gathered inside the forward (serving/worker.py
    MeshSlicedForward) — for models whose replica exceeds one chip."""
    from .worker import MeshSlicedForward
    if buckets.max_seq + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"largest seq bucket {buckets.max_seq} + max_new_tokens "
            f"{max_new_tokens} exceeds the model's max_seq_len "
            f"{cfg.max_seq_len}")
    return MeshSlicedForward(params, cfg, max_new_tokens, buckets,
                             mp=mp, axis=axis, devices=devices)


def classifier_forward(forward: Callable, params,
                       buckets: ShapeBuckets,
                       preprocess: Callable = None) -> BucketedForward:
    """A plain forward (bert/mnist-shaped ``forward(params, x) ->
    logits``) as a serving function: rows are flat inputs, output is
    the ``[B, 1]`` argmax label.  ``preprocess`` maps the int32 token
    batch to the model's input (e.g. reshape/scale image bytes)."""
    import jax.numpy as jnp

    def fn(tokens, lengths):
        x = tokens if preprocess is None else preprocess(tokens)
        logits = forward(params, x)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]

    return BucketedForward(fn, buckets)


def toy_echo_forward(buckets: ShapeBuckets, burn_dim: int = 200,
                     burn_iters: int = 3) -> BucketedForward:
    """Deterministic verification forward for benches and smokes.

    Burns a BATCH-INDEPENDENT matmul chain (``burn_iters`` x
    ``[burn_dim, burn_dim]``) — the CPU stand-in for the per-forward
    fixed cost (weight streaming, kernel dispatch) that real
    accelerator serving amortizes over the batch; a per-row cost would
    make CPU micro-batching pointless and the bench meaningless.  Then
    echoes ``tokens * 2 + 1``: unique payloads round-trip, so a routing
    or requeue bug shows up as a WRONG answer, not just a lost one.
    The burn result is folded in at a scale that truncates to +0 at
    runtime but cannot be simplified away at trace time.
    """
    import jax.numpy as jnp

    def fn(tokens, lengths):
        z = jnp.ones((burn_dim, burn_dim), jnp.float32) \
            * (1.0 + tokens.sum().astype(jnp.float32) * 1e-9)
        for _ in range(burn_iters):
            z = jnp.tanh(z @ z)
        return tokens * 2 + 1 + (z.sum() * 1e-30).astype(jnp.int32)

    return BucketedForward(fn, buckets)


def decode_rows(outputs: np.ndarray, lengths: np.ndarray,
                n_rows: int) -> list:
    """Strip pad rows from a batched output (helper for callers that
    bypass the plane)."""
    return [np.asarray(outputs[i]) for i in range(n_rows)]
