"""Exception types mirroring the reference's error surface.

Reference parity: ``HorovodInternalError`` is raised when a collective fails
(reference: horovod/common/exceptions.py — surfaced from the C++ status in
``horovod/common/operations.cc``); ``HostsUpdatedInterrupt`` is raised when the
elastic driver discovers a membership change (reference:
horovod/runner/elastic/worker.py).  On TPU the analogous events are an
ICI/DCN collective timeout / slice preemption (``HorovodInternalError``) and a
slice-discovery delta (``HostsUpdatedInterrupt``).
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """A collective operation failed (peer died, slice preempted, timeout).

    Elastic training catches this, restores state from the last commit and
    re-initializes the communication layer (see ``horovod_tpu.elastic.run``).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """The elastic driver discovered a host/slice membership change.

    Carries ``skip_sync``: when True the worker set only grew, so current
    state is still consistent and ``state.sync()`` may be skipped.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


class NotInitializedError(HorovodTpuError):
    """An API requiring ``hvd.init()`` was called before initialization."""

    def __init__(self, name: str = "this function"):
        super().__init__(
            f"horovod_tpu has not been initialized; call hvd.init() before "
            f"using {name}."
        )


class StallError(HorovodTpuError):
    """Raised when the stall inspector's shutdown deadline is exceeded."""
