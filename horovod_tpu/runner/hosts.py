"""Host / slot parsing and rank assignment.

Reference parity: ``horovod/runner/launch.py`` ``parse_host_files`` /
``parse_hosts`` and ``horovod/runner/common/util/hosts.py`` — hosts are
given as ``-H host1:slots,host2:slots`` or a ``--hostfile`` with
``hostname slots=N`` lines; ranks are assigned host-major (all of host 0's
slots, then host 1's, ...), which fixes HOROVOD_LOCAL_RANK and
HOROVOD_CROSS_RANK exactly as the reference does.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(spec: str) -> "HostInfo":
        spec = spec.strip()
        if ":" in spec:
            host, slots = spec.rsplit(":", 1)
            return HostInfo(host, int(slots))
        return HostInfo(spec, 1)


def parse_hosts(hosts_arg: str) -> List[HostInfo]:
    """Parse ``-H a:2,b:2`` host list."""
    out = [HostInfo.from_string(h) for h in hosts_arg.split(",") if h.strip()]
    if not out:
        raise ValueError(f"no hosts in {hosts_arg!r}")
    return out


def parse_hostfile(path: str) -> List[HostInfo]:
    """Parse a hostfile of ``hostname slots=N`` (or ``hostname N``) lines."""
    out: List[HostInfo] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            slots = 1
            if len(parts) > 1:
                p = parts[1]
                slots = int(p.split("=", 1)[1]) if p.startswith("slots=") \
                    else int(p)
            out.append(HostInfo(parts[0], slots))
    if not out:
        raise ValueError(f"no hosts found in hostfile {path}")
    return out


@dataclasses.dataclass(frozen=True)
class SlotAssignment:
    """One worker process's identity (the §3.4 env contract values)."""
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int   # index of this worker's host
    cross_size: int   # number of hosts
    hostname: str

    def to_env(self) -> Dict[str, str]:
        return {
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
            "HOROVOD_HOSTNAME": self.hostname,
        }


def assign_slots(hosts: List[HostInfo], np_: int) -> List[SlotAssignment]:
    """Host-major rank assignment over available slots (reference order)."""
    total = sum(h.slots for h in hosts)
    if np_ > total:
        raise ValueError(
            f"requested -np {np_} exceeds {total} available slots on "
            f"{len(hosts)} hosts")
    used: List[HostInfo] = []
    remaining = np_
    for h in hosts:
        if remaining <= 0:
            break
        take = min(h.slots, remaining)
        used.append(HostInfo(h.hostname, take))
        remaining -= take
    out: List[SlotAssignment] = []
    rank = 0
    for cross_rank, h in enumerate(used):
        for local_rank in range(h.slots):
            out.append(SlotAssignment(
                rank=rank, size=np_, local_rank=local_rank,
                local_size=h.slots, cross_rank=cross_rank,
                cross_size=len(used), hostname=h.hostname))
            rank += 1
    return out


def effective_hosts(hosts_arg: Optional[str], hostfile: Optional[str],
                    np_: int) -> List[HostInfo]:
    if hosts_arg and hostfile:
        raise ValueError("use either -H or --hostfile, not both")
    if hosts_arg:
        return parse_hosts(hosts_arg)
    if hostfile:
        return parse_hostfile(hostfile)
    return [HostInfo("localhost", np_)]
