"""Tiny JSON-over-HTTP RPC used by the elastic driver and workers.

Reference parity: ``horovod/runner/http/http_server.py`` (the launcher's
HTTP KV rendezvous store) and ``horovod/runner/common/service/*`` (driver/
task services over sockets).  One mechanism covers both here: a threaded
HTTP server dispatching POSTed JSON bodies to named handlers.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

logger = logging.getLogger("horovod_tpu")


class JsonRpcServer:
    """HTTP server mapping POST /<name> with a JSON body to
    ``handlers[name](payload) -> response dict``."""

    def __init__(self, handlers: Dict[str, Callable],
                 port: int = 0, host: str = "0.0.0.0"):
        self._handlers = dict(handlers)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib API name)
                name = self.path.strip("/")
                fn = outer._handlers.get(name)
                if fn is None:
                    self.send_error(404, f"no handler: {name}")
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    resp = fn(payload) or {}
                    body = json.dumps(resp).encode()
                except Exception as e:  # noqa: BLE001 - report to caller
                    logger.exception("rpc handler %s failed", name)
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def json_request(addr: str, port: int, name: str,
                 payload: Optional[dict] = None,
                 timeout: float = 30.0) -> dict:
    """POST ``payload`` to http://addr:port/<name>; returns the JSON reply."""
    req = urllib.request.Request(
        f"http://{addr}:{port}/{name}",
        data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")
