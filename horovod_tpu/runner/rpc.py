"""Tiny JSON-over-HTTP RPC used by the elastic driver and workers.

Reference parity: ``horovod/runner/http/http_server.py`` (the launcher's
HTTP KV rendezvous store) and ``horovod/runner/common/service/*`` (driver/
task services over sockets).  One mechanism covers both here: a threaded
HTTP server dispatching POSTed JSON bodies to named handlers.

Requests are HMAC-signed with the per-job secret (``secret.py``, parity
with upstream's request signing in ``runner/common/service``): when a
secret is configured — always, under the launcher/elastic driver — the
server rejects unsigned or tampered POSTs with 403 before dispatch.

Failure semantics (docs/elastic.md): ``json_request`` retries transient
transport failures (connection refused/reset, timeouts, 5xx) with
jittered exponential backoff; permanent failures (403/404) surface
immediately.  Non-idempotent calls pass ``idempotent=False`` and carry a
per-call idempotency token the server dedupes, so a retry whose first
attempt *did* reach the handler cannot double-apply (e.g. a FAILURE
report double-counting toward the host blacklist).  Both paths carry
chaos injection points (``rpc.request`` / ``rpc.server``) so fault
schedules can drop/delay/duplicate/5xx any control-plane message
deterministically (docs/env.md "Chaos engineering").
"""

from __future__ import annotations

import http.client
import io
import json
import logging
import os
import random
import threading
import time
import urllib.error
import urllib.request
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from .. import chaos as _chaos
from .. import metrics as _metrics
from . import secret as _secret

logger = logging.getLogger("horovod_tpu")

# -- metric families (docs/metrics.md; sites guard on _metrics.ACTIVE) --------
_m_client_reqs = _metrics.counter(
    "hvd_rpc_client_requests_total",
    "RPC client calls by method and outcome", labels=("method", "outcome"))
_m_client_retries = _metrics.counter(
    "hvd_rpc_client_retries_total",
    "RPC client retry attempts after transient failures",
    labels=("method",))
_m_client_backoff = _metrics.counter(
    "hvd_rpc_client_backoff_seconds_total",
    "Total seconds the RPC client slept in retry backoff",
    labels=("method",))
_m_client_latency = _metrics.histogram(
    "hvd_rpc_request_duration_seconds",
    "RPC client request latency (successful attempt)",
    labels=("method",), lo=-17, hi=6)
_m_server_reqs = _metrics.counter(
    "hvd_rpc_server_requests_total",
    "RPC server POSTs dispatched by method and status",
    labels=("method", "status"))
_m_server_replays = _metrics.counter(
    "hvd_rpc_server_idem_replays_total",
    "Duplicate deliveries answered from the idempotency-token cache")
_m_conn_reuse = _metrics.counter(
    "hvd_rpc_conn_reuse_total",
    "Keep-alive connection pool outcomes per request: hit = reused an "
    "idle socket, miss = dialed fresh, stale = a reused socket had died "
    "and was redialed", labels=("result",))

_ENV = object()  # sentinel: resolve the secret from the environment

# Retry defaults (docs/env.md).  Read per call so tests and operators can
# adjust without reimporting; an env read is one dict lookup.
RETRIES_ENV = "HOROVOD_RPC_RETRIES"
BACKOFF_ENV = "HOROVOD_RPC_BACKOFF_S"
MAX_BACKOFF_ENV = "HOROVOD_RPC_MAX_BACKOFF_S"
KEEPALIVE_ENV = "HOROVOD_RPC_KEEPALIVE"

#: Idempotency-token replies remembered per server (LRU).
_IDEM_CACHE_SIZE = 4096

_jitter = random.Random()


def _default_retries() -> int:
    try:
        return int(os.environ.get(RETRIES_ENV, "3"))
    except ValueError:
        return 3


def _default_backoff() -> float:
    try:
        return float(os.environ.get(BACKOFF_ENV, "0.1"))
    except ValueError:
        return 0.1


def _default_max_backoff() -> float:
    try:
        return float(os.environ.get(MAX_BACKOFF_ENV, "2.0"))
    except ValueError:
        return 2.0


def jittered_backoff_s(attempt: int, base: float, cap: float,
                       rng: random.Random = _jitter) -> float:
    """Exponential backoff delay for retry ``attempt`` (0-based):
    ``base * 2**attempt`` capped at ``cap``, scaled by a uniform 0.5–1.5
    jitter.  Shared by the RPC client and the controller's KV retry so
    the backoff shape is defined once."""
    return min(cap, base * (2 ** attempt)) * (0.5 + rng.random())


def keepalive_enabled() -> bool:
    """``HOROVOD_RPC_KEEPALIVE`` (default on).  ``0`` restores the
    one-connection-per-request ``urlopen`` transport."""
    return os.environ.get(KEEPALIVE_ENV, "1") != "0"


class ConnectionPool:
    """Thread-safe idle-connection stacks keyed by ``(host, port)``.

    A connection is checked out by exactly one thread at a time (it is
    popped under the lock and only returned after the response body has
    been fully read), so no HTTP pipelining or socket sharing ever
    happens.  Bounded per endpoint: surplus connections returned to a
    full stack are closed instead of pooled, so a burst of concurrent
    callers cannot grow the pool without bound.
    """

    def __init__(self, max_idle_per_host: int = 4):
        self._lock = threading.Lock()
        self._idle: Dict[tuple, list] = {}
        self._max_idle = max_idle_per_host

    def get(self, host: str, port: int):
        """An idle connection for the endpoint, or None (dial fresh)."""
        with self._lock:
            stack = self._idle.get((host, port))
            return stack.pop() if stack else None

    def put(self, host: str, port: int, conn) -> None:
        with self._lock:
            stack = self._idle.setdefault((host, port), [])
            if len(stack) < self._max_idle:
                stack.append(conn)
                return
        conn.close()  # pool full: close outside the lock

    def clear(self) -> None:
        """Close every idle connection (tests / interpreter teardown)."""
        with self._lock:
            conns = [c for stack in self._idle.values() for c in stack]
            self._idle.clear()
        for c in conns:
            c.close()


_POOL = ConnectionPool()


def _post_pooled(addr: str, port: int, name: str, body: bytes,
                 headers: dict, timeout: float) -> dict:
    """One POST over a pooled keep-alive connection.

    Stale-socket detection: a server restart or idle-timeout close only
    surfaces when the next request hits the dead socket, so a
    CONNECTION-level failure on a REUSED connection is retried once on a
    freshly dialed one (counted ``stale``).  A TIMEOUT is not staleness —
    the server is slow, not gone, and the request may still be executing
    (a parked ``key_value_dir_watch`` in particular), so an eager resend
    would double the caller's wait and burn a second held-watch slot; it
    propagates to ``json_request``'s retry/backoff machinery instead,
    like any failure on a freshly dialed connection.
    """
    went_stale = False
    for reused in (True, False):
        conn = _POOL.get(addr, port) if reused else None
        if reused and conn is None:
            continue  # nothing idle: fall through to the fresh dial
        if conn is None:
            conn = http.client.HTTPConnection(addr, port, timeout=timeout)
        try:
            if conn.sock is not None:  # pooled: refresh the deadline
                conn.sock.settimeout(timeout)
            conn.request("POST", f"/{name}", body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except Exception as e:
            conn.close()
            if reused and not isinstance(e, TimeoutError):
                went_stale = True
                if _metrics.ACTIVE:
                    _m_conn_reuse.inc(result="stale")
                continue  # the socket had died under us: redial once
            raise
        if _metrics.ACTIVE and not went_stale:
            # exactly ONE outcome per request: a stale-then-redialed
            # request already counted as "stale"
            _m_conn_reuse.inc(result="hit" if reused else "miss")
        if resp.will_close:
            conn.close()
        else:
            _POOL.put(addr, port, conn)
        if resp.status >= 400:
            raise urllib.error.HTTPError(
                f"http://{addr}:{port}/{name}", resp.status, resp.reason,
                resp.headers, io.BytesIO(data))
        return json.loads(data or b"{}")
    raise http.client.HTTPException(
        "keep-alive pool exhausted")  # pragma: no cover - loop covers both


class JsonRpcServer:
    """HTTP server mapping POST /<name> with a JSON body to
    ``handlers[name](payload) -> response dict``.

    ``secret`` defaults to the job secret from ``HOROVOD_SECRET_KEY``;
    pass ``None`` explicitly to run unauthenticated (unit tests only).

    Requests carrying an ``_idem`` token (sent by ``json_request(...,
    idempotent=False)``) are deduplicated: a token seen before returns
    the cached reply without re-invoking the handler, so client retries
    of non-idempotent methods are safe.

    GET routes: every server also answers ``GET /metrics`` (Prometheus
    text exposition of the process registry) and ``GET /healthz``
    (JSON liveness) — read-only introspection, served unauthenticated
    because scrapers cannot HMAC-sign (POST dispatch stays signed).
    ``get_routes`` adds/overrides routes; a route is a zero-arg callable
    returning ``(status, content_type, body)``.
    """

    def __init__(self, handlers: Dict[str, Callable],
                 port: int = 0, host: str = "0.0.0.0",
                 secret=_ENV,
                 get_routes: Optional[Dict[str, Callable]] = None):
        self._handlers = dict(handlers)
        self._get_routes = dict(_metrics.get_routes())
        if get_routes:
            self._get_routes.update(get_routes)
        self._secret = (_secret.get_secret_key()
                        if secret is _ENV else secret)
        self._idem: "OrderedDict[str, bytes]" = OrderedDict()
        self._idem_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: HTTP/1.1 persistent connections, so the client
            # pool can reuse one socket across control-plane calls.
            # Every reply path sends Content-Length (send_error included),
            # which 1.1 requires for the connection to stay open.
            protocol_version = "HTTP/1.1"
            # a reply is two small writes (header flush + body); Nagle
            # would hold the second behind the first's ACK, putting a
            # delayed-ack stall on the control plane's wake path
            disable_nagle_algorithm = True

            def _reply(self, body: bytes):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                name = self.path.split("?", 1)[0].strip("/")
                route = outer._get_routes.get(name)
                if route is None:
                    self.send_error(404, f"no GET route: {name}")
                    return
                try:
                    status, ctype, body = route()
                except Exception as e:  # noqa: BLE001 - report to caller
                    logger.exception("GET route %s failed", name)
                    self.send_error(500, str(e))
                    return
                data = (body if isinstance(body, bytes)
                        else body.encode("utf-8"))
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):  # noqa: N802 (stdlib API name)
                name = self.path.strip("/")
                fn = outer._handlers.get(name)
                if fn is None:
                    self.send_error(404, f"no handler: {name}")
                    return
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) or b"{}"
                if outer._secret is not None and not _secret.verify(
                        outer._secret, name, raw,
                        self.headers.get(_secret.SIGNATURE_HEADER),
                        self.headers.get(_secret.TIMESTAMP_HEADER)):
                    logger.warning(
                        "rejected unauthenticated rpc POST /%s", name)
                    self.send_error(
                        403, "missing or invalid request signature")
                    return
                drop_reply = False
                if _chaos.ACTIVE:
                    try:
                        act = _chaos.fire("rpc.server", method=name)
                    except Exception as e:  # noqa: BLE001 - injected 5xx
                        self.send_error(500, f"chaos: {e}")
                        return
                    if act is not None and act.kind == "drop":
                        # lost REQUEST: the handler never runs; the
                        # client sees the connection close with no
                        # status line and retries
                        self.close_connection = True
                        return
                    if act is not None and act.kind == "drop-reply":
                        # lost REPLY: the handler DOES run (and its
                        # reply is cached for an idempotency token), the
                        # connection then closes unanswered — the
                        # faithful simulation of a retry whose first
                        # attempt was applied
                        drop_reply = True
                marker = None
                try:
                    payload = json.loads(raw)
                    idem = (payload.pop("_idem", None)
                            if isinstance(payload, dict) else None)
                    if idem is not None:
                        # claim-or-replay under the lock: a duplicate
                        # arriving while the first delivery's handler is
                        # still running must WAIT for its reply, not
                        # dispatch the handler a second time
                        entry = None
                        with outer._idem_lock:
                            entry = outer._idem.get(idem)
                            if entry is None:
                                marker = threading.Event()
                                outer._idem[idem] = marker
                        if isinstance(entry, bytes):
                            if _metrics.ACTIVE:
                                _m_server_replays.inc()
                                _m_server_reqs.inc(method=name,
                                                   status="replay")
                            self._reply(entry)
                            return
                        if entry is not None:      # in flight elsewhere
                            entry.wait(70.0)
                            with outer._idem_lock:
                                done = outer._idem.get(idem)
                            if isinstance(done, bytes):
                                if _metrics.ACTIVE:
                                    _m_server_replays.inc()
                                    _m_server_reqs.inc(method=name,
                                                       status="replay")
                                self._reply(done)
                            else:
                                # first delivery failed or is wedged:
                                # tell the client to retry later rather
                                # than double-dispatching
                                self.send_error(
                                    503, "duplicate of an in-flight "
                                         "or failed request; retry")
                            return
                    resp = fn(payload) or {}
                    body = json.dumps(resp).encode()
                    if idem is not None:
                        with outer._idem_lock:
                            outer._idem[idem] = body
                            outer._idem.move_to_end(idem)
                            while len(outer._idem) > _IDEM_CACHE_SIZE:
                                outer._idem.popitem(last=False)
                        marker.set()
                        marker = None
                except Exception as e:  # noqa: BLE001 - report to caller
                    logger.exception("rpc handler %s failed", name)
                    if _metrics.ACTIVE:
                        _m_server_reqs.inc(method=name, status="error")
                    if _metrics.RECORDING:
                        _metrics.event("rpc.handler_failed", method=name,
                                       error=str(e))
                    self.send_error(500, str(e))
                    return
                finally:
                    if marker is not None:   # handler failed: release
                        with outer._idem_lock:
                            if outer._idem.get(idem) is marker:
                                del outer._idem[idem]
                        marker.set()
                if _metrics.ACTIVE:
                    _m_server_reqs.inc(method=name, status="ok")
                if drop_reply:
                    self.close_connection = True
                    return
                self._reply(body)

            def log_message(self, *args):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def add_handlers(self, handlers: Dict[str, Callable]) -> None:
        """Register additional POST handlers on a live server (the
        elastic driver attaches the serving plane's
        ``serve_submit``/``serve_pull``/``serve_push`` data path to its
        already-running control server).  Publication is one dict
        rebind: an in-flight dispatch sees the old table or the new
        one, never a torn state."""
        self._handlers = {**self._handlers, **handlers}

    def add_get_routes(self, routes: Dict[str, Callable]) -> None:
        """Same post-construction registration for GET routes."""
        self._get_routes = {**self._get_routes, **routes}

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _post_once(addr: str, port: int, name: str, body: bytes,
               secret, timeout: float) -> dict:
    headers = {"Content-Type": "application/json"}
    if secret is not None:
        # re-signed per attempt: retries get a fresh timestamp, so a
        # long backoff chain cannot drift past the freshness window
        headers.update(_secret.sign_headers(secret, name, body))
    if keepalive_enabled():
        return _post_pooled(addr, port, name, body, headers, timeout)
    req = urllib.request.Request(
        f"http://{addr}:{port}/{name}", data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def json_request(addr: str, port: int, name: str,
                 payload: Optional[dict] = None,
                 timeout: float = 30.0, secret=_ENV,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None,
                 max_backoff: Optional[float] = None,
                 idempotent: bool = True) -> dict:
    """POST ``payload`` to http://addr:port/<name>; returns the JSON reply.

    The body is HMAC-signed with the job secret when one is configured
    (``HOROVOD_SECRET_KEY``); ``secret=None`` sends unsigned.

    Transient transport failures (connection refused/reset, timeouts,
    HTTP 5xx) are retried up to ``retries`` times (default
    ``HOROVOD_RPC_RETRIES``, 3) with jittered exponential backoff
    (``backoff * 2**attempt``, capped at ``max_backoff``, scaled by a
    uniform 0.5–1.5 jitter).  ``retries=0`` opts out for callers with
    their own poll loop.  Permanent failures (4xx: bad signature,
    unknown endpoint) raise immediately.

    ``idempotent=False`` attaches a per-call idempotency token that
    every retry reuses and the server dedupes — required for methods
    whose double-delivery is not a no-op (failure reports feeding the
    blacklist).  Token dedup also defuses chaos-injected duplicate
    sends (``action=dup``).
    """
    if secret is _ENV:
        secret = _secret.get_secret_key()
    if retries is None:
        retries = _default_retries()
    if backoff is None:
        backoff = _default_backoff()
    if max_backoff is None:
        max_backoff = _default_max_backoff()
    data = dict(payload or {})
    if not idempotent:
        data["_idem"] = uuid.uuid4().hex
    body = json.dumps(data).encode()

    last_exc: Optional[Exception] = None
    for attempt in range(retries + 1):
        try:
            act = None
            if _chaos.ACTIVE:
                act = _chaos.fire("rpc.request", method=name, addr=addr,
                                  port=port, attempt=attempt)
            t0 = time.monotonic()
            reply = _post_once(addr, port, name, body, secret, timeout)
            if act is not None and act.kind == "dup":
                # duplicate delivery: the reply that "counts" is the
                # second — idempotency tokens make both land identically
                reply = _post_once(addr, port, name, body, secret,
                                   timeout)
            if _metrics.ACTIVE:
                _m_client_latency.observe(time.monotonic() - t0,
                                          method=name)
                _m_client_reqs.inc(method=name, outcome="ok")
            return reply
        except urllib.error.HTTPError as e:
            if e.code < 500:
                if _metrics.ACTIVE:
                    _m_client_reqs.inc(method=name, outcome="permanent")
                raise  # permanent: auth/unknown-endpoint; retry is futile
            last_exc = e
        except (urllib.error.URLError, OSError,
                http.client.HTTPException,
                _chaos.ChaosError) as e:
            # ChaosError: an injected generic fault at this site is
            # transient by definition — the retry path must absorb it
            # like the transport faults it stands in for
            last_exc = e
        if attempt >= retries:
            if _metrics.ACTIVE:
                _m_client_reqs.inc(method=name, outcome="exhausted")
            if _metrics.RECORDING:
                _metrics.event("rpc.failed", method=name, addr=addr,
                               port=port, attempts=attempt + 1,
                               error=str(last_exc))
            raise last_exc
        delay = jittered_backoff_s(attempt, backoff, max_backoff)
        if _metrics.ACTIVE:
            _m_client_retries.inc(method=name)
            _m_client_backoff.inc(delay, method=name)
        if _metrics.RECORDING:
            _metrics.event("rpc.retry", method=name, addr=addr,
                           port=port, attempt=attempt + 1,
                           error=str(last_exc))
        logger.debug("rpc %s to %s:%d failed (%s); retry %d/%d in %.2fs",
                     name, addr, port, last_exc, attempt + 1, retries,
                     delay)
        time.sleep(delay)
    raise last_exc  # pragma: no cover - loop always returns or raises
