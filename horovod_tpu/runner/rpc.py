"""Tiny JSON-over-HTTP RPC used by the elastic driver and workers.

Reference parity: ``horovod/runner/http/http_server.py`` (the launcher's
HTTP KV rendezvous store) and ``horovod/runner/common/service/*`` (driver/
task services over sockets).  One mechanism covers both here: a threaded
HTTP server dispatching POSTed JSON bodies to named handlers.

Requests are HMAC-signed with the per-job secret (``secret.py``, parity
with upstream's request signing in ``runner/common/service``): when a
secret is configured — always, under the launcher/elastic driver — the
server rejects unsigned or tampered POSTs with 403 before dispatch.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from . import secret as _secret

logger = logging.getLogger("horovod_tpu")

_ENV = object()  # sentinel: resolve the secret from the environment


class JsonRpcServer:
    """HTTP server mapping POST /<name> with a JSON body to
    ``handlers[name](payload) -> response dict``.

    ``secret`` defaults to the job secret from ``HOROVOD_SECRET_KEY``;
    pass ``None`` explicitly to run unauthenticated (unit tests only).
    """

    def __init__(self, handlers: Dict[str, Callable],
                 port: int = 0, host: str = "0.0.0.0",
                 secret=_ENV):
        self._handlers = dict(handlers)
        self._secret = (_secret.get_secret_key()
                        if secret is _ENV else secret)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib API name)
                name = self.path.strip("/")
                fn = outer._handlers.get(name)
                if fn is None:
                    self.send_error(404, f"no handler: {name}")
                    return
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) or b"{}"
                if outer._secret is not None and not _secret.verify(
                        outer._secret, name, raw,
                        self.headers.get(_secret.SIGNATURE_HEADER),
                        self.headers.get(_secret.TIMESTAMP_HEADER)):
                    logger.warning(
                        "rejected unauthenticated rpc POST /%s", name)
                    self.send_error(
                        403, "missing or invalid request signature")
                    return
                try:
                    payload = json.loads(raw)
                    resp = fn(payload) or {}
                    body = json.dumps(resp).encode()
                except Exception as e:  # noqa: BLE001 - report to caller
                    logger.exception("rpc handler %s failed", name)
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def json_request(addr: str, port: int, name: str,
                 payload: Optional[dict] = None,
                 timeout: float = 30.0, secret=_ENV) -> dict:
    """POST ``payload`` to http://addr:port/<name>; returns the JSON reply.

    The body is HMAC-signed with the job secret when one is configured
    (``HOROVOD_SECRET_KEY``); ``secret=None`` sends unsigned.
    """
    if secret is _ENV:
        secret = _secret.get_secret_key()
    body = json.dumps(payload or {}).encode()
    headers = {"Content-Type": "application/json"}
    if secret is not None:
        headers.update(_secret.sign_headers(secret, name, body))
    req = urllib.request.Request(
        f"http://{addr}:{port}/{name}", data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")
