"""Per-job shared secret for authenticating the control-plane RPC.

Reference parity: ``horovod/runner/common/util/secret.py`` (the launcher
mints one random key per job and every runner/elastic service message is
HMAC-signed with it; unsigned or tampered messages are dropped).  Here the
key travels in the spawn environment as ``HOROVOD_SECRET_KEY`` (hex), each
JSON-RPC request body is signed with HMAC-SHA256, and ``JsonRpcServer``
verifies the signature before dispatching — a stray or malicious POST to
an elastic driver/worker endpoint is rejected with 403 instead of failing
the job or forcing a spurious re-form.

The signature binds the endpoint name and a timestamp along with the body,
so a captured request neither verifies against a different endpoint nor
replays outside the freshness window (``HOROVOD_RPC_TS_TOLERANCE`` seconds,
default 900 — generous for clock skew across hosts).
"""

from __future__ import annotations

import hmac
import hashlib
import os
import secrets as _secrets
import time
from typing import Optional

SECRET_ENV = "HOROVOD_SECRET_KEY"
SIGNATURE_HEADER = "X-Horovod-Signature"
TIMESTAMP_HEADER = "X-Horovod-Timestamp"
TS_TOLERANCE_ENV = "HOROVOD_RPC_TS_TOLERANCE"


def make_secret_key() -> str:
    """Mint a fresh per-job key (hex, 256 bits)."""
    return _secrets.token_hex(32)


def get_secret_key() -> Optional[bytes]:
    """The job's secret from the environment, or None if not configured."""
    key = os.environ.get(SECRET_ENV)
    if not key:
        return None
    return key.encode()


def ts_tolerance() -> float:
    try:
        return float(os.environ.get(TS_TOLERANCE_ENV, "900"))
    except ValueError:
        return 900.0


def sign(secret: bytes, name: str, timestamp: str, body: bytes) -> str:
    msg = name.encode() + b"\n" + timestamp.encode() + b"\n" + body
    return hmac.new(secret, msg, hashlib.sha256).hexdigest()


def sign_headers(secret: bytes, name: str, body: bytes) -> dict:
    """Signature + timestamp headers for one outgoing request."""
    ts = str(int(time.time()))
    return {SIGNATURE_HEADER: sign(secret, name, ts, body),
            TIMESTAMP_HEADER: ts}


def verify(secret: bytes, name: str, body: bytes,
           signature: Optional[str], timestamp: Optional[str]) -> bool:
    if not signature or not timestamp:
        return False
    try:
        skew = abs(time.time() - int(timestamp))
    except ValueError:
        return False
    if skew > ts_tolerance():
        return False
    return hmac.compare_digest(sign(secret, name, timestamp, body),
                               signature)
