"""Routable-address / network-interface selection for the launcher.

Reference parity: ``horovod/runner/util/network.py`` +
``horovodrun --network-interface[s]`` (SURVEY.md §3.4 NIC matching) —
the reference resolves which local interface every host should use for
the rendezvous service instead of trusting ``gethostname()`` to be
routable.  Multi-NIC TPU VMs have the same problem: the hostname can
resolve to a DCN/management address that workers on the data network
cannot reach.

Selection order (:func:`coordinator_addr`):

1. an explicit interface (``--network-interface`` /
   ``HOROVOD_NETWORK_INTERFACE``) → that interface's IPv4;
2. all workers local → ``gethostname()`` (loopback routing is fine);
3. otherwise → the source address the kernel routes toward the first
   REMOTE host (a connected UDP socket performs the route lookup; no
   packet is sent), falling back to ``gethostname()`` when the lookup
   fails (e.g. the host resolves only at the workers).
"""

from __future__ import annotations

import os
import socket
from typing import Dict, Optional, Sequence

ENV_INTERFACE = "HOROVOD_NETWORK_INTERFACE"


def list_interfaces() -> Dict[str, str]:
    """Name → IPv4 for every interface with an address (linux ioctl;
    interfaces without an IPv4 address are omitted)."""
    import fcntl
    import struct
    out: Dict[str, str] = {}
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        for _idx, name in socket.if_nameindex():
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name.encode()[:15]))
                out[name] = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue  # interface has no IPv4 address
    return out


def resolve_interface(name: str) -> str:
    """IPv4 of ``name``, or ValueError listing the usable interfaces."""
    ifaces = list_interfaces()
    try:
        return ifaces[name]
    except KeyError:
        raise ValueError(
            f"network interface {name!r} not found or has no IPv4 "
            f"address; available: {sorted(ifaces)}") from None


def routable_source_addr(remote_host: str, port: int = 1) -> Optional[str]:
    """The local source IP the kernel would route toward ``remote_host``
    (connected-UDP route lookup — nothing is transmitted), or None when
    the host does not resolve/route from here."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((remote_host, port))
            return s.getsockname()[0]
    except OSError:
        return None


def coordinator_addr(hostnames: Sequence[str], is_local,
                     interface: Optional[str] = None) -> str:
    """The address workers should dial for the coordination service.

    The service lives in rank 0's process — on ``hostnames[0]``.  When
    that host is REMOTE, its hostfile name is returned unchanged (the
    user asserted it is reachable by naming it).  When it is THIS
    machine, the selection order from the module docstring picks which
    of the driver's addresses remote workers should dial.

    ``is_local`` is a predicate (``spawn.is_local``); ``interface``
    overrides detection (falls back to the ``HOROVOD_NETWORK_INTERFACE``
    env contract).
    """
    first = hostnames[0]
    if not is_local(first):
        return first
    interface = interface or os.environ.get(ENV_INTERFACE)
    if interface:
        return resolve_interface(interface)
    remotes = [h for h in hostnames if not is_local(h)]
    if not remotes:
        return socket.gethostname()
    src = routable_source_addr(remotes[0])
    return src if src is not None else socket.gethostname()


def local_service_addr(worker_host: str, is_local,
                       interface: Optional[str] = None) -> str:
    """The address a worker on ``worker_host`` should dial to reach a
    service running on THIS machine (elastic driver RPC, notification
    endpoints) — same selection order as :func:`coordinator_addr` with
    the service pinned here."""
    interface = interface or os.environ.get(ENV_INTERFACE)
    if interface:
        return resolve_interface(interface)
    if is_local(worker_host):
        return socket.gethostname()
    src = routable_source_addr(worker_host)
    return src if src is not None else socket.gethostname()
