"""Process launcher (reference: horovod/runner/ — ``horovodrun``).

``hvdrun`` spawns one worker process per slot across hosts, exports the
reference's §3.4 environment contract (HOROVOD_RANK/SIZE/LOCAL_RANK/...,
rendezvous address), and streams rank-prefixed output.  The rendezvous
itself is the JAX coordination service (``jax.distributed.initialize``),
the TPU-native replacement for the reference's Gloo HTTP KV store / mpirun.
"""

from .api import run  # noqa: F401
from .executor import TpuExecutor  # noqa: F401
from .launch import main, parse_args  # noqa: F401
