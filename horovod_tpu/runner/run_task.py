"""Worker-side entry for ``horovod_tpu.runner.run`` (reference:
horovod/runner/run_task.py + task_fn pickling in launch.py ``_run``).

Invoked as ``python -m horovod_tpu.runner.run_task <payload.pkl>
<results_dir>``: loads the pickled (fn, args, kwargs), initializes the
runtime, calls fn, and writes this rank's return value to
``results_dir/rank_<i>.pkl`` for the driver to collect.
"""

from __future__ import annotations

import os
import pickle
import sys


def main(payload_path: str, results_dir: str) -> int:
    # Platform override hook: the axon sitecustomize force-registers the TPU
    # plugin programmatically, so JAX_PLATFORMS in the env is not enough to
    # run CPU-mesh workers (tests, dry runs).  HOROVOD_TPU_FORCE_PLATFORM
    # wins over it because jax.config.update runs after sitecustomize.
    from horovod_tpu.runtime import apply_force_platform
    apply_force_platform()
    with open(payload_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)
    import horovod_tpu as hvd
    hvd.init()
    rank = int(os.environ.get("HOROVOD_RANK", hvd.rank()))
    try:
        result = fn(*args, **kwargs)
    finally:
        hvd.shutdown()
    tmp = os.path.join(results_dir, f".rank_{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(results_dir, f"rank_{rank}.pkl"))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
