"""Worker spawning: command construction + process supervision.

Reference parity: ``horovod/runner/gloo_run.py`` (per-slot worker exec with
the env contract pointing at the rendezvous) and ``mpi_run.py`` (remote
command construction — we assert the *generated command line* in tests the
same way ``test/single/test_run.py`` does).  Remote hosts are reached over
ssh like the reference's bootstrap; localhost workers are plain
subprocesses.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence

from .hosts import SlotAssignment

LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}

SSH_OPTS = ["-o", "StrictHostKeyChecking=no", "-o", "BatchMode=yes"]


def is_local(hostname: str) -> bool:
    import socket
    return (hostname in LOCAL_NAMES
            or hostname == socket.gethostname()
            or hostname == socket.getfqdn())


def worker_env(slot: SlotAssignment, coordinator_addr: str,
               coordinator_port: int,
               base_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The full §3.4 environment contract for one worker."""
    env = dict(base_env if base_env is not None else os.environ)
    env.update(slot.to_env())
    env.update({
        # reference names kept for script compatibility; the address points
        # at the JAX coordination service, not a Gloo store
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": coordinator_addr,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(coordinator_port),
        "HOROVOD_CONTROLLER": "jax",
        "HOROVOD_NUM_PROCESSES": str(slot.size),
        "HOROVOD_PROCESS_ID": str(slot.rank),
    })
    return env


def remote_command(slot: SlotAssignment, command: Sequence[str],
                   env: Dict[str, str], cwd: str) -> List[str]:
    """Build the ssh command line for a remote worker (reference: mpi_run /
    gloo_run remote exec).  Only HOROVOD_*/JAX_/XLA_ vars are forwarded —
    the reference forwards an explicit allowlist via ``-x`` for the same
    reason (remote shells own the rest of their environment)."""
    forwarded = {k: v for k, v in env.items()
                 if k.startswith(("HOROVOD_", "JAX_", "XLA_", "TPU_",
                                  "PYTHONPATH", "LIBTPU"))}
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(forwarded.items()))
    remote = f"cd {shlex.quote(cwd)} && env {exports} " + " ".join(
        shlex.quote(c) for c in command)
    return ["ssh", *SSH_OPTS, slot.hostname, remote]


class WorkerProcess:
    def __init__(self, slot: SlotAssignment, popen: subprocess.Popen):
        self.slot = slot
        self.popen = popen
        self.pump: Optional[threading.Thread] = None


def _pump_output(proc: WorkerProcess, prefix: bool, out_file=None):
    stream = proc.popen.stdout
    tag = f"[{proc.slot.rank}]<{proc.slot.hostname}>"
    for raw in iter(stream.readline, b""):
        line = raw.decode(errors="replace")
        if out_file is not None:
            out_file.write(line)
            out_file.flush()
        else:
            sys.stdout.write(f"{tag}: {line}" if prefix else line)
            sys.stdout.flush()


def spawn_workers(slots: List[SlotAssignment], command: Sequence[str],
                  coordinator_addr: str, coordinator_port: int,
                  prefix_output: bool = True,
                  output_filename: Optional[str] = None,
                  base_env: Optional[Dict[str, str]] = None
                  ) -> List[WorkerProcess]:
    procs: List[WorkerProcess] = []
    cwd = os.getcwd()
    for slot in slots:
        env = worker_env(slot, coordinator_addr, coordinator_port, base_env)
        if is_local(slot.hostname):
            cmd, popen_env = list(command), env
        else:
            cmd, popen_env = remote_command(slot, command, env, cwd), None
        popen = subprocess.Popen(
            cmd, env=popen_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True)
        proc = WorkerProcess(slot, popen)
        out_file = (open(f"{output_filename}.{slot.rank}", "w")
                    if output_filename else None)
        proc.pump = threading.Thread(
            target=_pump_output, args=(proc, prefix_output, out_file),
            daemon=True)
        proc.pump.start()
        procs.append(proc)
    return procs


def wait_workers(procs: List[WorkerProcess],
                 timeout: Optional[float] = None) -> int:
    """Wait for all workers; on first failure terminate the rest.

    Returns the exit code to propagate (0 iff every worker exited 0) —
    the reference's gloo_run semantics.
    """
    exit_code = 0
    pending = list(procs)
    try:
        while pending:
            for p in list(pending):
                try:
                    rc = p.popen.wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    continue
                pending.remove(p)
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    sys.stderr.write(
                        f"hvdrun: worker rank {p.slot.rank} "
                        f"({p.slot.hostname}) exited with {rc}; "
                        f"terminating remaining workers\n")
                    for q in pending:
                        _terminate(q)
    except KeyboardInterrupt:
        for q in pending:
            _terminate(q)
        exit_code = 128 + signal.SIGINT
    for p in procs:
        if p.pump is not None:
            p.pump.join(timeout=2)
    return exit_code


def _terminate(p: WorkerProcess, grace: float = 5.0):
    if p.popen.poll() is not None:
        return
    try:
        os.killpg(p.popen.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    try:
        p.popen.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.popen.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
