"""Worker spawning: command construction + process supervision.

Reference parity: ``horovod/runner/gloo_run.py`` (per-slot worker exec with
the env contract pointing at the rendezvous) and ``mpi_run.py`` (remote
command construction — we assert the *generated command line* in tests the
same way ``test/single/test_run.py`` does).  Remote hosts are reached over
ssh like the reference's bootstrap; localhost workers are plain
subprocesses.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence

from . import secret as _secret
from .hosts import SlotAssignment

LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}

SSH_OPTS = ["-o", "StrictHostKeyChecking=no", "-o", "BatchMode=yes"]


def is_local(hostname: str) -> bool:
    import socket
    return (hostname in LOCAL_NAMES
            or hostname == socket.gethostname()
            or hostname == socket.getfqdn())


def ensure_job_secret(base_env: Optional[Dict[str, str]] = None) -> str:
    """The job's control-plane secret, minting one on first launch.

    Looks in ``base_env`` then ``os.environ``; a freshly minted key is
    published to ``os.environ`` so launcher-side RPC (and later spawns)
    sign with the same key the workers receive.
    """
    key = ((base_env or {}).get(_secret.SECRET_ENV)
           or os.environ.get(_secret.SECRET_ENV))
    if not key:
        key = _secret.make_secret_key()
    os.environ[_secret.SECRET_ENV] = key
    return key


def worker_env(slot: SlotAssignment, coordinator_addr: str,
               coordinator_port: int,
               base_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The full §3.4 environment contract for one worker."""
    env = dict(base_env if base_env is not None else os.environ)
    env.update(slot.to_env())
    env.update({
        # reference names kept for script compatibility; the address points
        # at the JAX coordination service, not a Gloo store
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": coordinator_addr,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(coordinator_port),
        "HOROVOD_CONTROLLER": "jax",
        "HOROVOD_NUM_PROCESSES": str(slot.size),
        "HOROVOD_PROCESS_ID": str(slot.rank),
    })
    return env


def remote_command(slot: SlotAssignment, command: Sequence[str],
                   env: Dict[str, str], cwd: str) -> List[str]:
    """Build the ssh command line for a remote worker (reference: mpi_run /
    gloo_run remote exec).  Only HOROVOD_*/JAX_/XLA_ vars are forwarded —
    the reference forwards an explicit allowlist via ``-x`` for the same
    reason (remote shells own the rest of their environment)."""
    forwarded = {k: v for k, v in env.items()
                 if k.startswith(("HOROVOD_", "JAX_", "XLA_", "TPU_",
                                  "PYTHONPATH", "LIBTPU"))}
    # the job secret must NOT ride the ssh argv (visible in ps/procfs on
    # both hosts); it is delivered on the remote shell's stdin instead —
    # see the `read` prefix below and the stdin write in spawn_workers
    has_secret = forwarded.pop(_secret.SECRET_ENV, None) is not None
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(forwarded.items()))
    remote = f"cd {shlex.quote(cwd)} && env {exports} " + " ".join(
        shlex.quote(c) for c in command)
    if has_secret:
        remote = (f"IFS= read -r {_secret.SECRET_ENV} && "
                  f"export {_secret.SECRET_ENV} && " + remote)
    return ["ssh", *SSH_OPTS, slot.hostname, remote]


class WorkerProcess:
    def __init__(self, slot: SlotAssignment, popen: subprocess.Popen):
        self.slot = slot
        self.popen = popen
        self.pump: Optional[threading.Thread] = None


def _pump_output(proc: WorkerProcess, prefix: bool, out_file=None):
    stream = proc.popen.stdout
    tag = f"[{proc.slot.rank}]<{proc.slot.hostname}>"
    for raw in iter(stream.readline, b""):
        line = raw.decode(errors="replace")
        if out_file is not None:
            out_file.write(line)
            out_file.flush()
        else:
            sys.stdout.write(f"{tag}: {line}" if prefix else line)
            sys.stdout.flush()


def spawn_workers(slots: List[SlotAssignment], command: Sequence[str],
                  coordinator_addr: str, coordinator_port: int,
                  prefix_output: bool = True,
                  output_filename: Optional[str] = None,
                  base_env: Optional[Dict[str, str]] = None,
                  kv_server=None,
                  network_interface: Optional[str] = None
                  ) -> List[WorkerProcess]:
    procs: List[WorkerProcess] = []
    cwd = os.getcwd()
    # one control-plane secret per job (upstream mints in the launcher and
    # distributes via the env): published launcher-side too so this
    # process's RPC signs with the same key the workers verify against
    secret_key = ensure_job_secret(base_env)
    kv_envs: Dict[str, Dict[str, str]] = {}
    if kv_server is not None:
        # advertise the launcher-hosted KV server (runner/kv.py) with the
        # same NIC-aware address selection as the other local services;
        # one lookup per distinct hostname
        from .kv import kv_env_for
        kv_envs = {h: kv_env_for(h, is_local, kv_server,
                                 interface=network_interface)
                   for h in {s.hostname for s in slots}}
    for slot in slots:
        env = worker_env(slot, coordinator_addr, coordinator_port, base_env)
        env.setdefault(_secret.SECRET_ENV, secret_key)
        env.update(kv_envs.get(slot.hostname, {}))
        if is_local(slot.hostname):
            cmd, popen_env, stdin_data = list(command), env, None
        else:
            cmd, popen_env = remote_command(slot, command, env, cwd), None
            # secret via stdin, never argv (see remote_command)
            stdin_data = (env[_secret.SECRET_ENV] + "\n").encode()
        popen = subprocess.Popen(
            cmd, env=popen_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            stdin=subprocess.PIPE if stdin_data else subprocess.DEVNULL,
            start_new_session=True)
        if stdin_data:
            try:
                popen.stdin.write(stdin_data)
                popen.stdin.flush()
            except OSError:
                pass  # worker died at exec; the reaper reports it
            popen.stdin.close()
        proc = WorkerProcess(slot, popen)
        out_file = (open(f"{output_filename}.{slot.rank}", "w")
                    if output_filename else None)
        proc.pump = threading.Thread(
            target=_pump_output, args=(proc, prefix_output, out_file),
            daemon=True)
        proc.pump.start()
        procs.append(proc)
    return procs


def wait_workers(procs: List[WorkerProcess],
                 timeout: Optional[float] = None) -> int:
    """Wait for all workers; on first failure terminate the rest.

    Returns the exit code to propagate (0 iff every worker exited 0) —
    the reference's gloo_run semantics.
    """
    exit_code = 0
    pending = list(procs)
    try:
        while pending:
            for p in list(pending):
                try:
                    rc = p.popen.wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    continue
                pending.remove(p)
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    sys.stderr.write(
                        f"hvdrun: worker rank {p.slot.rank} "
                        f"({p.slot.hostname}) exited with {rc}; "
                        f"terminating remaining workers\n")
                    for q in pending:
                        _terminate(q)
    except KeyboardInterrupt:
        for q in pending:
            _terminate(q)
        exit_code = 128 + signal.SIGINT
    for p in procs:
        if p.pump is not None:
            p.pump.join(timeout=2)
    return exit_code


def _terminate(p: WorkerProcess, grace: float = 5.0):
    if p.popen.poll() is not None:
        return
    try:
        os.killpg(p.popen.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    try:
        p.popen.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.popen.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
