"""``hvdrun`` CLI (reference: ``horovodrun``, horovod/runner/launch.py §3.4).

Flags mirror the reference where the concept survives on TPU: ``-np``,
``-H``/``--hostfile``, ``--output-filename``, ``--verbose``,
``--start-timeout``, ``--disable-cache`` analogs via env.  MPI/Gloo
selection flags are gone: the rendezvous is always the JAX coordination
service.  Elastic flags (``--min-np``/``--max-np``/
``--host-discovery-script``) hand off to the elastic driver.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import spawn
from .hosts import assign_slots, effective_hosts

DEFAULT_PORT = 29410


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu job across hosts/slots "
                    "(TPU-native horovodrun).")
    p.add_argument("-np", "--num-proc", dest="np", type=int, default=None,
                   help="total number of worker processes")
    p.add_argument("-H", "--hosts", dest="hosts", default=None,
                   help="comma-separated host:slots list, e.g. a:4,b:4")
    p.add_argument("--hostfile", default=None,
                   help="hostfile with 'hostname slots=N' lines")
    p.add_argument("-p", "--port", type=int, default=DEFAULT_PORT,
                   help="coordination-service port on the first host")
    p.add_argument("--output-filename", default=None,
                   help="redirect each worker's output to FILE.<rank>")
    p.add_argument("--no-prefix-output", action="store_true",
                   help="do not prefix worker output with [rank]<host>")
    p.add_argument("--start-timeout", type=float, default=600.0,
                   help="seconds to wait for the job to finish rendezvous")
    p.add_argument("--network-interface", default=None,
                   help="local interface whose address remote workers "
                        "dial for the coordination service (reference: "
                        "horovodrun --network-interface; default: "
                        "HOROVOD_NETWORK_INTERFACE env, else the "
                        "route toward the first remote host)")
    p.add_argument("--verbose", "-v", action="store_true")
    # elastic (reference: --min-np/--max-np/--host-discovery-script)
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None,
                   help="executable printing current 'host:slots' lines; "
                        "enables elastic mode")
    p.add_argument("--check-build", action="store_true",
                   help="print framework/feature availability and exit "
                        "(reference: horovodrun --check-build)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="the training command, e.g. python train.py")
    args = p.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.check_build:
        return args
    if not args.command:
        p.error("no command given")
    if args.np is None and not args.host_discovery_script:
        p.error("-np is required (or use --host-discovery-script)")
    return args


def check_build(out=None) -> int:
    """Print the feature matrix (reference: ``horovodrun --check-build``
    lists built frameworks/controllers/ops)."""
    out = out or sys.stdout

    def probe(fn):
        try:
            return bool(fn())
        except Exception:  # noqa: BLE001 - availability probe
            return False

    def has_module(name):
        import importlib.util
        return importlib.util.find_spec(name) is not None

    def native_built():
        from ..native import loader
        # report-only: never kick off a compile from a status command
        return loader.load(auto_build=False) is not None

    def flash_ok():
        from jax.experimental import pallas  # noqa: F401
        return True

    def _tf_bridge_built():
        # report-only: a built artifact on disk, no compile kicked off
        import horovod_tpu.tensorflow._xla_bridge as bridge
        return os.path.exists(bridge._OUT)

    import horovod_tpu
    checks = [
        ("JAX (XLA collectives data plane)", lambda: has_module("jax")),
        ("Torch adapter", lambda: has_module("torch")),
        ("TensorFlow adapter", lambda: has_module("tensorflow")),
        ("Keras callbacks", lambda: has_module("tensorflow")),
        ("MXNet adapter", lambda: has_module("mxnet")),
        ("Native C++ core (_hvd_core)", native_built),
        ("TF XLA op bridge (jit_compile collectives)", _tf_bridge_built),
        ("Pallas kernels (flash attention, fused xent)", flash_ok),
        ("Elastic training", lambda: has_module("horovod_tpu.elastic")),
        ("Estimators (Torch/Keras)",
         lambda: has_module("horovod_tpu.estimator")),
        ("Lightning estimator", lambda: has_module("lightning")
         or has_module("pytorch_lightning")),
    ]
    print(f"horovod_tpu v{horovod_tpu.__version__}:", file=out)
    print("\nAvailable features:", file=out)
    for name, fn in checks:
        mark = "X" if probe(fn) else " "
        print(f"    [{mark}] {name}", file=out)
    return 0


def _coordinator_addr(hosts, interface: Optional[str] = None) -> str:
    from .network import coordinator_addr
    return coordinator_addr([h.hostname for h in hosts], spawn.is_local,
                            interface=interface)


def run_launcher(args: argparse.Namespace) -> int:
    if args.check_build:
        return check_build()
    if args.host_discovery_script:
        from ..elastic.driver import run_elastic_launcher
        return run_elastic_launcher(args)
    hosts = effective_hosts(args.hosts, args.hostfile, args.np)
    slots = assign_slots(hosts, args.np)
    addr = _coordinator_addr(hosts, args.network_interface)
    if args.verbose:
        for s in slots:
            print(f"hvdrun: rank {s.rank} -> {s.hostname} "
                  f"(local {s.local_rank}/{s.local_size})", file=sys.stderr)
        print(f"hvdrun: coordinator {addr}:{args.port}", file=sys.stderr)
    # interface-aware KV advertisement matches the coordinator address
    # above; hosted_kv mints the job secret before the server binds
    from . import kv as _kv
    with _kv.hosted_kv(expected_procs=len(slots)) as kv_server:
        procs = spawn.spawn_workers(
            slots, args.command, addr, args.port,
            prefix_output=not args.no_prefix_output,
            output_filename=args.output_filename,
            base_env=dict(os.environ), kv_server=kv_server,
            network_interface=args.network_interface)
        return spawn.wait_workers(procs, timeout=args.start_timeout)


def main(argv: Optional[List[str]] = None) -> int:
    return run_launcher(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
