"""``python -m horovod_tpu.runner`` == ``hvdrun``."""

import sys

from .launch import main

if __name__ == "__main__":
    sys.exit(main())
