"""TpuExecutor: a persistent worker-pool execution API (L5 tier).

Reference parity: ``horovod.ray.RayExecutor`` (SURVEY.md §2.2, L5) — the
cluster-integration capability class: start a pool of workers once
(placement-group actors in the reference; runtime-initialized processes
here), ``run()`` arbitrary functions on all of them repeatedly without
re-paying rendezvous/compile setup, then ``shutdown()``.

TPU-native redesign: workers are spawned through the same launcher
substrate as ``hvdrun`` (ssh/local, coordination-service rendezvous) and
keep their JAX runtime + compiled-kernel caches alive between calls —
the property that makes an executor worth having on TPU, where first
compiles are expensive.  Task distribution uses a shared control
directory (localhost or shared filesystem; the reference delegates the
equivalent plumbing to Ray's object store).
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
import time
from typing import Any, Callable, List, Optional

try:  # serialize __main__-defined functions by value (Ray ergonomics)
    import cloudpickle as _fn_pickle
except ImportError:  # pragma: no cover - cloudpickle ships with the image
    _fn_pickle = pickle

from . import spawn
from .hosts import assign_slots, effective_hosts
from .launch import DEFAULT_PORT, _coordinator_addr

_POLL_S = 0.05


class TpuExecutor:
    """Persistent pool of runtime-initialized workers.

    Usage (reference: RayExecutor)::

        ex = TpuExecutor(np=4)
        ex.start()
        results = ex.run(train_fn, args=(cfg,))   # list, one per rank
        more    = ex.run(eval_fn)                 # same workers, warm
        ex.shutdown()
    """

    def __init__(self, np: int = 1, hosts: Optional[str] = None,
                 hostfile: Optional[str] = None, port: int = DEFAULT_PORT,
                 env: Optional[dict] = None, verbose: bool = False):
        self.np = np
        self._hosts = hosts
        self._hostfile = hostfile
        self._port = port
        self._env = env or {}
        self._verbose = verbose
        self._procs = None
        self._tmp = None
        self._control_dir = None
        self._seq = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self, timeout_s: float = 120.0):
        """Spawn the worker pool and wait until every worker is ready
        (runtime initialized, task loop entered)."""
        if self._procs is not None:
            raise RuntimeError("executor already started")
        host_list = effective_hosts(self._hosts, self._hostfile, self.np)
        slots = assign_slots(host_list, self.np)
        addr = _coordinator_addr(host_list)
        self._tmp = tempfile.TemporaryDirectory(prefix="hvdexec_")
        self._control_dir = self._tmp.name
        command = [sys.executable, "-m",
                   "horovod_tpu.runner.executor_task", self._control_dir]
        base_env = dict(os.environ)
        base_env.update(self._env)
        self._procs = spawn.spawn_workers(
            slots, command, addr, self._port,
            prefix_output=self._verbose, base_env=base_env)
        self._slots = slots
        deadline = time.monotonic() + timeout_s
        try:
            for slot in slots:
                ready = os.path.join(self._control_dir,
                                     f"ready_{slot.rank}")
                while not os.path.exists(ready):
                    self._check_alive()
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"worker rank {slot.rank} not ready within "
                            f"{timeout_s}s")
                    time.sleep(_POLL_S)
        except BaseException:
            # a worker died or timed out during startup: stop the
            # survivors and reclaim the control dir before surfacing
            self.shutdown()
            raise

    def _check_alive(self):
        for p in self._procs or []:
            rc = p.popen.poll()
            if rc is not None and rc != 0:
                raise RuntimeError(
                    f"executor worker rank {p.slot.rank} exited with "
                    f"code {rc}")

    # -- execution -----------------------------------------------------------
    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None,
            timeout_s: float = 600.0) -> List[Any]:
        """Run ``fn(*args, **kwargs)`` on every worker; returns per-rank
        results ordered by rank (reference: RayExecutor.run)."""
        return self.fetch(self.run_remote(fn, args, kwargs), timeout_s)

    execute = run  # reference alias

    def run_remote(self, fn: Callable, args: tuple = (),
                   kwargs: Optional[dict] = None) -> int:
        """Submit without waiting; returns a task id for :meth:`fetch`."""
        if self._procs is None:
            raise RuntimeError("executor not started")
        seq = self._seq
        self._seq += 1
        task_tmp = os.path.join(self._control_dir, f".task_{seq}.tmp")
        with open(task_tmp, "wb") as f:
            _fn_pickle.dump((fn, args, kwargs or {}), f)
        os.replace(task_tmp, os.path.join(self._control_dir,
                                          f"task_{seq}.pkl"))
        return seq

    def fetch(self, task_id: int, timeout_s: float = 600.0) -> List[Any]:
        """Collect the per-rank results of a :meth:`run_remote` task."""
        results: List[Any] = [None] * self.np
        deadline = time.monotonic() + timeout_s
        for slot in self._slots:
            path = os.path.join(self._control_dir,
                                f"result_{task_id}_{slot.rank}.pkl")
            while not os.path.exists(path):
                self._check_alive()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"task {task_id}: no result from rank "
                        f"{slot.rank} within {timeout_s}s")
                time.sleep(_POLL_S)
            with open(path, "rb") as f:
                ok, payload = pickle.load(f)
            if not ok:
                raise RuntimeError(
                    f"task {task_id} failed on rank {slot.rank}:"
                    f"\n{payload}")
            results[slot.rank] = payload
        return results

    # -- teardown ------------------------------------------------------------
    def shutdown(self, timeout_s: float = 30.0):
        """Stop the pool (reference: RayExecutor.shutdown)."""
        if self._procs is None:
            return
        try:
            stop = os.path.join(self._control_dir, "stop")
            with open(stop, "w") as f:
                f.write("1")
            deadline = time.monotonic() + timeout_s
            for p in self._procs:
                while p.popen.poll() is None:
                    if time.monotonic() > deadline:
                        p.popen.terminate()
                        break
                    time.sleep(_POLL_S)
        finally:
            self._procs = None
            if self._tmp is not None:
                self._tmp.cleanup()
                self._tmp = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()
