"""``horovod_tpu.runner.run`` — the in-Python launch API.

Reference parity: ``horovod.run(fn, args=..., np=N, hosts=...)``
(horovod/runner/launch.py ``run`` / ``_run``): pickle a function, launch
``np`` workers that each call it under an initialized runtime, and return
the list of per-rank results ordered by rank.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, List, Optional

try:  # serialize __main__-defined functions by value (reference: horovod
    # uses cloudpickle for run(fn) the same way)
    import cloudpickle as _fn_pickle
except ImportError:  # pragma: no cover
    _fn_pickle = pickle

from . import spawn
from .hosts import assign_slots, effective_hosts
from .launch import DEFAULT_PORT, _coordinator_addr


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        np: int = 1, hosts: Optional[str] = None,
        hostfile: Optional[str] = None, port: int = DEFAULT_PORT,
        env: Optional[dict] = None, verbose: bool = False,
        prefix_output: bool = True) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` workers; returns per-rank
    results ordered by rank.  Raises RuntimeError if any worker fails."""
    kwargs = kwargs or {}
    host_list = effective_hosts(hosts, hostfile, np)
    slots = assign_slots(host_list, np)
    addr = _coordinator_addr(host_list)
    with tempfile.TemporaryDirectory(prefix="hvdrun_") as tmp:
        payload = os.path.join(tmp, "payload.pkl")
        with open(payload, "wb") as f:
            _fn_pickle.dump((fn, args, kwargs), f)
        results_dir = os.path.join(tmp, "results")
        os.makedirs(results_dir)
        command = [sys.executable, "-m", "horovod_tpu.runner.run_task",
                   payload, results_dir]
        base_env = dict(os.environ)
        if env:
            base_env.update(env)
        # event-driven negotiation KV, hosted here for the job's lifetime
        # (workers find it via HOROVOD_KV_ADDR; docs/controller.md
        # "Negotiation transport")
        from . import kv as _kv
        with _kv.hosted_kv(base_env, expected_procs=np) as kv_server:
            procs = spawn.spawn_workers(
                slots, command, addr, port, prefix_output=prefix_output,
                base_env=base_env, kv_server=kv_server)
            rc = spawn.wait_workers(procs)
        if rc != 0:
            raise RuntimeError(f"horovod_tpu.runner.run failed with exit "
                               f"code {rc}")
        results = []
        for slot in slots:
            path = os.path.join(results_dir, f"rank_{slot.rank}.pkl")
            if not os.path.exists(path):
                raise RuntimeError(
                    f"worker rank {slot.rank} exited 0 but wrote no result")
            with open(path, "rb") as f:
                results.append(pickle.load(f))
        return results
