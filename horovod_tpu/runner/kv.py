"""RPC-hosted control-plane key-value store with long-poll watch.

Reference parity: ``horovod/runner/http/http_server.py`` — the
launcher's HTTP KV rendezvous store — upgraded from polled GETs to an
event-driven transport.  The launcher (``hvdrun`` / ``runner.run`` /
the elastic driver) hosts one :class:`KvServer`; workers reach it
through :class:`RpcKvClient`, whose surface is a drop-in superset of
the JAX coordination-service client the negotiation controller was
built on (``key_value_set`` / ``key_value_dir_get`` /
``blocking_key_value_get`` / ``key_value_delete``), plus the one verb
the coordination service lacks: **``key_value_dir_watch``**, a long
poll that the server holds on a :class:`threading.Condition` until the
watched directory's version advances past the caller's known version
(every ``key_value_set`` bumps the version and notifies) or a bounded
deadline expires.  Steady-state negotiation latency then tracks the
network RTT instead of a poll tick (ISSUE 5; the coordination tail of
arXiv:2310.06993).

Wire format: every value is a string (the controller JSON-encodes its
round payloads already); directory listings are ``[key, value]`` pairs
carrying full key paths, matching ``key_value_dir_get`` on the JAX
client.  Watch replies carry a server version cursor the caller passes
back, so a set landing between two watch calls can never be missed.

Held watches are bounded (``HOROVOD_KV_WATCH_SLOTS``): past the limit a
watch degrades to an immediate snapshot (a poll) instead of parking one
more server thread, so watchers cannot starve the RPC thread pool.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import rpc as _rpc

logger = logging.getLogger("horovod_tpu")

#: Launch-contract env: ``host:port`` of the job's KV server.  Presence
#: routes the controller's negotiation transport here (docs/env.md).
KV_ADDR_ENV = "HOROVOD_KV_ADDR"
#: ``0`` disables the long-poll watch verb (client falls back to polled
#: dir-gets — the pre-event-driven transport, kept for A/B benching).
KV_WATCH_ENV = "HOROVOD_KV_WATCH"
#: Server-side bound on one held watch, seconds.
KV_WATCH_DEADLINE_ENV = "HOROVOD_KV_WATCH_DEADLINE_S"
#: Max concurrently HELD watches before degrading to snapshots.
KV_WATCH_SLOTS_ENV = "HOROVOD_KV_WATCH_SLOTS"
#: Root of the controller's negotiation keyspace (ops/controller.py pins
#: the same literal as ``_KEY_PREFIX``; layering keeps the controller
#: from importing the runner at module scope).  The elastic driver
#: subtree-deletes ``{CTL_KEY_PREFIX}/e{N}/`` for epochs whose workers
#: crashed without running ``cleanup_keys()``.
CTL_KEY_PREFIX = "hvdctl"

_DEFAULT_DEADLINE_S = 10.0
# floor for the configured hold: a zero/negative deadline would make
# every unsatisfied watch return an immediate snapshot with held=True —
# the caller's degraded-reply pacing never fires and each waiting gather
# becomes an unpaced tight RPC loop (use HOROVOD_KV_WATCH=0 to disable
# the watch transport; the deadline knob only bounds one hold)
_MIN_DEADLINE_S = 0.05
_DEFAULT_SLOTS = 64


def watch_enabled() -> bool:
    return os.environ.get(KV_WATCH_ENV, "1") != "0"


def watch_deadline_s() -> float:
    try:
        configured = float(os.environ.get(KV_WATCH_DEADLINE_ENV,
                                          str(_DEFAULT_DEADLINE_S)))
    except ValueError:
        return _DEFAULT_DEADLINE_S
    return max(_MIN_DEADLINE_S, configured)


def _watch_slots(default: Optional[int] = None) -> int:
    """The held-watch bound: explicit env wins, then the launcher's
    job-size-derived ``default``, then the module floor."""
    fallback = _DEFAULT_SLOTS if default is None else default
    raw = os.environ.get(KV_WATCH_SLOTS_ENV)
    if raw is None:
        return fallback
    try:
        return int(raw)
    except ValueError:
        return fallback


class KvStore:
    """In-memory versioned KV store with per-directory change signals.

    One global monotonic version stamps every mutation; each directory
    prefix of the mutated key records the stamp (key ``a/b/c`` bumps
    ``a/``, ``a/b/``).  A watch on prefix ``d`` parks on the store's
    Condition until ``dir_version(d)`` exceeds the caller's cursor, so
    wake-ups are edge-triggered per directory and a watcher re-arming
    with the cursor from its last reply can never miss an update.
    """

    def __init__(self, slots: Optional[int] = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data: Dict[str, str] = {}
        self._ver = 0
        self._dir_ver: Dict[str, int] = {}
        # per-key stamps + a per-directory deletion stamp, so a watch may
        # EXCLUDE the caller's own key from its wake predicate (``skip``):
        # publish-then-watch is the controller's round shape, and without
        # the exclusion every first watch would wake on the caller's own
        # publish — one wasted RPC per negotiation round
        self._key_ver: Dict[str, int] = {}
        self._tomb_ver: Dict[str, int] = {}
        # live-key count per directory prefix: O(1) min_entries wake
        # predicate (the controller's steady-state gather re-evaluates
        # it on every store mutation while parked)
        self._dir_count: Dict[str, int] = {}
        self._held = 0
        self._max_held = _watch_slots(slots)
        self._degrade_warned = False

    @staticmethod
    def _dirs_of(key: str) -> List[str]:
        parts = key.split("/")[:-1]
        return ["/".join(parts[:i + 1]) + "/" for i in range(len(parts))]

    def _bump(self, key: str, tomb: bool = False,
              fresh: bool = False) -> None:
        # caller holds self._lock
        self._ver += 1
        for d in self._dirs_of(key):
            self._dir_ver[d] = self._ver
            if tomb:
                self._tomb_ver[d] = self._ver
                self._dir_count[d] -= 1
                if self._dir_count[d] <= 0:
                    del self._dir_count[d]
            elif fresh:
                self._dir_count[d] = self._dir_count.get(d, 0) + 1
        if tomb:
            self._key_ver.pop(key, None)
        else:
            self._key_ver[key] = self._ver
        self._cond.notify_all()
        if len(self._dir_ver) > self._PRUNE_AT:
            self._prune()

    #: Version-map compaction threshold: negotiation rounds mint new
    #: per-seq directory names forever, so the stamp dicts (NOT the key
    #: data — that is cleaned per round) would grow without bound on the
    #: elastic driver's job-lifetime server.
    _PRUNE_AT = 4096

    def _prune(self) -> None:
        # caller holds self._lock.  Drop stamps for directories with no
        # live keys whose last activity is at least a full threshold of
        # versions old.  Safe: parked watchers were notified AT the
        # original mutation; a watcher arriving later with a pre-prune
        # cursor merely waits out its bounded deadline and re-arms with
        # the fresh cursor its (correct, live) snapshot reply carries —
        # no update can be observed wrongly, and a NEW write under a
        # pruned directory recreates its stamp at a higher version than
        # any outstanding cursor, so it wakes watchers as usual.
        floor = self._ver - self._PRUNE_AT
        dead = [d for d, v in self._dir_ver.items()
                if v <= floor and d not in self._dir_count]
        for d in dead:
            del self._dir_ver[d]
            self._tomb_ver.pop(d, None)

    def _dir_changed(self, prefix: str, since: int,
                     skip: Optional[str]) -> bool:
        # caller holds self._lock (dir_watch's wait predicate runs under
        # its Condition, which wraps the lock)
        if skip is None:
            return self._dir_ver.get(prefix, 0) > since
        if self._tomb_ver.get(prefix, 0) > since:
            return True
        return any(v > since for k, v in self._key_ver.items()
                   if k.startswith(prefix) and k != skip)

    # -- mutation ------------------------------------------------------------
    def set(self, key: str, value: str) -> None:
        with self._cond:
            fresh = key not in self._data
            self._data[key] = str(value)
            self._bump(key, fresh=fresh)

    def delete(self, key: str) -> None:
        """Delete ``key``; a trailing ``/`` deletes the whole subtree
        (the JAX client's directory-delete convention the controller's
        namespace cleanup relies on)."""
        with self._cond:
            if key.endswith("/"):
                doomed = [k for k in self._data if k.startswith(key)]
                for k in doomed:
                    del self._data[k]
                for k in doomed:
                    self._bump(k, tomb=True)
            elif key in self._data:
                del self._data[key]
                self._bump(key, tomb=True)

    # -- reads ---------------------------------------------------------------
    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)

    def _snapshot(self, prefix: str) -> List[Tuple[str, str]]:
        # caller holds self._lock
        return sorted((k, v) for k, v in self._data.items()
                      if k.startswith(prefix))

    def dir_get(self, prefix: str) -> Tuple[List[Tuple[str, str]], int]:
        with self._lock:
            return self._snapshot(prefix), self._ver

    def dir_watch(self, prefix: str, since: int, deadline_s: float,
                  extra: Optional[str] = None, skip: Optional[str] = None,
                  min_entries: Optional[int] = None
                  ) -> Tuple[List[Tuple[str, str]], int,
                             List[Tuple[str, str]], bool]:
        """Hold until ``prefix`` (or ``extra``, when given) changes past
        version ``since``, or ``deadline_s`` elapses.

        Returns ``(entries, version_cursor, extra_entries, ok)``.
        ``extra`` is a second directory folded into the same wake
        condition and reply — the controller rides its leave-marker
        directory here, so a departing peer wakes waiting rounds
        immediately instead of at the next bounded marker check.
        ``skip`` names ONE key (the caller's own publish) whose writes
        do not satisfy the wake predicate, so publish-then-watch costs a
        single watch.  ``min_entries`` switches the primary predicate
        from "any change past ``since``" to "at least this many non-skip
        keys under ``prefix``" — a gather that needs all N-1 peers then
        wakes ONCE, at the last arrival, instead of once per peer
        (``extra`` changes still wake it either way).  ``ok=False``
        flags a slot-exhausted degrade to an immediate snapshot, telling
        the caller to pace its retry instead of spinning.
        """
        deadline_s = max(0.0, min(float(deadline_s), 3600.0))
        deadline = time.monotonic() + deadline_s
        with self._cond:
            def changed() -> bool:
                # runs under self._cond == self._lock (wait predicate;
                # re-evaluated by every parked watcher on every store
                # mutation, so it must be O(1): live-key counts come
                # from _dir_count, not a store scan)
                if min_entries is not None:
                    n = self._dir_count.get(prefix, 0)
                    if (skip is not None and skip.startswith(prefix)
                            and skip in self._data):
                        n -= 1
                    if n >= min_entries:
                        return True
                elif self._dir_changed(prefix, since, skip):
                    return True
                return (extra is not None
                        and self._dir_changed(extra, since, None))

            degraded = False
            if not changed():
                if self._held >= self._max_held and not self._degrade_warned:
                    # a silent degrade would quietly cost more than the
                    # polling this transport replaced (the caller paces
                    # snapshot retries at 20 Hz); say so ONCE
                    self._degrade_warned = True
                    logger.warning(
                        "KV watch slots exhausted (%d held); further "
                        "watches degrade to snapshot polling — raise %s "
                        "(launchers default it to 4x the process count)",
                        self._held, KV_WATCH_SLOTS_ENV)
                if self._held < self._max_held:
                    self._held += 1
                    try:
                        while not changed():
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            self._cond.wait(remaining)
                    finally:
                        self._held -= 1
                else:
                    degraded = True
            entries = self._snapshot(prefix)
            extra_entries = ([] if extra is None
                             else self._snapshot(extra))
            return entries, self._ver, extra_entries, not degraded


def kv_handlers(store: KvStore) -> Dict[str, callable]:
    """``JsonRpcServer`` handler table exposing ``store`` (wire format in
    the module docstring).  A missing ``key_value_get`` key answers
    ``{"ok": false}`` — never an error status, so a poll loop's misses
    don't trip the client's retry machinery."""
    def _set(p):
        store.set(p["k"], p["v"])
        return {}

    def _get(p):
        v = store.get(p["k"])
        return {"ok": v is not None, "v": v}

    def _dir_get(p):
        entries, ver = store.dir_get(p["d"])
        return {"e": [[k, v] for k, v in entries], "ver": ver}

    def _delete(p):
        store.delete(p["k"])
        return {}

    def _watch(p):
        min_entries = p.get("min")
        entries, ver, extra, ok = store.dir_watch(
            p["d"], int(p.get("ver", 0)),
            float(p.get("deadline_s", _DEFAULT_DEADLINE_S)),
            extra=p.get("x"), skip=p.get("skip"),
            min_entries=(None if min_entries is None
                         else int(min_entries)))
        return {"e": [[k, v] for k, v in entries], "ver": ver,
                "xe": [[k, v] for k, v in extra], "held": ok}

    return {
        "key_value_set": _set,
        "key_value_get": _get,
        "key_value_dir_get": _dir_get,
        "key_value_delete": _delete,
        "key_value_dir_watch": _watch,
    }


class KvServer:
    """A :class:`KvStore` served over :class:`~.rpc.JsonRpcServer`
    (HMAC-signed POSTs like every other control-plane endpoint)."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 secret=_rpc._ENV, slots: Optional[int] = None):
        self.store = KvStore(slots=slots)
        self._server = _rpc.JsonRpcServer(
            kv_handlers(self.store), port=port, host=host, secret=secret)
        self.port = self._server.port

    def close(self):
        self._server.close()


class RpcKvClient:
    """Client for :class:`KvServer` with the JAX coordination-service
    client's KV surface, plus ``key_value_dir_watch``.

    Every call rides :func:`~.rpc.json_request` — keep-alive pooled
    connections, retry/backoff, HMAC signing, and the ``rpc.request``
    chaos injection site (so fault schedules can drop/delay any verb,
    ``key_value_dir_watch`` included) all compose for free.
    """

    def __init__(self, addr: str, port: int, secret=_rpc._ENV,
                 timeout: float = 30.0):
        self._addr = addr
        self._port = int(port)
        self._secret = secret
        self._timeout = timeout

    def _call(self, name: str, payload: dict, timeout=None, **kw) -> dict:
        return _rpc.json_request(
            self._addr, self._port, name, payload,
            timeout=timeout or self._timeout, secret=self._secret, **kw)

    # -- JAX-client-compatible surface ---------------------------------------
    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = True) -> None:
        # allow_overwrite accepted for signature parity; the store always
        # overwrites, which is the controller's contract (_kv_set)
        self._call("key_value_set", {"k": key, "v": value})

    def key_value_dir_get(self, prefix: str) -> List[Tuple[str, str]]:
        reply = self._call("key_value_dir_get", {"d": prefix})
        return [(k, v) for k, v in reply["e"]]

    def key_value_delete(self, key: str) -> None:
        self._call("key_value_delete", {"k": key})

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        """Block until ``key`` exists (watch-driven when enabled, else a
        bounded poll); raises ``TimeoutError`` at the deadline like the
        coordination client's DEADLINE_EXCEEDED."""
        deadline = time.monotonic() + timeout_ms / 1000.0
        parent = key.rsplit("/", 1)[0] + "/" if "/" in key else ""
        ver = 0
        use_watch = watch_enabled() and bool(parent)
        while True:
            got = self._call("key_value_get", {"k": key})
            if got.get("ok"):
                return got["v"]
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"key {key!r} not set within {timeout_ms} ms")
            if use_watch:
                try:
                    _e, ver, _x, held = self.key_value_dir_watch(
                        parent, ver, min(remaining, watch_deadline_s()))
                    if not held:
                        time.sleep(min(0.05, max(0.0, remaining)))
                except Exception:  # noqa: BLE001 - server lacks watch
                    use_watch = False
            else:
                time.sleep(min(0.05, remaining))

    # -- the event-driven verb -----------------------------------------------
    def key_value_dir_watch(self, prefix: str, since: int,
                            deadline_s: float, extra: Optional[str] = None,
                            skip: Optional[str] = None,
                            min_entries: Optional[int] = None
                            ) -> Tuple[List[Tuple[str, str]], int,
                                       List[Tuple[str, str]], bool]:
        payload = {"d": prefix, "ver": int(since),
                   "deadline_s": float(deadline_s)}
        if extra is not None:
            payload["x"] = extra
        if skip is not None:
            payload["skip"] = skip
        if min_entries is not None:
            payload["min"] = int(min_entries)
        # the RPC timeout must outlive a full server-side hold, or every
        # quiet watch would be misread as a transport failure and retried
        reply = self._call("key_value_dir_watch", payload,
                           timeout=deadline_s + self._timeout)
        return ([(k, v) for k, v in reply["e"]], int(reply["ver"]),
                [(k, v) for k, v in reply.get("xe", [])],
                bool(reply.get("held", True)))


# -- launcher wiring ----------------------------------------------------------

def start_kv_server(base_env: Optional[dict] = None,
                    expected_procs: Optional[int] = None
                    ) -> Optional[KvServer]:
    """Start the job's KV server in the launcher process, unless an outer
    launcher already exported one (``HOROVOD_KV_ADDR`` present in the
    spawn env) — elastic epochs share the driver's single store, and the
    controller's per-incarnation namespaces keep them isolated.

    ``expected_procs`` sizes the held-watch bound (4x the process count,
    floored at the module default): steady state parks ONE watch per
    worker, so the default cap must scale with the job or large jobs
    would silently degrade to snapshot polling.
    """
    env = base_env if base_env is not None else os.environ
    if env.get(KV_ADDR_ENV) or os.environ.get(KV_ADDR_ENV):
        return None
    slots = (None if expected_procs is None
             else max(_DEFAULT_SLOTS, 4 * int(expected_procs)))
    try:
        srv = KvServer(slots=slots)
    except Exception:  # noqa: BLE001 - port exhaustion etc.: workers fall
        # back to the coordination-service transport, nothing breaks
        logger.warning("control-plane KV server failed to start; workers "
                       "will use the coordination-service KV",
                       exc_info=True)
        return None
    logger.debug("control-plane KV server on port %d", srv.port)
    return srv


@contextlib.contextmanager
def hosted_kv(base_env: Optional[dict] = None,
              expected_procs: Optional[int] = None):
    """One launcher-side KV hosting block, shared by every launcher
    (`runner.run`, ``hvdrun``): mint the job secret BEFORE the server
    binds (it resolves its HMAC key at construction), start the server,
    close it when the job ends."""
    from .spawn import ensure_job_secret
    ensure_job_secret(base_env)
    srv = start_kv_server(base_env, expected_procs=expected_procs)
    try:
        yield srv
    finally:
        if srv is not None:
            srv.close()


def kv_env_for(worker_host: str, is_local, kv_server: Optional[KvServer],
               interface: Optional[str] = None) -> Dict[str, str]:
    """The spawn-env entries advertising ``kv_server`` to a worker on
    ``worker_host`` (same reachable-address selection as the elastic
    driver's RPC endpoint)."""
    if kv_server is None:
        return {}
    from .network import local_service_addr
    addr = local_service_addr(worker_host, is_local, interface=interface)
    return {KV_ADDR_ENV: f"{addr}:{kv_server.port}"}
