"""Worker-side loop for :class:`TpuExecutor` (reference: the Ray actor's
``execute`` method body in horovod/ray/runner.py).

Invoked as ``python -m horovod_tpu.runner.executor_task <control_dir>``:
initializes the runtime ONCE, announces readiness, then serves pickled
tasks from the control directory until the stop marker appears — the
JAX runtime and compiled-kernel caches stay warm across tasks.
"""

from __future__ import annotations

import os
import pickle
import sys
import time
import traceback

_POLL_S = 0.05


def main(control_dir: str) -> int:
    from horovod_tpu.runtime import apply_force_platform
    apply_force_platform()
    import horovod_tpu as hvd
    hvd.init()
    rank = int(os.environ.get("HOROVOD_RANK", hvd.rank()))

    ready_tmp = os.path.join(control_dir, f".ready_{rank}.tmp")
    with open(ready_tmp, "w") as f:
        f.write("1")
    os.replace(ready_tmp, os.path.join(control_dir, f"ready_{rank}"))

    seq = 0
    try:
        while True:
            if os.path.exists(os.path.join(control_dir, "stop")):
                return 0
            task = os.path.join(control_dir, f"task_{seq}.pkl")
            if not os.path.exists(task):
                time.sleep(_POLL_S)
                continue
            with open(task, "rb") as f:
                fn, args, kwargs = pickle.load(f)
            try:
                result = (True, fn(*args, **kwargs))
            except Exception:  # noqa: BLE001 - report to the driver
                result = (False, traceback.format_exc())
            tmp = os.path.join(control_dir, f".result_{seq}_{rank}.tmp")
            with open(tmp, "wb") as f:
                pickle.dump(result, f)
            os.replace(tmp, os.path.join(control_dir,
                                         f"result_{seq}_{rank}.pkl"))
            seq += 1
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
