"""Core runtime: initialization, topology, process sets, global state.

Reference parity: this module rebuilds the capability surface of
``horovod/common/operations.cc`` (init/shutdown/rank/size C exports),
``horovod/common/global_state.h`` (HorovodGlobalState) and
``horovod/common/process_set.cc`` (ProcessSet / ProcessSetTable) — see
SURVEY.md §2.1/§3.1 — redesigned for the TPU SPMD model:

* The reference runs **one process per accelerator**; rank == process.  On
  TPU one Python process drives many chips through XLA, so we map Horovod's
  "worker" onto a **chip**: ``size()`` is the number of chips participating
  in collectives (``jax.device_count()``), ``local_size()`` the chips owned
  by this process.  ``rank()`` is the global index of this process's lead
  chip, which preserves the two idioms user scripts rely on:
  ``hvd.rank() == 0`` gates checkpointing exactly on the coordinator
  process, and rank-dependent data sharding maps to per-chip shards.
* The reference's MPI/Gloo rendezvous becomes ``jax.distributed.initialize``
  against the coordination service (over DCN); the background negotiation
  thread lives in ``horovod_tpu.ops.engine``.
* Process sets (subsets of workers with their own communicators) become
  sub-``Mesh``es over device subsets; XLA emits collectives only over the
  sub-mesh's ICI/DCN links.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.extend.backend
import numpy as np

from .config import Config
from .exceptions import NotInitializedError

logger = logging.getLogger("horovod_tpu")

# Reduction op enums, mirroring the reference's hvd.Sum/Average/Adasum/Min/Max
# (horovod/common/common.h ReduceOp + horovod/torch/mpi_ops.py).
class ReduceOp:
    AVERAGE = "average"
    SUM = "sum"
    ADASUM = "adasum"
    MIN = "min"
    MAX = "max"
    PRODUCT = "product"
    BAND = "band"
    BOR = "bor"
    BXOR = "bxor"


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


class ProcessSet:
    """A subset of workers (chips) with its own communicator (sub-mesh).

    Reference parity: ``horovod/common/process_set.cc`` — each ProcessSet had
    its own controller + tensor queue over an MPI sub-communicator.  Here a
    process set owns a 1-D ``jax.sharding.Mesh`` over the selected chips;
    eager collectives over the set are compiled against that mesh, and the
    engine keeps a separate pending-queue per set.

    ``ranks`` are *global worker (chip) indices* into ``hvd.size()``.
    """

    def __init__(self, ranks: Optional[Sequence[int]] = None):
        self.ranks: Optional[List[int]] = (
            sorted(int(r) for r in ranks) if ranks is not None else None)
        self.process_set_id: Optional[int] = None
        self._mesh: Optional[jax.sharding.Mesh] = None
        self._axis: str = "workers"
        self._spans: Optional[bool] = None

    # -- queries -------------------------------------------------------------
    def initialized(self) -> bool:
        return self.process_set_id is not None

    def size(self) -> int:
        self._check()
        return len(self.ranks)

    def included(self) -> bool:
        self._check()
        return _state().lead_worker_rank in self.ranks

    def rank(self) -> int:
        """Rank of this process's lead chip within the set (-1 if excluded)."""
        self._check()
        lead = _state().lead_worker_rank
        return self.ranks.index(lead) if lead in self.ranks else -1

    @property
    def mesh(self) -> jax.sharding.Mesh:
        self._check()
        return self._mesh

    @property
    def axis(self) -> str:
        return self._axis

    @property
    def spans_processes(self) -> bool:
        """True when the set's mesh includes other processes' devices
        (constant per set; computed once — hot-path queried)."""
        if self._spans is None:
            self._check()
            me = jax.process_index()
            self._spans = any(d.process_index != me
                              for d in self._mesh.devices.flat)
        return self._spans

    def hier_shape(self) -> Optional[tuple]:
        """(n_groups, group_size) for hierarchical collectives, or None.

        Reference: NCCLHierarchicalAllreduce's intra-node/inter-node split
        (SURVEY §2.1/§5.8) — on TPU the analog is ICI within a host's
        chips vs DCN across hosts.  Valid when the set's workers group by
        process contiguously with uniform size (TPU slices are).  Cached
        (hot-path queried per dispatch); tests may force a factorization
        by assigning ``_hier_shape``.
        """
        if getattr(self, "_hier_shape", None) is not None:
            return self._hier_shape
        cached = getattr(self, "_hier_cached", False)
        if cached is not False:
            return cached
        self._check()
        self._hier_cached = self._compute_hier_shape()
        return self._hier_cached

    def _compute_hier_shape(self) -> Optional[tuple]:
        procs = [d.process_index for d in self._mesh.devices.flat]
        n = len(procs)
        n_groups = len(set(procs))
        if n_groups <= 1 or n % n_groups:
            return None
        group = n // n_groups
        # contiguous process-major grouping required for the 2-D reshape
        for g in range(n_groups):
            if len({procs[g * group + i] for i in range(group)}) != 1:
                return None
        return (n_groups, group)

    def _check(self):
        if not self.initialized():
            raise NotInitializedError("ProcessSet")

    def _materialize(self, set_id: int, all_devices, axis: str):
        self.process_set_id = set_id
        self._axis = axis
        if self.ranks is None:
            self.ranks = list(range(len(all_devices)))
        if any(r < 0 or r >= len(all_devices) for r in self.ranks):
            raise ValueError(
                f"process set ranks {self.ranks} out of range for "
                f"{len(all_devices)} workers")
        devs = np.array([all_devices[r] for r in self.ranks])
        self._mesh = jax.sharding.Mesh(devs, (axis,))

    def __repr__(self):
        return (f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})")


class ProcessSetTable:
    """Registry of process sets (reference: ProcessSetTable, process_set.cc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[int, ProcessSet] = {}
        self._next_id = 0

    def register(self, ps: ProcessSet, all_devices, axis: str) -> int:
        with self._lock:
            # Duplicate rank-lists map to the existing set, as in the
            # reference's AddProcessSet.
            for existing in self._table.values():
                if existing.ranks == (ps.ranks if ps.ranks is not None
                                      else list(range(len(all_devices)))):
                    raise ValueError(
                        f"A process set with ranks {existing.ranks} already "
                        f"exists (id={existing.process_set_id})")
            set_id = self._next_id
            self._next_id += 1
            ps._materialize(set_id, all_devices, axis)
            self._table[set_id] = ps
            return set_id

    def remove(self, set_id: int):
        with self._lock:
            if set_id == 0:
                raise ValueError("cannot remove the global process set")
            if set_id not in self._table:
                raise ValueError(f"no process set with id {set_id}")
            ps = self._table.pop(set_id)
            ps.process_set_id = None

    def get(self, set_id: int) -> ProcessSet:
        with self._lock:
            return self._table[set_id]

    def ids(self) -> List[int]:
        with self._lock:
            return sorted(self._table)

    def clear(self):
        with self._lock:
            for ps in self._table.values():
                ps.process_set_id = None
            self._table.clear()
            self._next_id = 0


class _RuntimeState:
    """Singleton global state (reference: HorovodGlobalState, global_state.h)."""

    def __init__(self):
        self.initialized = False
        self.config: Optional[Config] = None
        self.devices: List = []
        self.global_mesh: Optional[jax.sharding.Mesh] = None
        self.process_set_table = ProcessSetTable()
        self.global_process_set: Optional[ProcessSet] = None
        self.lead_worker_rank: int = 0
        self.engine = None          # ops.engine.CollectiveEngine
        self.timeline = None        # timeline.Timeline
        self.stall_inspector = None  # stall.StallInspector
        self.autotuner = None       # autotune.ParameterManager
        self.shutdown_hooks: List = []
        self.owns_jax_distributed = False
        self._init_lock = threading.Lock()


_STATE = _RuntimeState()
_INIT_GENERATION = 0  # survives shutdown(); processes re-init in lockstep


def _state() -> _RuntimeState:
    return _STATE


def _require_init() -> _RuntimeState:
    if not _STATE.initialized:
        raise NotInitializedError()
    return _STATE


def apply_force_platform() -> None:
    """Apply ``HOROVOD_TPU_FORCE_PLATFORM`` to the JAX config (CPU-forced
    tests/CI/dev runs).  The TPU sitecustomize overrides JAX_PLATFORMS
    programmatically, so the env var alone is not enough; must run
    before the first backend touch (no-op once a backend exists)."""
    plat = os.environ.get("HOROVOD_TPU_FORCE_PLATFORM")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 - backend already initialized
            pass


def init(comm=None, process_sets: Optional[Sequence[ProcessSet]] = None):
    """Initialize the runtime (reference: horovod_init → InitializeHorovodOnce).

    Resolves topology from the TPU slice / JAX runtime instead of
    MPI_COMM_WORLD:

    * Under the ``hvdrun`` launcher (or any launcher exporting the reference's
      §3.4 env contract: HOROVOD_RANK/SIZE + rendezvous address), calls
      ``jax.distributed.initialize`` so every process joins the coordination
      service and sees the global device set.
    * Stand-alone, uses whatever devices JAX exposes (single host).

    ``comm`` is accepted for API compatibility (the reference takes an MPI
    communicator); only ``None`` (world) is supported.
    ``process_sets`` are additional process sets to create at init, as in the
    reference's ``hvd.init(process_sets=...)``.
    """
    apply_force_platform()
    with _STATE._init_lock:
        if _STATE.initialized:
            return
        if comm is not None:
            raise ValueError(
                "horovod_tpu.init(comm=...) with a custom communicator is not "
                "supported on TPU; use process_sets for sub-groups.")
        cfg = Config.from_env()
        _setup_logging(cfg)

        _STATE.config = cfg

        # Elastic rendezvous retry loop: a worker blocked in a stale
        # epoch's coordination-service barrier (its peers died before
        # joining) must not hang forever — each attempt re-fetches the
        # driver's CURRENT assignment (reference: elastic rendezvous
        # re-query, §3.5), so when the driver bumps the epoch mid-wait
        # the next attempt rendezvouses into the new world.
        start_deadline = time.monotonic() + float(os.environ.get(
            "HOROVOD_ELASTIC_START_TIMEOUT", "600"))
        attempt = 0
        while True:
            if cfg.elastic:
                from .elastic import worker as elastic_worker
                # first attempt wants an epoch newer than the last one this
                # worker saw (request_reform guarantees the bump); retries
                # accept the latest published epoch, whatever it is
                min_ep = (None if attempt == 0
                          else max(elastic_worker._last_epoch, 0))
                asg = elastic_worker.fetch_assignment(min_epoch=min_ep)
                if asg is not None:
                    cfg.rank = asg["rank"]
                    cfg.size = asg["size"]
                    cfg.local_rank = asg["local_rank"]
                    cfg.local_size = asg["local_size"]
                    cfg.cross_rank = asg["cross_rank"]
                    cfg.cross_size = asg["cross_size"]
                    cfg.rendezvous_addr = asg["coordinator_addr"]
                    cfg.rendezvous_port = asg["coordinator_port"]
                    cfg.num_processes = asg["size"]
                    cfg.process_id = asg["rank"]

            # Multi-process rendezvous via the JAX coordination service
            # (the TPU-native replacement for MPI/Gloo rendezvous, SURVEY.md
            # §5.8).  Process count/id resolution: prefer the launcher's
            # explicit HOROVOD_NUM_PROCESSES/PROCESS_ID; fall back to the
            # cross_* vars (one process per host driving all its chips) and
            # finally to rank/size (one process per worker).
            n_procs = cfg.num_processes or cfg.cross_size or cfg.size
            if not (n_procs is not None and n_procs > 1
                    and cfg.rendezvous_addr):
                break  # single-process: nothing to rendezvous
            coordinator = (
                f"{cfg.rendezvous_addr}:{cfg.rendezvous_port or 9999}")
            if cfg.process_id is not None:
                proc_id = cfg.process_id
            elif cfg.num_processes is None and cfg.cross_rank is not None:
                proc_id = cfg.cross_rank
            else:
                proc_id = cfg.rank
            dist_kwargs = {}
            if cfg.elastic:
                # survive peer death instead of LOG(FATAL)-ing: collectives
                # fail with a catchable error (→ HorovodInternalError path)
                # and this process can re-rendezvous at the next epoch
                try:
                    jax.config.update("jax_enable_recoverability", True)
                except Exception:  # noqa: BLE001 - older jax
                    logger.warning("jax recoverability unavailable")
                hb = int(os.environ.get(
                    "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", "10"))
                # init timeout gates EPOCH FORMATION only (post-init
                # death is the heartbeat's job).  Two pressures: it must
                # cover the slowest member's spawn + jax import on an
                # oversubscribed host (30 s is too tight for 3 workers
                # on one core), but a member stuck in RegisterTask is
                # UNINTERRUPTIBLE until this deadline LOG(FATAL)s it —
                # so it must not exceed the driver's start_timeout or
                # stuck members stay a full epoch out of phase with the
                # driver's re-forms.
                dist_kwargs = dict(
                    heartbeat_timeout_seconds=hb,
                    shutdown_timeout_seconds=hb,
                    initialization_timeout=int(os.environ.get(
                        "HOROVOD_ELASTIC_INIT_TIMEOUT", "60")))
            try:
                # a prior solo epoch (job shrunk to 1 process: distributed
                # init skipped) may have lazily created local backends;
                # they must go before the world re-forms
                from jax._src import xla_bridge as _xb
                if _xb.backends_are_initialized():
                    jax.extend.backend.clear_backends()
            except Exception:  # noqa: BLE001 - internal API drift
                logger.debug("pre-init backend clear skipped",
                             exc_info=True)
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=n_procs,
                    process_id=proc_id,
                    **dist_kwargs,
                )
                _STATE.owns_jax_distributed = True
                break
            except Exception as e:  # noqa: BLE001 - barrier timeout /
                # half-dead coordinator; non-elastic jobs fail loudly
                if not cfg.elastic or time.monotonic() > start_deadline:
                    raise
                attempt += 1
                logger.warning(
                    "elastic rendezvous attempt %d failed (%s); "
                    "re-fetching assignment", attempt, e)
                try:
                    jax.distributed.shutdown()
                except Exception:  # noqa: BLE001 - partial init
                    pass

        # Invalidate compiled-kernel caches from a previous incarnation:
        # device ids collide across re-inits but the device objects (and
        # their runtime clients) are new, so stale jitted fns would fail
        # with "incompatible devices".
        from .ops.collectives import reset_kernel_caches
        reset_kernel_caches()

        _STATE.devices = list(jax.devices())
        n = len(_STATE.devices)
        _STATE.global_mesh = jax.sharding.Mesh(
            np.array(_STATE.devices), (cfg.worker_axis,))
        _STATE.lead_worker_rank = (
            jax.process_index() * jax.local_device_count())

        _STATE.process_set_table.clear()
        global_ps = ProcessSet(None)
        _STATE.process_set_table.register(
            global_ps, _STATE.devices, cfg.worker_axis)
        _STATE.global_process_set = global_ps
        if process_sets:
            for ps in process_sets:
                _STATE.process_set_table.register(
                    ps, _STATE.devices, cfg.worker_axis)

        # Observability subsystems.
        from . import metrics as _metrics
        # metrics exposition + flight recorder env contract (SIGUSR1
        # dump handler, HOROVOD_METRICS_DUMP snapshots,
        # HOROVOD_METRICS_PORT scrape server); idempotent across
        # elastic re-inits
        _metrics.init_from_env()
        if _metrics.RECORDING:
            _metrics.event("runtime.init", process=jax.process_index(),
                           processes=jax.process_count())
        from .timeline import Timeline
        from .stall import StallInspector
        _STATE.timeline = Timeline(
            cfg.timeline_path, mark_cycles=cfg.timeline_mark_cycles,
            use_native=cfg.use_native_core)
        # straggler-score -> elastic-blacklist bridge (OptiReduce tail
        # prescription): a host whose EWMA lateness crosses
        # HOROVOD_TAIL_BLACKLIST_SCORE is reported to the elastic
        # driver as a SOFT failure — it feeds the blacklist before the
        # host dies outright.  Best effort and a no-op outside the
        # elastic driver (no endpoint exported).
        def _report_straggler(process, score):
            from .elastic import worker as _ew
            _ew.report_straggler(process, score)

        _STATE.stall_inspector = StallInspector(
            check_time=cfg.stall_check_time,
            shutdown_time=cfg.stall_shutdown_time,
            disabled=cfg.stall_check_disable,
            use_native=cfg.use_native_core,
            blacklist_score=cfg.tail_blacklist_score,
            on_straggler=_report_straggler)

        if cfg.autotune:
            from .autotune import ParameterManager
            # hierarchical collectives need a valid (groups, group_size)
            # factorization of the global set; without one the GP's hier
            # dimension would be inert and waste its sample budget
            _STATE.autotuner = ParameterManager(
                cfg, hier_available=global_ps.hier_shape() is not None)

        # The background collective engine (reference: BackgroundThreadLoop)
        # with its cross-process negotiation controller (controller.cc).
        # Controller keys are namespaced per incarnation so init→shutdown→
        # init against a persistent coordination service never reads the
        # previous incarnation's rounds: elastic re-forms share the
        # driver's epoch; plain re-inits count generations in lockstep.
        global _INIT_GENERATION
        _INIT_GENERATION += 1
        if cfg.elastic:
            from .elastic import worker as elastic_worker
            ns = f"e{max(elastic_worker._last_epoch, 0)}"
        else:
            ns = f"g{_INIT_GENERATION}"
        # distributed-tracing identity/context (tracing/): spans carry
        # this worker's process rank, host, and elastic epoch so the
        # driver's /trace/job merge can assign one pid per host and
        # correlate rounds across incarnations
        from . import tracing as _tracing
        _tracing.init_from_env()
        _tracing.set_identity(
            process=jax.process_index(),
            host=os.environ.get("HOROVOD_HOSTNAME") or None,
            epoch=int(ns[1:]))
        # training-health evaluator identity (health/): verdicts carry
        # this worker's rank/host so the driver's /health/job merge
        # attributes them; history survives elastic re-inits (a
        # post-mortem scrape wants the pre-reform verdicts)
        from . import health as _health
        _health.init_from_env()
        _health.set_identity(
            process=jax.process_index(),
            host=os.environ.get("HOROVOD_HOSTNAME") or None)
        from .ops.controller import Controller
        from .ops.engine import CollectiveEngine
        _STATE.engine = CollectiveEngine(
            cfg, _STATE.global_mesh, _STATE.timeline,
            _STATE.stall_inspector, _STATE.autotuner,
            controller=Controller(cfg, _STATE.stall_inspector,
                                  namespace=ns))
        _STATE.engine.start()

        _STATE.initialized = True
        atexit.register(shutdown)
        if cfg.elastic:
            # rendezvous complete: the driver now counts a death of this
            # worker as a real host failure, not re-rendezvous churn
            from .elastic.worker import record_running
            record_running()
        logger.info(
            "horovod_tpu initialized: %d workers (%d local), process %d/%d",
            n, jax.local_device_count(), jax.process_index(),
            jax.process_count())


def shutdown():
    """Tear down the runtime (reference: horovod_shutdown)."""
    with _STATE._init_lock:
        if not _STATE.initialized:
            return
        try:
            from . import metrics as _metrics
            if _metrics.RECORDING:
                _metrics.event("runtime.shutdown")
            _metrics.stop_exposition()
            if _STATE.engine is not None:
                _STATE.engine.stop()
            if _STATE.timeline is not None:
                _STATE.timeline.close()
            for hook in _STATE.shutdown_hooks:
                try:
                    hook()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    logger.exception("shutdown hook failed")
        finally:
            if _STATE.owns_jax_distributed:
                # With recoverable tasks the default shutdown barrier is
                # skipped, so the leader can tear the coordination service
                # down while peers are still disconnecting (they then die
                # fatally).  Meet at an explicit barrier first, as the
                # coordination service docs prescribe for recoverable mode.
                try:
                    from jax._src import distributed as _dist
                    client = _dist.global_state.client
                    if client is not None and jax.process_count() > 1:
                        client.wait_at_barrier(
                            "horovod_tpu_shutdown",
                            int(float(os.environ.get(
                                "HOROVOD_SHUTDOWN_BARRIER_TIMEOUT",
                                "15")) * 1000))
                        if (jax.process_index() == 0
                                and _STATE.config is not None
                                and _STATE.config.elastic):
                            # the barrier alone is not enough: after it,
                            # the leader's shutdown can still destroy the
                            # coordination service while followers'
                            # disconnect RPCs are in flight — with
                            # recoverable tasks (elastic only) that is a
                            # LOG(FATAL) process death, not a catchable
                            # error, and a re-form degrades to respawns.
                            # Let followers disconnect first.  Non-elastic
                            # jobs keep jax's default shutdown barrier and
                            # need no linger.
                            time.sleep(float(os.environ.get(
                                "HOROVOD_SHUTDOWN_LEADER_LINGER", "1.5")))
                except Exception:  # noqa: BLE001 - peers may be gone
                    logger.debug("shutdown barrier failed", exc_info=True)
                # release the coordination-service connection so an elastic
                # re-init can re-join the (possibly re-formed) cluster
                try:
                    jax.distributed.shutdown()
                except Exception:  # noqa: BLE001 - peer may already be gone
                    logger.warning("jax.distributed.shutdown failed",
                                   exc_info=True)
                # the device clients embed the old distributed world (size,
                # process id); drop them so re-init builds fresh ones.
                # NOTE: live device arrays die with the backends — the
                # elastic run wrapper calls state.evacuate() (snapshot →
                # host) before re-initializing for exactly this reason.
                try:
                    jax.extend.backend.clear_backends()
                except Exception:  # noqa: BLE001 - best effort
                    logger.warning("clear_backends failed", exc_info=True)
                _STATE.owns_jax_distributed = False
            _STATE.initialized = False
            _STATE.engine = None
            _STATE.global_mesh = None
            _STATE.global_process_set = None
            _STATE.process_set_table.clear()


def is_initialized() -> bool:
    """Reference: horovod_is_initialized / hvd.is_initialized()."""
    return _STATE.initialized


def start_timeline(file_path: str, mark_cycles: bool = False):
    """Reference: hvd.start_timeline (horovod/common/basics.py)."""
    st = _require_init()
    st.timeline.reopen(file_path, mark_cycles=mark_cycles)


def stop_timeline():
    st = _require_init()
    st.timeline.close()


def start_profiler(logdir: str):
    """Start a device (XLA/libtpu) trace via ``jax.profiler``.

    The NVTX-integration analog (reference: nvtx_op_range.cc + Nsight):
    while active, the engine's per-dispatch TraceAnnotation ranges land
    in the same Perfetto trace as XLA's collective/kernel spans, giving
    the merged framework+device view SURVEY §5.1 prescribes.  View with
    ``tensorboard --logdir`` or Perfetto.
    """
    _require_init()
    import jax.profiler
    jax.profiler.start_trace(logdir)


def stop_profiler():
    _require_init()
    import jax.profiler
    jax.profiler.stop_trace()


# --- topology accessors (reference: horovod/common/basics.py) ---------------

def size() -> int:
    """Total number of workers (chips) participating in collectives."""
    _require_init()
    return len(_STATE.devices)


def rank() -> int:
    """Global rank of this process's lead worker (chip).

    ``rank() == 0`` is true exactly on the coordinator process, preserving
    the reference's checkpoint-gating idiom.
    """
    _require_init()
    return _STATE.lead_worker_rank


def local_size() -> int:
    """Number of workers (chips) driven by this process."""
    _require_init()
    return jax.local_device_count()


def local_rank() -> int:
    """Rank of the lead worker within this host (0 in SPMD: the process owns
    all its local chips)."""
    _require_init()
    return 0


def cross_size() -> int:
    """Number of processes (hosts) — reference: ranks with my local_rank."""
    _require_init()
    return jax.process_count()


def cross_rank() -> int:
    """Index of this process among processes (hosts)."""
    _require_init()
    return jax.process_index()


def process_count() -> int:
    """TPU-native explicit name for ``jax.process_count()``."""
    _require_init()
    return jax.process_count()


def process_index() -> int:
    """TPU-native explicit name for ``jax.process_index()``."""
    _require_init()
    return jax.process_index()


def is_homogeneous() -> bool:
    """Reference: horovod_is_homogeneous — equal local sizes on all hosts.

    TPU slices are homogeneous by construction.
    """
    _require_init()
    return True


def mesh() -> jax.sharding.Mesh:
    """The global 1-D worker mesh (TPU-native addition)."""
    _require_init()
    return _STATE.global_mesh


def worker_axis() -> str:
    _require_init()
    return _STATE.config.worker_axis


# --- feature queries (reference: util.py check_extension / basics.py) -------

def mpi_threads_supported() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    """All collectives compile to XLA on this framework."""
    return True


def tpu_built() -> bool:
    return True


# --- process set API (reference: horovod/common/process_sets.py) ------------

global_process_set: Optional[ProcessSet] = None  # set lazily via __getattr__


def _get_global_process_set() -> ProcessSet:
    _require_init()
    return _STATE.global_process_set


def add_process_set(ps_or_ranks) -> ProcessSet:
    """Create a new process set at runtime (reference: hvd.add_process_set)."""
    st = _require_init()
    ps = (ps_or_ranks if isinstance(ps_or_ranks, ProcessSet)
          else ProcessSet(ps_or_ranks))
    st.process_set_table.register(ps, st.devices, st.config.worker_axis)
    return ps


def remove_process_set(ps: ProcessSet) -> bool:
    st = _require_init()
    if not ps.initialized():
        return False
    st.process_set_table.remove(ps.process_set_id)
    return True


def get_process_set_ids_and_ranks() -> Dict[int, List[int]]:
    st = _require_init()
    return {i: list(st.process_set_table.get(i).ranks)
            for i in st.process_set_table.ids()}


def get_process_set_by_id(set_id: int) -> ProcessSet:
    """Resolve a registered process set by its id (reference:
    process_set.cc lookups — used by bindings that carry the id through
    an op attribute, e.g. the TF custom-op bridge)."""
    st = _require_init()
    try:
        return st.process_set_table.get(set_id)
    except KeyError:
        raise ValueError(
            f"process set id {set_id} is not registered (removed, or "
            "from a previous init?) — compiled graphs carrying the id "
            "must not outlive remove_process_set") from None


def _setup_logging(cfg: Config):
    level = {
        "trace": logging.DEBUG, "debug": logging.DEBUG,
        "info": logging.INFO, "warning": logging.WARNING,
        "error": logging.ERROR, "fatal": logging.CRITICAL,
        "off": logging.CRITICAL,
    }.get(cfg.log_level.lower(), logging.WARNING)
    fmt = ("%(asctime)s %(name)s %(levelname)s: %(message)s"
           if cfg.log_timestamp else "%(name)s %(levelname)s: %(message)s")
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(fmt))
    logger.handlers[:] = [handler]
    logger.setLevel(level)
