"""TF custom-op bridge: registered collective ops with XLA kernels.

Reference parity: ``horovod/tensorflow/mpi_ops.cc`` (registered custom
ops as the binding) + ``xla_mpi_ops.cc`` (XLA CustomCall registration so
the ops survive ``tf.function(jit_compile=True)``) — SURVEY.md §2.1.

``native/tf_xla_ops.cc`` registers ``HorovodTpuCollective`` /
``HorovodTpuGroupedAllreduce`` with a CPU kernel (eager + plain graphs)
and an XlaOpKernel lowering to a typed-FFI custom call (XLA:CPU
clusters).  Both kernels call back into :func:`_dispatch` below, which
routes into the same engine as every other frontend — so multi-process
collectives now work INSIDE ``jit_compile=True`` graphs, the capability
the py_function fence previously blocked.

Built on demand with the toolchain g++ against the pip TF headers
(``tf.sysconfig``); ``HOROVOD_TF_XLA_OPS=0`` disables, and any
build/load failure falls back to the py_function path silently (the
fence keeps working exactly as before).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig
from typing import Optional

import numpy as np

logger = logging.getLogger("horovod_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_HERE), "native", "tf_xla_ops.cc")
_OUT = os.path.join(os.path.dirname(_HERE), "native", "_hvd_tf_xla_ops.so")

_lib = None
_lib_failed = False


def _build(timeout: float = 600.0) -> bool:
    """Compile the op library.

    Always file-locked (hvdrun spawns N workers that may all trigger a
    first-use build), and the compiler writes to a temp path that is
    os.replace()d into place — a reader can never observe a partially
    written .so."""
    import tensorflow as tf

    lock_path = _OUT + ".lock"
    with open(lock_path, "w") as lock_f:
        import fcntl
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            if os.path.exists(_OUT) and \
                    os.path.getmtime(_OUT) >= os.path.getmtime(_SRC):
                return True
            tf_dir = os.path.dirname(tf.__file__)
            inc = os.path.join(tf_dir, "include")
            tmp = _OUT + ".tmp"
            cmd = [os.environ.get("CXX", "g++"), "-shared", "-fPIC", "-O2",
                   _SRC, "-o", tmp,
                   f"-I{sysconfig.get_paths()['include']}",
                   f"-I{inc}",
                   f"-I{os.path.join(inc, 'external', 'highwayhash')}",
                   f"-I{os.path.join(inc, 'external', 'farmhash_archive', 'src')}",  # noqa: E501
                   "-D_GLIBCXX_USE_CXX11_ABI=1", "--std=c++17",
                   "-DEIGEN_MAX_ALIGN_BYTES=64",
                   f"-L{tf_dir}", "-l:libtensorflow_framework.so.2"]
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=timeout)
            os.replace(tmp, _OUT)
            logger.info("built TF XLA op bridge: %s", _OUT)
            return True
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                FileNotFoundError) as exc:
            stderr = getattr(exc, "stderr", b"") or b""
            logger.warning(
                "TF XLA op bridge build failed (%s); multi-process "
                "collectives keep the py_function path.\n%s", exc,
                stderr.decode(errors="replace")[-2000:])
            return False
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def available() -> bool:
    """True when the op library is built and loaded.  The env kill
    switch is honored per call (not cached), so a job can fence the
    bridge off even after a load."""
    global _lib, _lib_failed
    if os.environ.get("HOROVOD_TF_XLA_OPS", "1") in ("0", "false"):
        return False
    if _lib is not None:
        return True
    if _lib_failed:
        return False
    try:
        if not _build():
            _lib_failed = True
            return False
        import tensorflow as tf
        _lib = tf.load_op_library(_OUT)
        return True
    except Exception as exc:  # noqa: BLE001 - any failure means fallback
        logger.warning("TF XLA op bridge unavailable (%s); multi-process "
                       "collectives keep the py_function path.", exc)
        _lib_failed = True
        return False


def ops():
    """The loaded op module (call :func:`available` first)."""
    return _lib


def sanitize_name(name: str) -> str:
    """Attr-safe tensor name (it rides an MLIR attribute dictionary in
    the XLA lowering; applied in ONE place so the eager/graph/XLA paths
    all negotiate the same identity)."""
    return "".join(c if (c.isalnum() or c in "._/-") else "_"
                   for c in name)


def _np_dtype(dtype: str):
    if dtype == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtype)


def _dispatch(kind: str, name: str, rop: str, root: int, pre: float,
              post: float, psid: int, dtype: str, in_views, in_dims,
              out_views, out_dims) -> None:
    """Kernel-side trampoline: zero-copy memoryviews in/out.

    Runs on a TF executor (or XLA runtime) thread under the GIL; the
    engine's synchronize() waits on an Event, which releases the GIL so
    the background engine thread keeps negotiating.  ``psid`` selects a
    registered process set (-1 = global).
    """
    from .. import api, runtime

    ps = None if psid < 0 else runtime.get_process_set_by_id(int(psid))
    dt = _np_dtype(dtype)
    arrs = [np.frombuffer(v, dtype=dt).reshape(d).copy()
            for v, d in zip(in_views, in_dims)]

    if kind == "grouped_allreduce":
        res = api.grouped_allreduce(arrs, op=rop, name=name or None,
                                    prescale_factor=pre,
                                    postscale_factor=post, process_set=ps)
    else:
        x = arrs[0]
        if kind == "allreduce":
            res = api.allreduce(x, op=rop, name=name or None,
                                prescale_factor=pre, postscale_factor=post,
                                process_set=ps)
        elif kind == "allgather":
            res = api.allgather(x, name=name or None, process_set=ps)
            got = np.asarray(res).shape
            if got != tuple(out_dims[0]):
                raise ValueError(
                    f"bridge allgather result shape {got} != static XLA "
                    f"shape {tuple(out_dims[0])}: ragged (Allgatherv) "
                    "inputs need the py_function path - set "
                    "HOROVOD_TF_XLA_OPS=0 for this job")
        elif kind == "broadcast":
            res = api.broadcast(x, int(root), name=name or None,
                                process_set=ps)
        elif kind == "alltoall":
            res = api.alltoall(x, name=name or None, process_set=ps)
            if isinstance(res, list):
                res = res[runtime.rank()]
        elif kind == "reducescatter":
            res = api.rs_own_slice_np(
                api.reducescatter(x, op=rop, name=name or None,
                                  process_set=ps),
                x.ndim, api._ps(ps))
        else:
            raise ValueError(f"unknown collective kind {kind!r}")
        res = [res]

    for r, v, d in zip(res, out_views, out_dims):
        out = np.frombuffer(v, dtype=dt).reshape(d)
        out[...] = np.asarray(r, dtype=dt).reshape(d)
