"""TensorFlow framework adapter (L2/L3 binding).

Reference parity: ``horovod/tensorflow/__init__.py`` (SURVEY.md §2.2,
§3.3 TF analog) — ``DistributedGradientTape``, ``DistributedOptimizer``
(legacy-style wrapper), ``broadcast_variables``, tensor collectives, and
the aggregation knobs (``backward_passes_per_step`` via local
accumulation).

TPU-native redesign: TF tensors are converted at the binding boundary
and fed to the same eager engine as every other frontend; collectives
execute as XLA programs over the TPU mesh.  Inside ``tf.function`` the
collective is reached through ``tf.py_function`` — the graph-compatible
escape hatch to the engine (the reference reached its C++ core through
registered custom ops; SURVEY §2.1 ``HorovodAllreduceOp``).

XLA compilation boundary (reference: ``xla_mpi_ops.cc``): single-process
jobs lower collectives to pure TF ops at trace time, so
``tf.function(jit_compile=True)`` compiles them natively; multi-process
collectives must cross the process boundary through the engine and are
NOT XLA-compilable — the py_function op names carry
``requires_jit_compile_False_see_docs_adapters_md`` so the XLA
"unsupported op" error is actionable.  See docs/adapters.md.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np
import tensorflow as tf

from .. import api as _api
from ..compression import Compression
from ..exceptions import HorovodInternalError  # noqa: F401
from ..runtime import (Adasum, Average, ReduceOp, Sum,  # noqa: F401
                       init, is_initialized, shutdown, rank, size,
                       local_rank, local_size, cross_rank, cross_size,
                       mpi_threads_supported, mpi_built, mpi_enabled,
                       gloo_built, gloo_enabled, nccl_built, cuda_built,
                       rocm_built, xla_built, tpu_built,
                       ProcessSet, add_process_set, remove_process_set)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "Average", "Sum", "Adasum",
    "allreduce", "grouped_allreduce", "allgather", "grouped_allgather",
    "reducescatter", "grouped_reducescatter", "broadcast",
    "broadcast_variables", "broadcast_object", "allgather_object",
    "alltoall", "join",
    "barrier", "rank_op", "size_op", "local_rank_op", "local_size_op",
    "DistributedGradientTape", "DistributedOptimizer",
    "Compression", "ProcessSet", "add_process_set", "remove_process_set",
]


def _eager_allreduce_np(x: np.ndarray, name: str, op: str,
                        prescale: float, postscale: float,
                        process_set=None) -> np.ndarray:
    out = _api.allreduce(x, name=name or None, op=op,
                         prescale_factor=prescale,
                         postscale_factor=postscale,
                         process_set=process_set)
    return np.asarray(out)


# Reference: horovod/tensorflow/xla_mpi_ops.cc kept collectives alive
# under XLA compilation via CustomCall.  The rebuild's analog has two
# halves: (1) single-process jobs lower collectives to pure TF ops at
# trace time — fully compilable under tf.function(jit_compile=True),
# and XLA fuses the identity/scale arithmetic away; (2) multi-process
# jobs must reach the cross-process engine, which cannot live inside an
# XLA cluster, so the py_function op carries a self-documenting name —
# the "unsupported op" error XLA raises then names the fix
# (jit_compile=False) and the doc (docs/adapters.md) instead of a bare
# EagerPyFunc.
_XLA_FENCE = "requires_jit_compile_False_see_docs_adapters_md"

# dtypes the custom-op bridge's kernels support
_BRIDGE_DTYPES = (tf.float32, tf.float64, tf.float16, tf.int32, tf.int64,
                  tf.bfloat16)
_bridge_consensus: dict = {}
_bridge_consensus_state = None


def _psid(process_set) -> int:
    return -1 if process_set is None else int(process_set.process_set_id)


def _bridge_agreed(process_set=None) -> bool:
    """Whether EVERY member process has a working bridge.

    The bridge and py_function paths submit structurally different work
    (per-dtype grouped ops with suffixed names vs one group), so mixed
    availability across processes would deadlock the negotiation.  One
    engine round over the call's process set at first use agrees the
    answer (cached per set); a process whose build failed forces its
    peers onto py_function — loudly."""
    # keyed per init incarnation: shutdown/re-init recycles set ids,
    # and a stale answer would skip (or desync) the agreement round
    global _bridge_consensus_state
    from .. import runtime as _runtime_mod
    st = _runtime_mod._require_init()
    if _bridge_consensus_state is not st:
        _bridge_consensus.clear()
        _bridge_consensus_state = st
    key = _psid(process_set)
    if key not in _bridge_consensus:
        from . import _xla_bridge
        local = _xla_bridge.available()
        oks = _api.allgather_object(bool(local),
                                    name=f"tfxla.bridge.ok.{key}",
                                    process_set=process_set)
        _bridge_consensus[key] = all(oks)
        if local and not _bridge_consensus[key]:
            import logging
            logging.getLogger("horovod_tpu").warning(
                "TF XLA op bridge disabled for this job: %d/%d processes "
                "failed to build/load it (their logs say why); every "
                "process keeps the py_function path so submissions "
                "match.", sum(1 for o in oks if not o), len(oks))
    return _bridge_consensus[key]


def _f32_exact(v: float) -> bool:
    return float(np.float32(v)) == float(v)


def _bridge(dtypes, process_set=None, scales=()):
    """The registered custom-op library when it can serve this call
    (reference: mpi_ops.cc registered ops + xla_mpi_ops.cc CustomCall —
    collectives that survive ``tf.function(jit_compile=True)``).

    Falls back to the py_function path (returns None) for dtypes
    outside the kernel table and single-process jobs (their stacked
    per-worker semantics don't match the one-worker-per-process op
    contract — and single-process graphs already lower to pure TF ops);
    HOROVOD_TF_XLA_OPS=0 disables outright.  Process-set scoped calls
    carry the registered set id through the op attr.  Availability is
    agreed across the set's processes (one engine round, cached per
    set) so every member takes the same path."""
    if process_set is not None:
        if not process_set.initialized():
            return None
        from ..ops.collectives import spans_processes
        if not spans_processes(process_set):
            # a set confined to one process keeps the engine's stacked
            # per-worker semantics, which the one-worker-per-process op
            # contract cannot represent
            return None
    try:
        if cross_size() <= 1:
            return None
    except Exception:  # noqa: BLE001 - not initialized
        return None
    for dt in dtypes:
        if dt not in _BRIDGE_DTYPES:
            return None
    # scale factors ride f32 op attrs (upstream's op def uses float
    # too); a factor that f32 cannot represent exactly keeps the
    # py_function path, which forwards the full double
    for v in scales:
        if not _f32_exact(v):
            return None
    if not _bridge_agreed(process_set):
        return None
    from . import _xla_bridge
    return _xla_bridge


def _n_workers(process_set) -> int:
    return process_set.size() if process_set is not None else size()


def _graph_singleproc() -> bool:
    """Tracing a tf.function in a single-process job?  (Eager calls keep
    the engine path for timeline/stats; multi-process always does.)"""
    if tf.executing_eagerly():
        return False
    try:
        return cross_size() == 1
    except Exception:  # noqa: BLE001 - not initialized: engine path raises
        return False


def _scaled(x, factor: float):
    return x if factor == 1.0 else x * tf.cast(factor, x.dtype)


def _replicated_reduce(x, op, n: int):
    """Mirror the engine's replicated-input op table
    (ops/collectives.py _replicated_allreduce_fn): Sum scales by the
    worker count, Product is x**n, the idempotent ops (Average, Adasum,
    Min, Max) are identity on identical contributions."""
    if n <= 1:
        return x
    if op == Sum:
        return x * tf.cast(n, x.dtype)
    if op == ReduceOp.PRODUCT:
        return x ** n
    return x


def _allreduce_sparse_many(slices, name, rop, process_set):
    """Sparse allreduce for a LIST of tf.IndexedSlices: every tensor's
    values+indices gather in ONE atomic group (a single negotiated
    round for all sparse gradients — the same one-round design as the
    dense grouped path).  Duplicate indices sum implicitly when the
    slices are applied (reference: hvd.tensorflow's IndexedSlices
    handling); each worker's nonzero count may differ — the ragged
    gathers ride the engine's Allgatherv."""
    if rop not in (Sum, Average):
        raise ValueError(
            f"sparse allreduce supports Sum and Average, got {rop}")
    flat = []
    for sl in slices:
        flat.append(tf.convert_to_tensor(sl.values))
        flat.append(tf.convert_to_tensor(sl.indices))
    gathered = grouped_allgather(flat, name=name, process_set=process_set)
    n = _n_workers(process_set)
    out = []
    for k, sl in enumerate(slices):
        vals, idx = gathered[2 * k], gathered[2 * k + 1]
        if rop == Average:
            vals = vals / tf.cast(n, vals.dtype)
        out.append(tf.IndexedSlices(vals, idx, sl.dense_shape))
    return out


def _allreduce_sparse(sl, name, rop, process_set):
    return _allreduce_sparse_many([sl], name, rop, process_set)[0]


def allreduce(tensor, average=None, name=None, op=None,
              compression=Compression.none, prescale_factor=1.0,
              postscale_factor=1.0, process_set=None):
    """Allreduce a tf.Tensor or tf.IndexedSlices (works eagerly and
    inside ``tf.function``; sparse slices reduce via ragged allgather)."""
    if average is not None and op is not None:
        raise ValueError("The average and op arguments cannot both be set")
    rop = op if op is not None else (
        Average if (average is None or average) else Sum)
    if isinstance(tensor, tf.IndexedSlices):
        # reference parity: scale factors / compression are rejected for
        # sparse inputs, never silently dropped
        if prescale_factor != 1.0 or postscale_factor != 1.0:
            raise ValueError(
                "prescale/postscale factors are not supported for "
                "tf.IndexedSlices")
        if compression is not Compression.none:
            raise ValueError(
                "compression is not supported for tf.IndexedSlices")
        return _allreduce_sparse(tensor, name or "tfsparse", rop,
                                 process_set)
    nm = name or f"tfallreduce.{tensor.shape.rank}d"
    wire_dtype = tensor.dtype
    if compression is not Compression.none and tensor.dtype in (
            tf.float32, tf.float64):
        wire = tf.cast(tensor, tf.bfloat16
                       if compression is Compression.bf16 else tf.float16)
        reduced = allreduce(wire, op=rop, name=nm,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor,
                            process_set=process_set)
        return tf.cast(reduced, wire_dtype)

    if _graph_singleproc():
        # all local workers contribute the same replicated tensor —
        # pure TF ops, XLA-compilable under jit_compile=True
        out = _scaled(tensor, prescale_factor)
        out = _replicated_reduce(out, rop, _n_workers(process_set))
        return _scaled(out, postscale_factor)

    br = _bridge([tensor.dtype], process_set,
                 scales=(prescale_factor, postscale_factor))
    if br is not None:
        return br.ops().horovod_tpu_collective(
            tensor, kind="allreduce", tensor_name=br.sanitize_name(nm),
            reduce_op=rop, prescale=prescale_factor,
            postscale=postscale_factor, nproc=_n_workers(process_set),
            process_set_id=_psid(process_set))

    def _np_op(x):
        return _eager_allreduce_np(x.numpy(), nm, rop, prescale_factor,
                                   postscale_factor, process_set)

    out = tf.py_function(_np_op, [tensor], Tout=tensor.dtype,
                         name=f"HorovodAllreduce__{_XLA_FENCE}")
    out.set_shape(tensor.shape)
    return out


def grouped_allreduce(tensors: Sequence, average=None, name=None, op=None,
                      compression=Compression.none, prescale_factor=1.0,
                      postscale_factor=1.0, process_set=None) -> List:
    """Allreduce a list of tensors as ONE atomic fusion group: one
    negotiated round and one (or few) fused dispatches instead of a
    synchronous engine round-trip per tensor (reference:
    hvd.grouped_allreduce / group_table.cc)."""
    if average is not None and op is not None:
        raise ValueError("The average and op arguments cannot both be set")
    rop = op if op is not None else (
        Average if (average is None or average) else Sum)
    nm = name or "tfgrouped"

    if compression is not Compression.none:
        wire_dt = (tf.bfloat16 if compression is Compression.bf16
                   else tf.float16)
        comp = [t.dtype in (tf.float32, tf.float64) for t in tensors]
        wires = [tf.cast(t, wire_dt) if c else t
                 for t, c in zip(tensors, comp)]
        outs = grouped_allreduce(
            wires, op=rop, name=nm, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=process_set)
        return [tf.cast(o, t.dtype) if c else o
                for o, t, c in zip(outs, tensors, comp)]

    if _graph_singleproc():
        n = _n_workers(process_set)
        return [_scaled(_replicated_reduce(
            _scaled(t, prescale_factor), rop, n), postscale_factor)
            for t in tensors]

    br = _bridge([t.dtype for t in tensors], process_set,
                 scales=(prescale_factor, postscale_factor))
    if br is not None and tensors:
        # one variadic op per dtype (the engine's fusion buckets are
        # per-dtype anyway — SURVEY §2.1 fusion buffer); deterministic
        # dtype order so every process negotiates the same groups
        out: List = [None] * len(tensors)
        by_dtype: dict = {}
        for i, t in enumerate(tensors):
            by_dtype.setdefault(t.dtype.name, []).append(i)
        for dt_name in sorted(by_dtype):
            idxs = by_dtype[dt_name]
            outs = br.ops().horovod_tpu_grouped_allreduce(
                [tensors[i] for i in idxs],
                tensor_name=br.sanitize_name(f"{nm}.{dt_name}"),
                reduce_op=rop, prescale=prescale_factor,
                postscale=postscale_factor,
                process_set_id=_psid(process_set))
            for i, o in zip(idxs, outs):
                out[i] = o
        return out

    def _np_op(*xs):
        outs = _api.grouped_allreduce([x.numpy() for x in xs],
                                      name=nm, op=rop,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      process_set=process_set)
        return [np.asarray(o) for o in outs]

    outs = tf.py_function(_np_op, list(tensors),
                          Tout=[t.dtype for t in tensors],
                          name=f"HorovodGroupedAllreduce__{_XLA_FENCE}")
    outs = _as_output_list(outs, len(tensors))
    for o, t in zip(outs, tensors):
        o.set_shape(t.shape)
    return outs


def _as_output_list(outs, n: int) -> List:
    """``tf.py_function`` with a single-element ``Tout`` returns a bare
    tensor, not a 1-list — zipping it against the inputs would iterate
    its ELEMENTS.  Normalize to a list of ``n`` tensors."""
    if n == 1 and not isinstance(outs, (list, tuple)):
        return [outs]
    return list(outs)


def _set_gather_shape(out, inp):
    """Gathered outputs keep the input shape with an unknown leading dim
    (the worker-count concat axis)."""
    shape = inp.shape.as_list()
    if shape:
        shape[0] = None
    out.set_shape(shape)
    return out


def grouped_allgather(tensors: Sequence, name=None,
                      process_set=None) -> List:
    """Allgather a list of tensors as one atomic fusion group
    (reference: hvd.grouped_allgather)."""
    nm = name or "tfgroupedallgather"

    if _graph_singleproc():
        n = _n_workers(process_set)
        return [tf.concat([t] * n, axis=0) for t in tensors]

    def _np_op(*xs):
        outs = _api.grouped_allgather([x.numpy() for x in xs], name=nm,
                                      process_set=process_set)
        return [np.asarray(o) for o in outs]

    outs = tf.py_function(_np_op, list(tensors),
                          Tout=[t.dtype for t in tensors],
                          name=f"HorovodGroupedAllgather__{_XLA_FENCE}")
    outs = _as_output_list(outs, len(tensors))
    return [_set_gather_shape(o, t) for o, t in zip(outs, tensors)]


def allgather(tensor, name=None, process_set=None):
    nm = name or "tfallgather"

    if _graph_singleproc():
        # replicated allgather = worker-count copies along dim 0
        return tf.concat([tensor] * _n_workers(process_set), axis=0)

    # uniform shapes only via the bridge: the XLA lowering needs the
    # n*dim0 output shape statically, and no single process can verify
    # its peers' row counts at trace time.  Eager calls therefore skip
    # the bridge entirely (the engine path handles ragged Allgatherv);
    # graph-mode ragged inputs fail actionably in the dispatch.
    if not tf.executing_eagerly() and tensor.shape.rank \
            and tensor.shape[0] is not None:
        br = _bridge([tensor.dtype], process_set)
        if br is not None:
            return br.ops().horovod_tpu_collective(
                tensor, kind="allgather",
                tensor_name=br.sanitize_name(nm),
                nproc=_n_workers(process_set),
                process_set_id=_psid(process_set))

    def _np_op(x):
        return np.asarray(_api.allgather(x.numpy(), name=nm,
                                         process_set=process_set))

    out = tf.py_function(_np_op, [tensor], Tout=tensor.dtype,
                         name=f"HorovodAllgather__{_XLA_FENCE}")
    return _set_gather_shape(out, tensor)


def _set_rs_shape(out, inp, n: int):
    """Reducescatter outputs keep the input shape with dim 0 divided by
    the worker count (unknown-rank inputs stay unknown, mirroring
    allreduce's behavior on them)."""
    if inp.shape.rank is None:
        return out
    shape = inp.shape.as_list()
    if shape:
        shape[0] = (shape[0] // n) if shape[0] is not None else None
    out.set_shape(shape)
    return out


def _rs_validate(rop, tensor, n: int):
    """Mode-independent argument validation (the engine raises the same
    errors at submission — the answer cannot depend on eager vs graph)."""
    if rop not in (Sum, Average):
        raise ValueError(
            f"reducescatter supports Sum and Average, got {rop}")
    d0 = tensor.shape[0] if tensor.shape.rank else None
    if d0 is not None and int(d0) % n:
        raise ValueError(
            f"reducescatter dim-0 {int(d0)} not divisible by {n}")


def reducescatter(tensor, op=None, name=None, process_set=None):
    """Reduce across workers, keep this worker's dim-0 slice
    (reference: hvd.tensorflow reducescatter)."""
    rop = op if op is not None else Average
    nm = name or "tfreducescatter"
    n = _n_workers(process_set)
    _rs_validate(rop, tensor, n)

    if _graph_singleproc() and tensor.shape.rank \
            and tensor.shape[0] is not None:
        # engine replicated-branch semantics (ops/collectives.py
        # reducescatter_array): reducing n identical copies scales by n
        # for Sum and is the identity for Average; keep OUR slice —
        # pure TF ops, XLA-compilable under jit_compile=True
        if n <= 1:
            return tf.identity(tensor)
        idx = _api._ps(process_set).rank()
        if idx < 0:
            raise ValueError(
                "reducescatter called from a worker outside the process "
                "set")
        chunk = int(tensor.shape[0]) // n
        out = tensor[idx * chunk:(idx + 1) * chunk]
        return out * tf.cast(n, out.dtype) if rop == Sum else out

    if tensor.shape.rank and tensor.shape[0] is not None:
        br = _bridge([tensor.dtype], process_set)
        if br is not None:
            return br.ops().horovod_tpu_collective(
                tensor, kind="reducescatter",
                tensor_name=br.sanitize_name(nm), reduce_op=rop, nproc=n,
                process_set_id=_psid(process_set))

    def _np_op(x):
        ps = _api._ps(process_set)
        arr = x.numpy()
        res = _api.reducescatter(arr, op=rop, name=nm,
                                 process_set=process_set)
        return _api.rs_own_slice_np(res, arr.ndim, ps)

    out = tf.py_function(_np_op, [tensor], Tout=tensor.dtype,
                         name=f"HorovodReducescatter__{_XLA_FENCE}")
    return _set_rs_shape(out, tensor, n)


def grouped_reducescatter(tensors: Sequence, op=None, name=None,
                          process_set=None) -> List:
    """Reducescatter a list of tensors as one atomic fusion group
    (reference: hvd.grouped_reducescatter)."""
    rop = op if op is not None else Average
    nm = name or "tfgroupedreducescatter"
    n = _n_workers(process_set)
    for t in tensors:
        _rs_validate(rop, t, n)

    if _graph_singleproc() and all(
            t.shape.rank and t.shape[0] is not None for t in tensors):
        return [reducescatter(t, op=rop, name=f"{nm}.{i}",
                              process_set=process_set)
                for i, t in enumerate(tensors)]

    def _np_op(*xs):
        ps = _api._ps(process_set)
        arrs = [x.numpy() for x in xs]
        outs = _api.grouped_reducescatter(arrs, op=rop, name=nm,
                                          process_set=process_set)
        return [_api.rs_own_slice_np(o, a.ndim, ps)
                for o, a in zip(outs, arrs)]

    outs = tf.py_function(_np_op, list(tensors),
                          Tout=[t.dtype for t in tensors],
                          name=f"HorovodGroupedReducescatter__{_XLA_FENCE}")
    outs = _as_output_list(outs, len(tensors))
    return [_set_rs_shape(o, t, n) for o, t in zip(outs, tensors)]


def broadcast(tensor, root_rank: int = 0, name=None, process_set=None):
    nm = name or "tfbroadcast"

    if _graph_singleproc():
        return tf.identity(tensor)  # replicated: already everywhere

    br = _bridge([tensor.dtype], process_set)
    if br is not None:
        return br.ops().horovod_tpu_collective(
            tensor, kind="broadcast", tensor_name=br.sanitize_name(nm),
            root_rank=root_rank, nproc=_n_workers(process_set),
            process_set_id=_psid(process_set))

    def _np_op(x):
        return np.asarray(_api.broadcast(x.numpy(), root_rank, name=nm,
                                         process_set=process_set))

    out = tf.py_function(_np_op, [tensor], Tout=tensor.dtype,
                         name=f"HorovodBroadcast__{_XLA_FENCE}")
    out.set_shape(tensor.shape)
    return out


def alltoall(tensor, splits=None, name=None, process_set=None):
    nm = name or "tfalltoall"

    static_uniform = splits is None
    if splits is not None and not tf.is_tensor(splits):
        sp = np.asarray(splits)
        n_ = _n_workers(process_set)
        # same validation the engine applies at dispatch (api.py), so
        # the answer cannot depend on the compilation mode
        if sp.ndim != 1 or sp.shape[0] != n_:
            raise ValueError(
                f"splits must have one entry per worker ({n_}), got "
                f"{sp.shape[0] if sp.ndim == 1 else sp.shape}")
        static_uniform = bool(sp.size) and bool(np.all(sp == sp[0]))
    if _graph_singleproc() and static_uniform:
        # replicated input, single process: worker j's result is n copies
        # of chunk j, stacked over the local workers — exactly the eager
        # engine's replicated branch (ops/collectives.py alltoall_array,
        # which chunks by dim0 // n regardless of uniform splits) — as
        # pure TF ops, XLA-compilable under jit_compile=True.  Uneven or
        # tensor-valued splits keep the engine path, as does a dynamic
        # leading dimension (the chunking is shape-dependent).
        n = _n_workers(process_set)
        if tensor.shape.rank and tensor.shape[0] is not None:
            rows = int(tensor.shape[0]) // n
            per_worker = [
                tf.concat([tensor[j * rows:(j + 1) * rows]] * n, axis=0)
                for j in range(n)]
            return tf.stack(per_worker, axis=0)

    if splits is None:
        br = _bridge([tensor.dtype], process_set)
        if br is not None:
            return br.ops().horovod_tpu_collective(
                tensor, kind="alltoall", tensor_name=br.sanitize_name(nm),
                nproc=_n_workers(process_set),
                process_set_id=_psid(process_set))

    def _np_op(x):
        res = _api.alltoall(x.numpy(), splits=splits, name=nm,
                            process_set=process_set)
        if isinstance(res, list):
            from .. import runtime
            res = res[runtime.rank()]
        return np.asarray(res)

    out = tf.py_function(_np_op, [tensor], Tout=tensor.dtype,
                         name=f"HorovodAlltoall__{_XLA_FENCE}")
    return out


def rank_op(name=None):
    """Graph-mode rank (reference: hvd.tensorflow rank_op)."""
    return tf.constant(rank(), name=name or "horovod_rank")


def size_op(name=None):
    return tf.constant(size(), name=name or "horovod_size")


def local_rank_op(name=None):
    return tf.constant(local_rank(), name=name or "horovod_local_rank")


def local_size_op(name=None):
    return tf.constant(local_size(), name=name or "horovod_local_size")


def join(device: int = -1) -> int:
    return _api.join(device)


def barrier(process_set=None):
    return _api.barrier(process_set)


def broadcast_object(obj, root_rank: int = 0, name=None, process_set=None):
    return _api.broadcast_object(obj, root_rank, name, process_set)


def allgather_object(obj, name=None, process_set=None):
    return _api.allgather_object(obj, name, process_set)


def broadcast_variables(variables, root_rank: int = 0, process_set=None):
    """Assign every variable its value on ``root_rank`` (reference:
    hvd.broadcast_variables — used at train start so all workers agree)."""
    for i, v in enumerate(variables):
        name = f"bv.{getattr(v, 'name', i)}"
        v.assign(broadcast(tf.convert_to_tensor(v), root_rank, name=name,
                           process_set=process_set))


class DistributedGradientTape:
    """Gradient tape wrapper whose ``gradient()`` allreduces each gradient.

    Reference: ``hvd.DistributedGradientTape(tape)`` (SURVEY §3.3 TF
    analog) — wraps an existing ``tf.GradientTape``; every other method
    delegates to it.  ``backward_passes_per_step > 1`` accumulates
    locally and reduces every N-th call (gradients summed over passes,
    averaged over workers).
    """

    def __init__(self, tape: Optional[tf.GradientTape] = None,
                 compression=Compression.none, op=Average,
                 gradient_predivide_factor: float = 1.0,
                 backward_passes_per_step: int = 1,
                 persistent: bool = False, sparse_as_dense: bool = False,
                 process_set=None):
        self._wrapped = tape if tape is not None else tf.GradientTape(
            persistent=persistent)
        self._compression = compression
        self._op = op
        self._sparse_as_dense = bool(sparse_as_dense)
        if gradient_predivide_factor != 1.0 and op != Average:
            raise ValueError(
                "gradient_predivide_factor requires op == Average")
        self._prescale = (1.0 / gradient_predivide_factor
                          if gradient_predivide_factor != 1.0 else 1.0)
        self._postscale = gradient_predivide_factor
        self._bpps = int(backward_passes_per_step)
        self._pass = 0
        self._acc: Optional[List] = None
        self._process_set = process_set

    def __getattr__(self, name):
        return getattr(self._wrapped, name)

    def __enter__(self):
        self._wrapped.__enter__()
        return self

    def __exit__(self, *exc):
        return self._wrapped.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._wrapped.gradient(target, sources, output_gradients)
        self._pass += 1
        if self._bpps > 1:
            if not self._sparse_as_dense and any(
                    isinstance(g, tf.IndexedSlices) for g in grads):
                raise ValueError(
                    "backward_passes_per_step > 1 accumulates gradients "
                    "densely; pass sparse_as_dense=True to accept the "
                    "dense materialization of sparse gradients")
            if self._acc is None:
                self._acc = [tf.zeros_like(g) if g is not None else None
                             for g in grads]
            self._acc = [a + g if g is not None else a
                         for a, g in zip(self._acc, grads)]
            if self._pass % self._bpps != 0:
                return [None if g is None else tf.zeros_like(g)
                        for g in grads]
            grads, self._acc = self._acc, None
        # ONE grouped submission for every dense gradient: a single
        # negotiated round + fused dispatch instead of a synchronous
        # engine round-trip per gradient (the TF frontend's former
        # per-op latency tax)
        dense_idx, dense = [], []
        sparse_idx, sparse_sl = [], []
        out: List = [None] * len(grads)
        for i, g in enumerate(grads):
            if g is None:
                continue
            if isinstance(g, tf.IndexedSlices):
                if self._sparse_as_dense:
                    g = tf.convert_to_tensor(g)  # densify (reference knob)
                else:
                    if self._compression is not Compression.none:
                        raise ValueError(
                            "compression is not supported for sparse "
                            "gradients; pass sparse_as_dense=True to "
                            "densify them")
                    sparse_idx.append(i)
                    sparse_sl.append(g)
                    continue
            dense_idx.append(i)
            dense.append(g)
        if sparse_sl:  # ONE ragged-gather round for all sparse grads
            for i, r in zip(sparse_idx, _allreduce_sparse_many(
                    sparse_sl, "tape.sparse", self._op,
                    self._process_set)):
                out[i] = r
        reduced = grouped_allreduce(
            dense, op=self._op, name="tape.grads",
            compression=self._compression,
            prescale_factor=self._prescale,
            postscale_factor=self._postscale,
            process_set=self._process_set) if dense else []
        for i, r in zip(dense_idx, reduced):
            out[i] = r
        return out


def DistributedOptimizer(optimizer, name=None,
                         compression=Compression.none, op=Average,
                         backward_passes_per_step: int = 1,
                         sparse_as_dense: bool = False,
                         process_set=None):
    """Wrap a ``keras.optimizers.Optimizer``: gradients are allreduced
    before being applied (reference: hvd.DistributedOptimizer for TF2 —
    an ``apply_gradients`` interceptor)."""
    base = optimizer.__class__

    class _Dist(base):  # noqa: D401 - dynamic wrapper
        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            if backward_passes_per_step > 1:
                return self._hvd_accumulate_apply(gv, args, kwargs)
            # one grouped round for all dense gradients (see
            # DistributedGradientTape.gradient)
            dense_idx, dense = [], []
            sparse_idx, sparse_sl = [], []
            for i, (g, _v) in enumerate(gv):
                if g is None:
                    continue
                if isinstance(g, tf.IndexedSlices):
                    if sparse_as_dense:
                        g = tf.convert_to_tensor(g)
                    elif compression is not Compression.none:
                        raise ValueError(
                            "compression is not supported for sparse "
                            "gradients; pass sparse_as_dense=True to "
                            "densify them")
                    else:
                        sparse_idx.append(i)
                        sparse_sl.append(g)
                        continue
                dense_idx.append(i)
                dense.append(g)
            if sparse_sl:  # one ragged-gather round for all sparse
                for i, r in zip(sparse_idx, _allreduce_sparse_many(
                        sparse_sl, "opt.sparse", op, process_set)):
                    gv[i] = (r, gv[i][1])
            outs = grouped_allreduce(
                dense, op=op, name="opt.grads", compression=compression,
                process_set=process_set) if dense else []
            reduced = list(gv)
            for i, r in zip(dense_idx, outs):
                reduced[i] = (r, reduced[i][1])
            return base.apply_gradients(self, reduced, *args, **kwargs)

        def _hvd_accumulate_apply(self, gv, args, kwargs):
            """Local gradient accumulation: reduce + apply every N-th
            call (reference: backward_passes_per_step via the TF
            LocalGradientAggregationHelper — variable-backed counter and
            accumulators so keras's tf.function-compiled train steps
            count correctly)."""
            if any(isinstance(g, tf.IndexedSlices)
                   for g, _v in gv if g is not None)                     and not sparse_as_dense:
                raise ValueError(
                    "backward_passes_per_step > 1 accumulates gradients "
                    "densely; pass sparse_as_dense=True to accept the "
                    "dense materialization of sparse gradients")
            gv = [(tf.convert_to_tensor(g)
                   if isinstance(g, tf.IndexedSlices) else g, v)
                  for g, v in gv]
            if not hasattr(self, "_hvd_bpps_counter"):
                self._hvd_bpps_counter = tf.Variable(
                    0, trainable=False, dtype=tf.int64,
                    name="hvd_bpps_counter")
                self._hvd_bpps_acc = {}
            idxs = [i for i, (g, _v) in enumerate(gv) if g is not None]
            for i in idxs:
                # keyed by VARIABLE, not position: one optimizer may
                # serve several apply_gradients call shapes (GAN nets,
                # freeze schedules) — upstream's aggregation helper
                # keys by variable for the same reason
                key = gv[i][1].ref()
                if key not in self._hvd_bpps_acc:
                    self._hvd_bpps_acc[key] = tf.Variable(
                        tf.zeros_like(gv[i][0]), trainable=False,
                        name=f"hvd_bpps_acc_{len(self._hvd_bpps_acc)}")
                self._hvd_bpps_acc[key].assign_add(gv[i][0])
            self._hvd_bpps_counter.assign_add(1)

            def _apply():
                accs = [self._hvd_bpps_acc[gv[i][1].ref()] for i in idxs]
                outs = grouped_allreduce(
                    [a.value() for a in accs],
                    op=op, name="opt.acc.grads", compression=compression,
                    process_set=process_set) if idxs else []
                reduced = [(o, gv[i][1]) for o, i in zip(outs, idxs)]
                base.apply_gradients(self, reduced, *args, **kwargs)
                for a in accs:
                    a.assign(tf.zeros_like(a))
                return tf.constant(True)

            return tf.cond(
                tf.equal(self._hvd_bpps_counter % backward_passes_per_step,
                         0),
                _apply, lambda: tf.constant(False))

    _Dist.__name__ = base.__name__
    optimizer.__class__ = _Dist
    return optimizer


from . import elastic  # noqa: E402,F401 - hvd.elastic namespace

__all__ += ["elastic"]


# Load the custom-op bridge BEFORE the first TF op executes: TF
# materializes its XLA compilation-kernel registry once, and op
# libraries loaded after that point lose their XlaOpKernel
# registrations (jit_compile would then fail with "no registered
# kernel ... compatible"; the reference loaded mpi_lib at import for
# the same reason).  Only multi-process launches need the bridge, so
# single-process imports skip the one-time build; availability stays
# consensus-agreed at first use either way.
# (HOROVOD_NUM_PROCESSES counts hvdrun-launched worker processes;
# HOROVOD_SIZE is the reference's §3.4 contract any launcher exports.
# The env contract's CROSS_SIZE counts hosts — 1 for local jobs.)
if os.environ.get("HOROVOD_NUM_PROCESSES", "1") not in ("", "1") or \
        os.environ.get("HOROVOD_SIZE", "1") not in ("", "1"):
    from . import _xla_bridge as _xla_bridge_eager_load
    _xla_bridge_eager_load.available()
