"""Elastic state for tf.keras models (reference:
``horovod/tensorflow/elastic.py`` ``TensorFlowKerasState`` —
SURVEY.md §2.2).

``TensorFlowKerasState(model, optimizer=None, **scalars)`` snapshots
the model (and optimizer) weights in memory on ``commit()``, rolls back
on ``restore()`` after a collective failure, and ``sync()``s everything
from the coordinator after membership changes — the TF face of the same
elastic machinery :class:`horovod_tpu.torch.elastic.TorchState` gives
torch and :class:`horovod_tpu.elastic.ArrayState` gives JAX pytrees.
Use with ``@hvd.elastic.run`` exactly as upstream:

    state = hvd.elastic.TensorFlowKerasState(model, optimizer=opt,
                                             batch=0, epoch=0)

    @hvd.elastic.run
    def train(state): ...
"""

from __future__ import annotations

import copy

from ..elastic.state import FrameworkState


class TensorFlowKerasState(FrameworkState):
    """Elastic snapshot/sync for keras models + optimizers + scalars
    (scalar/attribute machinery shared via FrameworkState)."""

    def __init__(self, model, optimizer=None, **kwargs):
        super().__init__(
            model=model,
            optimizer=(optimizer if optimizer is not None
                       else getattr(model, "optimizer", None)),
            **kwargs)

    def _opt_vars(self):
        opt = self._optimizer
        if opt is None:
            return []
        vars_ = getattr(opt, "variables", None)
        if vars_ is None:
            return []
        return list(vars_() if callable(vars_) else vars_)

    # State interface ----------------------------------------------------
    def save(self):
        opt_vars = self._opt_vars()
        names = [v.name for v in opt_vars]
        self._saved = {
            "model": [w.copy() for w in self._model.get_weights()],
            # keyed by name so slot variables created AFTER a commit are
            # detected on restore instead of silently mis-zipped
            "optimizer": ({v.name: v.numpy().copy() for v in opt_vars}
                          if len(set(names)) == len(names)
                          else [v.numpy().copy() for v in opt_vars]),
            "scalars": copy.deepcopy(self._scalars),
        }

    def restore(self):
        if self._saved.get("model"):
            self._model.set_weights(
                [w.copy() for w in self._saved["model"]])
        saved_opt = self._saved.get("optimizer", {})
        cur = self._opt_vars()
        if isinstance(saved_opt, dict):
            missing = [v.name for v in cur if v.name not in saved_opt]
            if missing:
                import logging
                logging.getLogger("horovod_tpu").warning(
                    "TensorFlowKerasState.restore(): optimizer variables "
                    "created after the last commit cannot be rolled back "
                    "(%s) — commit() after the first training step so "
                    "slot variables are captured.", ", ".join(missing))
            for v in cur:
                if v.name in saved_opt:
                    v.assign(saved_opt[v.name])
        else:  # duplicate names: positional fallback
            if len(saved_opt) != len(cur):
                import logging
                logging.getLogger("horovod_tpu").warning(
                    "TensorFlowKerasState.restore(): optimizer variable "
                    "count changed since the last commit (%d -> %d); "
                    "only the common prefix is rolled back.",
                    len(saved_opt), len(cur))
            for var, val in zip(cur, saved_opt):
                var.assign(val)
        self._scalars = copy.deepcopy(self._saved.get("scalars", {}))

    def sync(self):
        """Broadcast live model/optimizer/scalars from the coordinator
        (after a membership change the new worker set must agree)."""
        from . import broadcast_object, broadcast_variables
        variables = list(self._model.variables) + self._opt_vars()
        if variables:
            broadcast_variables(variables, root_rank=0)
        self._scalars = broadcast_object(self._scalars, root_rank=0)
        self.save()


# the TF elastic namespace mirrors upstream hvd.elastic: the run
# wrapper, sampler, and object state come from the shared machinery
from ..elastic import ElasticSampler, run  # noqa: E402,F401
from ..elastic.state import ObjectState, State  # noqa: E402,F401
