"""Engine 2: lock-acquisition-graph self-check (HVD101–HVD103).

A lightweight static race detector for the framework's own threaded
modules (``ops/engine.py``, ``ops/controller.py``, ``elastic/driver.py``,
``stall.py``, ...).  It recognizes lock attributes from
``self.X = threading.Lock()/RLock()/Condition(...)`` assignments, walks
every method tracking the held-lock set through ``with self.X:`` blocks
and ``acquire()``/``release()`` pairs, propagates acquisitions through
one intra-class call fixpoint, and flags:

* **HVD101** — two locks acquired in opposite orders somewhere in the
  class (a cycle in the acquisition-order graph);
* **HVD102** — ``cv.wait()`` while holding a lock other than the
  condition's own (wait() releases only its own lock, so the notifier
  can never run);
* **HVD103** — re-acquiring a non-reentrant ``threading.Lock`` already
  held on the same path.

``threading.Condition(self._lock)`` aliases the condition to its
underlying lock, so ``with self._cv:`` and ``with self._lock:`` are the
same acquisition — nesting them is HVD103 only when the lock is a plain
``Lock``... which is exactly the real-world bug this catches.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .report import Finding


@dataclasses.dataclass
class _LockDef:
    name: str                 # attribute name, e.g. "_lock"
    kind: str                 # "lock" | "rlock" | "condition"
    underlying: str           # the lock actually acquired (Condition alias)
    line: int = 0


@dataclasses.dataclass
class _MethodSummary:
    name: str
    # (held frozenset of lock names, acquired lock name, line)
    acquisitions: List[Tuple[frozenset, str, int]] = \
        dataclasses.field(default_factory=list)
    # (held frozenset, callee method name, line)
    calls: List[Tuple[frozenset, str, int]] = \
        dataclasses.field(default_factory=list)
    # (held frozenset, condition attr name, line)
    waits: List[Tuple[frozenset, str, int]] = \
        dataclasses.field(default_factory=list)


def _lock_ctor(node: ast.expr) -> Optional[Tuple[str, Optional[str]]]:
    """(kind, condition's-underlying-attr) for threading lock constructors."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else None
    if name == "Lock":
        return ("lock", None)
    if name == "RLock":
        return ("rlock", None)
    if name == "Condition":
        under = None
        if node.args and isinstance(node.args[0], ast.Attribute):
            under = node.args[0].attr
        return ("condition", under)
    return None


def _self_attr(node: ast.expr) -> Optional[str]:
    """'attr' for ``self.attr`` (or ``OBJ.attr`` — locks are matched by
    attribute name, so module-level singletons like ``_STATE._init_lock``
    resolve to the class's lock definition)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _ClassLockAnalysis:
    def __init__(self, cls: ast.ClassDef, path: str):
        self.cls = cls
        self.path = path
        self.locks: Dict[str, _LockDef] = {}
        self.methods: Dict[str, _MethodSummary] = {}

    # -- discovery -----------------------------------------------------------
    def collect_locks(self):
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            attr = _self_attr(target)
            if attr is None:
                continue
            ctor = _lock_ctor(node.value)
            if ctor is None:
                continue
            kind, under = ctor
            self.locks[attr] = _LockDef(
                name=attr, kind=kind, underlying=under or attr,
                line=node.lineno)

    def _underlying(self, attr: str) -> str:
        d = self.locks.get(attr)
        return d.underlying if d else attr

    def _kind(self, attr: str) -> str:
        d = self.locks.get(attr)
        return d.kind if d else "lock"

    # -- per-method simulation ----------------------------------------------
    def summarize_methods(self, findings: List[Finding]):
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                summary = _MethodSummary(node.name)
                self._walk(node.body, frozenset(), summary, findings)
                self.methods[node.name] = summary

    def _record_acquire(self, attr: str, held: frozenset, line: int,
                        summary: _MethodSummary, findings: List[Finding]):
        lock = self._underlying(attr)
        if lock in held:
            base = self.locks.get(lock)
            if base is not None and base.kind == "lock":
                findings.append(Finding(
                    "HVD103", self.path, line, 0,
                    f"{self.cls.name}.{summary.name} re-acquires "
                    f"non-reentrant lock 'self.{lock}' already held on "
                    f"this path; a plain threading.Lock self-deadlocks"))
            return
        summary.acquisitions.append((held, lock, line))

    def _walk(self, stmts, held: frozenset, summary: _MethodSummary,
              findings: List[Finding]):
        for stmt in stmts:
            held = self._walk_stmt(stmt, held, summary, findings)

    def _walk_stmt(self, stmt: ast.stmt, held: frozenset,
                   summary: _MethodSummary,
                   findings: List[Finding]) -> frozenset:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                ctx = item.context_expr
                attr = _self_attr(ctx) if isinstance(ctx, ast.Attribute) \
                    else None
                if attr is not None and attr in self.locks:
                    self._record_acquire(attr, inner, stmt.lineno,
                                         summary, findings)
                    inner = inner | {self._underlying(attr)}
            self._walk(stmt.body, inner, summary, findings)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (callbacks) run later, on an unknown thread;
            # analyze them with an empty held set
            nested = _MethodSummary(f"{summary.name}.<{stmt.name}>")
            self._walk(stmt.body, frozenset(), nested, findings)
            self.methods[nested.name] = nested
            return held
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, held, summary, findings)
            for handler in stmt.handlers:
                self._walk(handler.body, held, summary, findings)
            self._walk(stmt.orelse, held, summary, findings)
            self._walk(stmt.finalbody, held, summary, findings)
            return held
        if isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor)):
            for field in ("body", "orelse"):
                self._walk(getattr(stmt, field, []), held, summary,
                           findings)
            test = getattr(stmt, "test", None) or getattr(stmt, "iter", None)
            if test is not None:
                self._scan_calls(test, held, summary, findings)
            return held
        if isinstance(stmt, ast.Match):
            self._scan_calls(stmt.subject, held, summary, findings)
            for case in stmt.cases:
                self._walk(case.body, held, summary, findings)
            return held
        return self._scan_linear(stmt, held, summary, findings)

    def _scan_linear(self, stmt: ast.stmt, held: frozenset,
                     summary: _MethodSummary,
                     findings: List[Finding]) -> frozenset:
        """Explicit acquire()/release()/wait()/self-calls in a leaf
        statement; returns the updated held set (acquire() holds until a
        matching release() later in the method)."""
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            recv = _self_attr(fn.value)
            if recv is not None and recv in self.locks:
                if fn.attr == "acquire":
                    self._record_acquire(recv, held, node.lineno,
                                         summary, findings)
                    held = held | {self._underlying(recv)}
                elif fn.attr == "release":
                    held = held - {self._underlying(recv)}
                elif fn.attr in ("wait", "wait_for") \
                        and self._kind(recv) == "condition":
                    summary.waits.append((held, recv, node.lineno))
            elif isinstance(fn.value, ast.Name) and fn.value.id == "self":
                held_now = held
                summary.calls.append((held_now, fn.attr, node.lineno))
        return held

    def _scan_calls(self, expr: ast.expr, held: frozenset,
                    summary: _MethodSummary, findings: List[Finding]):
        wrapper = ast.Expr(value=expr)
        ast.copy_location(wrapper, expr)
        self._scan_linear(wrapper, held, summary, findings)

    # -- whole-class verdicts -----------------------------------------------
    def finish(self, findings: List[Finding]):
        # one-level-plus fixpoint: locks a method may acquire, directly or
        # through intra-class calls
        acquires: Dict[str, Set[str]] = {
            m: {lock for _, lock, _ in s.acquisitions}
            for m, s in self.methods.items()}
        changed = True
        while changed:
            changed = False
            for m, s in self.methods.items():
                for _, callee, _ in s.calls:
                    extra = acquires.get(callee, set()) - acquires[m]
                    if extra:
                        acquires[m] |= extra
                        changed = True

        # acquisition-order edges: direct nestings + lock-held calls into
        # methods that acquire
        edges: Dict[Tuple[str, str], int] = {}
        for s in self.methods.values():
            for held, lock, line in s.acquisitions:
                for h in held:
                    edges.setdefault((h, lock), line)
            for held, callee, line in s.calls:
                if not held:
                    continue
                for lock in acquires.get(callee, ()):
                    for h in held:
                        if h != lock:
                            edges.setdefault((h, lock), line)

        reported = set()
        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if (b, a) in edges and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                findings.append(Finding(
                    "HVD101", self.path, line, 0,
                    f"{self.cls.name}: locks 'self.{a}' and 'self.{b}' are "
                    f"acquired in both orders (also line "
                    f"{edges[(b, a)]}); two threads taking opposite orders "
                    f"deadlock"))

        # cv waits while holding an unrelated lock
        for s in self.methods.values():
            for held, cv, line in s.waits:
                others = held - {self._underlying(cv)}
                if others:
                    other = ", ".join(f"self.{o}" for o in sorted(others))
                    findings.append(Finding(
                        "HVD102", self.path, line, 0,
                        f"{self.cls.name}.{s.name} waits on "
                        f"'self.{cv}' while holding {other}; wait() only "
                        f"releases the condition's own lock, so the "
                        f"notifying thread blocks on {other} forever"))


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            analysis = _ClassLockAnalysis(node, path)
            analysis.collect_locks()
            if not analysis.locks:
                continue
            analysis.summarize_methods(findings)
            analysis.finish(findings)
    return findings
