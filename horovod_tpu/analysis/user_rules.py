"""Engine 1: AST rules over user training scripts (HVD001–HVD006).

The hazard taxonomy is the classic Horovod one (deadlock from
rank-conditional collectives, divergence from a missing initial
broadcast, order divergence from unordered submission — see
docs/analysis.md for the catalog with examples).  Every check is
syntactic and conservative: we only flag a call when the receiver
provably resolves to a horovod module alias (``import horovod_tpu as
hvd``), so ``"".join(...)`` or ``thread.join()`` can never trip the
``join`` rule.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .report import Finding

# Names that submit (or gate on) a negotiated collective.  synchronize()
# is deliberately absent: it blocks locally on an already-submitted
# handle, which is rank-conditionally safe (it is still HVD006 in jit).
COLLECTIVES: Dict[str, str] = {}
for _base in ("allreduce", "allgather", "broadcast", "alltoall",
              "reducescatter"):
    for _variant in ("{b}", "{b}_", "{b}_async", "{b}_async_",
                     "grouped_{b}", "grouped_{b}_",
                     "grouped_{b}_async", "grouped_{b}_async_"):
        COLLECTIVES[_variant.format(b=_base)] = _base
COLLECTIVES.update({
    "allgather_object": "allgather",
    "broadcast_object": "broadcast",
    "broadcast_parameters": "broadcast",
    "broadcast_variables": "broadcast",
    "broadcast_optimizer_state": "broadcast",
    "barrier": "barrier",
    "join": "join",
})

GROUPED = frozenset(n for n in COLLECTIVES if n.startswith("grouped_"))
RANK_FNS = frozenset({"rank", "local_rank", "cross_rank"})
# Calls that establish the initial-state sync HVD002 looks for.
SYNC_MARKERS = frozenset({
    "broadcast_parameters", "broadcast_variables",
    "broadcast_optimizer_state", "broadcast_object", "broadcast",
    "broadcast_async", "BroadcastGlobalVariablesCallback",
    # elastic state objects restore/sync on commit — an elastic script
    # has its initial-state story covered by the State machinery
    "ArrayState", "TorchState", "TFState", "State",
})
DIST_WRAPPERS = frozenset({"DistributedOptimizer", "DistributedGradientTape"})
# jax tracing entry points: the eager engine API must not run under these
JIT_WRAPPERS = frozenset({"jit", "pmap", "shard_map"})
# Blocking handle operations (local, but fatal under tracing).
HANDLE_SYNC = frozenset({"synchronize", "wait"})


@dataclasses.dataclass
class _Ctx:
    """Lexical context threaded through the statement walk."""
    rank_line: Optional[int] = None      # innermost rank-conditional branch
    except_line: Optional[int] = None    # innermost except handler
    in_jit: bool = False                 # under a jit/shard_map trace
    func: Optional[dict] = None          # per-function mutable state

    def replace(self, **kw) -> "_Ctx":
        return dataclasses.replace(self, **kw)


class UserScriptChecker:
    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.findings: List[Finding] = []
        self.hvd_aliases: Set[str] = set()
        self.bare_collectives: Dict[str, str] = {}  # local name -> attr
        self.bare_rank_fns: Set[str] = set()
        self.bare_init_fns: Set[str] = set()
        # names bound to jax (or its submodules): jit-tracing detection
        # is gated on them so @numba.jit / @tf.function never match
        self.jax_aliases: Set[str] = set()
        self.bare_jit_fns: Set[str] = set()
        self.rank_vars: Set[str] = set()
        self.jit_wrapped_funcs: Set[str] = set()
        # HVD005 bookkeeping: name literal -> (base_op, op_repr, line)
        self._name_sigs: Dict[str, Tuple[str, Optional[str], int]] = {}
        # HVD002 bookkeeping
        self._init_call: Optional[ast.Call] = None
        self._dist_opt_call: Optional[ast.Call] = None
        self._has_sync_marker = False
        # relative imports only count as horovod-ish when analyzing the
        # package itself; user scripts' own relative modules stay inert
        self._trust_relative = "horovod_tpu" in path.replace("\\", "/")
        # one-level interprocedural view: module-level helpers that
        # directly submit a collective.  name -> (base op, def line)
        self.helper_collectives: Dict[str, Tuple[str, int]] = {}

    # -- pre-passes ----------------------------------------------------------
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    top = a.name.split(".")[0]
                    if top.startswith("horovod"):
                        self.hvd_aliases.add(a.asname or top)
                    elif top == "jax":
                        self.jax_aliases.add(a.asname or top)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "jax" or mod.startswith("jax."):
                    for a in node.names:
                        bound = a.asname or a.name
                        if a.name in JIT_WRAPPERS:
                            self.bare_jit_fns.add(bound)
                        else:
                            self.jax_aliases.add(bound)
                    continue
                hvdish = (mod.startswith("horovod")
                          or (node.level > 0 and self._trust_relative))
                if not hvdish:
                    continue
                for a in node.names:
                    bound = a.asname or a.name
                    if a.name in COLLECTIVES:
                        self.bare_collectives[bound] = a.name
                    elif a.name in RANK_FNS:
                        self.bare_rank_fns.add(bound)
                    elif a.name == "init":
                        self.bare_init_fns.add(bound)
                    else:
                        # submodule / helper object (hvd.torch, runtime,
                        # api, ...): treat as a module alias so
                        # ``runtime.rank()`` and ``api.barrier()`` resolve
                        self.hvd_aliases.add(bound)

    def _collect_rank_vars(self):
        # Simple flow: ``r = hvd.rank()`` (and zipped tuple assignments)
        # makes ``r`` rank-dependent for the whole module.  Scope-blind,
        # which is fine for a linter: a shadowed ``r`` merely over-warns.
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if (isinstance(target, ast.Tuple)
                        and isinstance(node.value, ast.Tuple)
                        and len(target.elts) == len(node.value.elts)):
                    for t, v in zip(target.elts, node.value.elts):
                        if isinstance(t, ast.Name) and self._is_rank_expr(v):
                            self.rank_vars.add(t.id)
                elif isinstance(target, ast.Name) \
                        and self._is_rank_expr(node.value):
                    self.rank_vars.add(target.id)

    def _collect_helpers(self):
        """Module-level functions that directly submit a collective —
        HVD001/003/006 see through ONE level of these: calling such a
        helper inside a rank branch / except handler / jit trace is the
        same hazard as calling the collective there directly.  Nested
        defs/lambdas are skipped: a factory that merely *defines* a
        collective-bearing closure submits nothing when called."""
        def own_calls(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    yield child
                yield from own_calls(child)

        for node in self.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for call in own_calls(node):
                coll = self._collective_name(call)
                if coll is not None:
                    self.helper_collectives[node.name] = (
                        COLLECTIVES[coll], node.lineno)
                    break

    def _collect_jit_wrapped(self):
        # functions passed positionally into jax.jit(f) / shard_map(f, ...)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and self._is_jit_wrapper(node.func):
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        self.jit_wrapped_funcs.add(a.id)

    # -- predicates ----------------------------------------------------------
    def _is_hvd(self, node: ast.expr) -> bool:
        return isinstance(node, ast.Name) and node.id in self.hvd_aliases

    def _hvd_rooted(self, fn: ast.expr) -> bool:
        """Does this call target provably live in the horovod package?
        (``hvd.x``, ``hvd.elastic.x``, or a name imported from it.)"""
        if isinstance(fn, ast.Attribute):
            root = fn.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) \
                and root.id in self.hvd_aliases
        if isinstance(fn, ast.Name):
            return (fn.id in self.hvd_aliases
                    or fn.id in self.bare_collectives
                    or fn.id in self.bare_init_fns)
        return False

    def _collective_name(self, call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVES \
                and self._is_hvd(fn.value):
            return fn.attr
        if isinstance(fn, ast.Name) and fn.id in self.bare_collectives:
            return self.bare_collectives[fn.id]
        return None

    def _is_rank_expr(self, node: ast.expr) -> bool:
        """True when the expression's value depends on this process's rank."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = sub.func
                if isinstance(fn, ast.Attribute) and fn.attr in RANK_FNS \
                        and self._is_hvd(fn.value):
                    return True
                if isinstance(fn, ast.Name) and fn.id in self.bare_rank_fns:
                    return True
            elif isinstance(sub, ast.Name) and sub.id in self.rank_vars:
                return True
        return False

    def _is_jit_wrapper(self, fn: ast.expr) -> bool:
        # only jax tracing counts: numba.jit / tf.function compile the
        # python body, where the eager engine API works fine
        if isinstance(fn, ast.Attribute) and fn.attr in JIT_WRAPPERS:
            root = fn.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) \
                and root.id in self.jax_aliases
        if isinstance(fn, ast.Name):
            return fn.id in self.bare_jit_fns
        return False

    def _is_jit_decorator(self, dec: ast.expr) -> bool:
        # @jax.jit / @jit / @partial(jax.jit, ...) / @jax.jit(...)
        if self._is_jit_wrapper(dec):
            return True
        if isinstance(dec, ast.Call):
            if self._is_jit_wrapper(dec.func):
                return True
            fn = dec.func
            partial = (isinstance(fn, ast.Name) and fn.id == "partial") or \
                (isinstance(fn, ast.Attribute) and fn.attr == "partial")
            if partial and dec.args \
                    and self._is_jit_wrapper(dec.args[0]):
                return True
        return False

    def _is_unordered(self, node: ast.expr) -> bool:
        """Does iterating this expression yield a cross-process-unstable
        order?  (set/frozenset literals, comprehensions over them, ...)"""
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self._is_unordered(node.generators[0].iter)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name):
                if fn.id in ("set", "frozenset"):
                    return True
                if fn.id == "sorted":
                    return False  # sorted() restores a total order
                if fn.id in ("list", "tuple", "reversed"):
                    return bool(node.args) and self._is_unordered(node.args[0])
        return False

    # -- the walk ------------------------------------------------------------
    def run(self) -> List[Finding]:
        self._collect_imports()
        self._collect_helpers()
        self._collect_rank_vars()
        self._collect_jit_wrapped()
        self._walk_stmts(self.tree.body, _Ctx(func={"divergent": None}))
        self._check_hvd002()
        return self.findings

    def _add(self, code: str, node: ast.AST, message: str):
        self.findings.append(Finding(
            code=code, path=self.path, line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0), message=message))

    def _walk_stmts(self, stmts, ctx: _Ctx):
        for stmt in stmts:
            self._walk_stmt(stmt, ctx)

    def _walk_stmt(self, stmt: ast.stmt, ctx: _Ctx):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            jit = (ctx.in_jit
                   or stmt.name in self.jit_wrapped_funcs
                   or any(self._is_jit_decorator(d)
                          for d in stmt.decorator_list))
            for d in stmt.decorator_list:
                self._scan_expr(d, ctx)
            self._walk_stmts(stmt.body, ctx.replace(
                in_jit=jit, func={"divergent": None}))
            return
        if isinstance(stmt, ast.ClassDef):
            self._walk_stmts(stmt.body, ctx)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, ctx)
            rank = self._is_rank_expr(stmt.test)
            sub = ctx.replace(rank_line=stmt.lineno) if rank else ctx
            loop = isinstance(stmt, ast.While)
            saved_loop_exit = (ctx.func.get("divergent_loop")
                               if loop and ctx.func is not None else None)
            self._walk_stmts(stmt.body, sub)
            self._walk_stmts(stmt.orelse, sub)
            if loop and ctx.func is not None:
                # break/continue inside this while exit THIS loop only:
                # code after it is reached by every rank
                ctx.func["divergent_loop"] = saved_loop_exit
            if rank and ctx.func is not None \
                    and ctx.func["divergent"] is None:
                # a rank-conditional branch that can leave the function
                # makes everything after it rank-divergent (HVD003); one
                # that can only leave the LOOP ITERATION (break/continue)
                # taints the rest of the enclosing loop body, never the
                # code after the loop
                if any(isinstance(s, (ast.Return, ast.Raise))
                       for s in stmt.body + stmt.orelse):
                    ctx.func["divergent"] = stmt.lineno
                elif not loop and ctx.func.get("divergent_loop") is None \
                        and any(isinstance(s, (ast.Break, ast.Continue))
                                for s in stmt.body + stmt.orelse):
                    ctx.func["divergent_loop"] = stmt.lineno
            return
        if isinstance(stmt, ast.Try):
            self._walk_stmts(stmt.body, ctx)
            for handler in stmt.handlers:
                self._walk_stmts(handler.body,
                                 ctx.replace(except_line=handler.lineno))
            self._walk_stmts(stmt.orelse, ctx)
            self._walk_stmts(stmt.finalbody, ctx)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, ctx)
            saved_loop_exit = (ctx.func.get("divergent_loop")
                               if ctx.func is not None else None)
            self._walk_stmts(stmt.body, ctx)
            self._walk_stmts(stmt.orelse, ctx)
            if ctx.func is not None:
                ctx.func["divergent_loop"] = saved_loop_exit
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, ctx)
            self._walk_stmts(stmt.body, ctx)
            return
        if isinstance(stmt, ast.Match):
            # match on a rank-dependent subject is a rank-conditional
            # branch, same as `if` on one
            self._scan_expr(stmt.subject, ctx)
            rank = self._is_rank_expr(stmt.subject)
            sub = ctx.replace(rank_line=stmt.lineno) if rank else ctx
            for case in stmt.cases:
                if case.guard is not None:
                    self._scan_expr(case.guard, sub)
                body_ctx = sub
                if not rank and case.guard is not None \
                        and self._is_rank_expr(case.guard):
                    body_ctx = ctx.replace(rank_line=case.pattern.lineno)
                self._walk_stmts(case.body, body_ctx)
            return
        # leaf statements: scan the contained expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child, ctx)

    def _scan_expr(self, node: ast.expr, ctx: _Ctx):
        if isinstance(node, ast.IfExp):
            self._scan_expr(node.test, ctx)
            sub = (ctx.replace(rank_line=node.lineno)
                   if self._is_rank_expr(node.test) else ctx)
            self._scan_expr(node.body, sub)
            self._scan_expr(node.orelse, sub)
            return
        if isinstance(node, ast.Call):
            self._check_call(node, ctx)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword, ast.comprehension)):
                if isinstance(child, ast.keyword):
                    self._scan_expr(child.value, ctx)
                elif isinstance(child, ast.comprehension):
                    self._scan_expr(child.iter, ctx)
                    for cond in child.ifs:
                        self._scan_expr(cond, ctx)
                else:
                    self._scan_expr(child, ctx)

    # -- per-call rules ------------------------------------------------------
    def _check_call(self, call: ast.Call, ctx: _Ctx):
        fn = call.func
        callname = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)

        # HVD002 state only moves on provably-horovod calls: an
        # unrelated udp_sock.broadcast() or foreign State() must neither
        # satisfy nor trigger the rule
        if callname in DIST_WRAPPERS and self._dist_opt_call is None \
                and self._hvd_rooted(fn):
            self._dist_opt_call = call
        if callname in SYNC_MARKERS and self._hvd_rooted(fn):
            self._has_sync_marker = True
        if self._init_call is None and callname == "init" and (
                (isinstance(fn, ast.Attribute) and self._is_hvd(fn.value))
                or (isinstance(fn, ast.Name)
                    and fn.id in self.bare_init_fns)):
            self._init_call = call

        # generic .wait()/.synchronize() receivers can't be proven to be
        # horovod handles, so this only applies in modules that import
        # horovod at all — never to unrelated jax code
        if ctx.in_jit and callname in HANDLE_SYNC \
                and isinstance(fn, ast.Attribute) \
                and (self.hvd_aliases or self.bare_collectives):
            self._add("HVD006", call,
                      f"blocking .{callname}() inside a jit/shard_map-traced "
                      f"function; the trace cannot await a host-side handle")

        coll = self._collective_name(call)
        if coll is None:
            if isinstance(fn, ast.Name) and fn.id in self.helper_collectives:
                self._check_helper_call(call, fn.id, ctx)
            return

        if ctx.rank_line is not None:
            self._add("HVD001", call,
                      f"collective '{coll}' submitted inside a branch "
                      f"conditioned on the process rank (branch at line "
                      f"{ctx.rank_line}); ranks skipping the branch never "
                      f"submit it and the others deadlock")
        if ctx.except_line is not None:
            self._add("HVD003", call,
                      f"collective '{coll}' inside an except handler "
                      f"(line {ctx.except_line}); an exception raised on a "
                      f"subset of ranks strands the rest")
        elif ctx.func is not None and self._divergent_line(ctx) is not None:
            self._add("HVD003", call,
                      f"collective '{coll}' after a rank-conditional "
                      f"early exit (line {self._divergent_line(ctx)}); only "
                      f"the ranks that did not exit reach this call")
        if ctx.in_jit:
            self._add("HVD006", call,
                      f"eager collective '{coll}' inside a jit/shard_map-"
                      f"traced function; it blocks on the background engine "
                      f"under tracing — use the in-jit form "
                      f"(hvd.{COLLECTIVES[coll]}_p)")
        if coll in GROUPED and call.args \
                and self._is_unordered(call.args[0]):
            self._add("HVD004", call,
                      f"grouped collective '{coll}' fed from an "
                      f"unordered set iteration; member order can differ "
                      f"across processes, diverging the fusion plan")
        self._check_hvd005(call, COLLECTIVES[coll])

    @staticmethod
    def _divergent_line(ctx: _Ctx):
        """Line of the rank-divergent exit governing this point: a
        function-leaving one (return/raise — taints the rest of the
        function), else a loop-iteration-leaving one (break/continue —
        taints only the rest of the enclosing loop body)."""
        if ctx.func is None:
            return None
        if ctx.func["divergent"] is not None:
            return ctx.func["divergent"]
        return ctx.func.get("divergent_loop")

    def _check_helper_call(self, call: ast.Call, name: str, ctx: _Ctx):
        """HVD001/003/006 through one helper level: ``name`` is a
        module-level function that directly submits a collective."""
        base_op, def_line = self.helper_collectives[name]
        via = (f"via helper '{name}' (line {def_line}), which submits "
               f"'{base_op}'")
        if ctx.rank_line is not None:
            self._add("HVD001", call,
                      f"collective submitted {via}, inside a branch "
                      f"conditioned on the process rank (branch at line "
                      f"{ctx.rank_line}); ranks skipping the branch never "
                      f"submit it and the others deadlock")
        if ctx.except_line is not None:
            self._add("HVD003", call,
                      f"collective submitted {via}, inside an except "
                      f"handler (line {ctx.except_line}); an exception "
                      f"raised on a subset of ranks strands the rest")
        elif ctx.func is not None and self._divergent_line(ctx) is not None:
            self._add("HVD003", call,
                      f"collective submitted {via}, after a "
                      f"rank-conditional early exit (line "
                      f"{self._divergent_line(ctx)}); only the ranks that "
                      f"did not exit reach this call")
        if ctx.in_jit:
            self._add("HVD006", call,
                      f"eager collective submitted {via}, inside a "
                      f"jit/shard_map-traced function; it blocks on the "
                      f"background engine under tracing — use the in-jit "
                      f"form (hvd.{base_op}_p)")

    def _check_hvd005(self, call: ast.Call, base_op: str):
        name = None
        op_repr: Optional[str] = None
        for kw in call.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                name = kw.value.value
            elif kw.arg == "op":
                op_repr = ast.unparse(kw.value)
            elif kw.arg == "average":
                op_repr = f"average={ast.unparse(kw.value)}"
        if name is None:
            return
        sig = (base_op, op_repr)
        prev = self._name_sigs.get(name)
        if prev is None:
            self._name_sigs[name] = (base_op, op_repr, call.lineno)
        elif (prev[0], prev[1]) != sig:
            self._add("HVD005", call,
                      f"tensor name '{name}' reused with a different "
                      f"signature: {prev[0]}/op={prev[1]} at line {prev[2]} "
                      f"vs {base_op}/op={op_repr} here; negotiation matches "
                      f"by name and would pair incompatible requests")

    def _check_hvd002(self):
        if self._init_call is None or self._dist_opt_call is None:
            return
        if self._has_sync_marker:
            return
        self._add("HVD002", self._dist_opt_call,
                  "DistributedOptimizer is used but no initial-state "
                  "broadcast (broadcast_parameters / broadcast_object / "
                  "elastic State) follows hvd.init(); differently-seeded "
                  "workers silently train diverging replicas")


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    return UserScriptChecker(tree, path).run()
