"""Collective-schedule extraction from jaxprs (hvdsched; HVD210/HVD211).

The fused-psum plan a compiled step issues is the framework's most
safety-critical invariant: every replica must execute the same
collectives, in the same order, over the same axes — and the next wave
of perf work (ZeRO-style sharded updates, per-bucket compressed
collectives, async bucket dispatch; ROADMAP items 1–3) rewrites exactly
that plan.  This module makes the plan a *reviewable artifact*: it
traces a step function to a jaxpr **on CPU** (no devices, no mesh — an
``axis_env`` stands in for the hardware), walks the jaxpr through every
``pjit``/``scan``/``cond``/``while``/custom-derivative sub-jaxpr, and
emits the ordered collective records as stable JSON:

    (primitive, axis names, operand shapes/dtypes, sub-jaxpr path,
     fusion-bucket id, primitive params)

The fusion-bucket id rides the jaxpr's name stack: ``fused_reduce_tree``
traces each bucket under ``jax.named_scope("hvd_bucket<i>")``.

Two checks ride on top:

* **snapshot check (HVD211)** — ``tests/schedules/*.json`` records the
  schedule of every builtin entry point; ``tools/hvdsched --check``
  re-traces and diffs, so any change to the fused-psum plan (bucket
  order, threshold semantics, a new collective) is an explicit,
  reviewed snapshot update — and an accidental one fails CI.
* **consistency check (HVD210)** — the *canonical* schedule (shapes and
  axis sizes erased) must be identical across mesh sizes and any other
  configuration axis: a schedule that varies with rank or world size
  deadlocks the compiled programs against each other.

jax (and the framework's runtime deps) are imported lazily: importing
``horovod_tpu.analysis`` alone still costs only the standard library.
"""

from __future__ import annotations

import dataclasses
import difflib
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .report import Finding

#: jaxpr primitives that lower to cross-replica communication.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "reduce_scatter",
    "ppermute", "pbroadcast", "psum_scatter",
})

#: eqn params recorded verbatim (JSON-serializable, order-stable).
#: ``axis_size`` is recorded but ERASED from the canonical form — it
#: legitimately varies with the mesh.
_RECORDED_PARAMS = (
    "axis_index_groups", "perm", "all_gather_dimension",
    "scatter_dimension", "split_axis", "concat_axis", "tiled",
    "axis_size",
)

_BUCKET_RE = re.compile(r"hvd_bucket(\d+)")

#: Snapshot format version (bump on any JSON layout change).
FORMAT = 1


# ---------------------------------------------------------------------------
# schedule model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CollectiveRecord:
    """One collective in trace order."""
    index: int
    prim: str
    axes: List[str]
    inputs: List[str]            # "float32[8x16]" aval strings
    outputs: List[str]
    path: str                    # sub-jaxpr context, "" = top level
    bucket: Optional[int]        # fusion bucket id from the name stack
    params: Dict[str, Any]

    def as_dict(self) -> dict:
        return {"index": self.index, "prim": self.prim, "axes": self.axes,
                "inputs": self.inputs, "outputs": self.outputs,
                "path": self.path, "bucket": self.bucket,
                "params": self.params}

    def canonical(self) -> Tuple:
        """Shape-and-mesh-erased identity for HVD210 comparisons."""
        params = {k: v for k, v in self.params.items()
                  if k not in ("axis_size", "perm")}
        return (self.prim, tuple(self.axes), self.path, self.bucket,
                tuple(sorted((k, json.dumps(v)) for k, v in params.items())))


@dataclasses.dataclass
class Schedule:
    entry: str
    axis_env: List[Tuple[str, int]]
    records: List[CollectiveRecord]

    def to_json(self) -> str:
        payload = {
            "format": FORMAT,
            "entry": self.entry,
            "axis_env": [[n, int(s)] for n, s in self.axis_env],
            "records": [r.as_dict() for r in self.records],
        }
        return json.dumps(payload, indent=2, sort_keys=True) + "\n"

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        data = json.loads(text)
        if int(data.get("format", -1)) != FORMAT:
            raise ValueError(
                f"schedule snapshot format {data.get('format')} != "
                f"supported format {FORMAT}; re-record with "
                f"tools/hvdsched --update")
        records = [CollectiveRecord(
            index=r["index"], prim=r["prim"], axes=list(r["axes"]),
            inputs=list(r["inputs"]), outputs=list(r["outputs"]),
            path=r["path"], bucket=r["bucket"],
            params=dict(r["params"])) for r in data["records"]]
        return cls(entry=data["entry"],
                   axis_env=[(n, int(s)) for n, s in data["axis_env"]],
                   records=records)

    def canonical(self) -> List[Tuple]:
        return [r.canonical() for r in self.records]


# ---------------------------------------------------------------------------
# jaxpr walk
# ---------------------------------------------------------------------------

def _aval_str(aval) -> str:
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dtype is None or shape is None:
        return str(aval)
    return f"{dtype.name}[{'x'.join(str(int(d)) for d in shape)}]"


def _axis_names(eqn) -> List[str]:
    raw = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return [str(a) for a in raw if isinstance(a, str)]


def _bucket_of(eqn) -> Optional[int]:
    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:  # noqa: BLE001 - source info is best-effort
        return None
    m = _BUCKET_RE.search(stack)
    return int(m.group(1)) if m else None


def _jsonable(value) -> Any:
    if isinstance(value, (tuple, list)):
        return [_jsonable(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


def _sub_jaxprs(eqn) -> List[Tuple[str, Any]]:
    """(context label, inner jaxpr) for every jaxpr-valued param, in a
    deterministic order.  Duck-typed — no jax import at module scope:
    a ClosedJaxpr has ``.jaxpr``, a Jaxpr has ``.eqns``."""
    out: List[Tuple[str, Any]] = []
    prim = eqn.primitive.name
    for key in sorted(eqn.params):
        val = eqn.params[key]
        candidates: List[Tuple[str, Any]] = []
        if hasattr(val, "eqns") or hasattr(val, "jaxpr"):
            candidates.append(("", val))
        elif isinstance(val, (tuple, list)):
            for i, v in enumerate(val):
                if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                    candidates.append((f"[{i}]", v))
        for suffix, v in candidates:
            inner = v.jaxpr if hasattr(v, "jaxpr") else v
            label = f"{prim}:{key}{suffix}"
            if prim == "pjit":
                name = eqn.params.get("name")
                if name:
                    label = f"pjit<{name}>"
            out.append((label, inner))
    return out


def _walk(jaxpr, path: str, records: List[CollectiveRecord]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            params = {k: _jsonable(eqn.params[k])
                      for k in _RECORDED_PARAMS if k in eqn.params}
            records.append(CollectiveRecord(
                index=len(records), prim=name, axes=_axis_names(eqn),
                inputs=[_aval_str(v.aval) for v in eqn.invars],
                outputs=[_aval_str(v.aval) for v in eqn.outvars],
                path=path, bucket=_bucket_of(eqn), params=params))
        for label, inner in _sub_jaxprs(eqn):
            _walk(inner, f"{path}/{label}" if path else label, records)


def trace_schedule(fn, example_args: Sequence,
                   axis_env: Sequence[Tuple[str, int]] = (),
                   entry: str = "<fn>") -> Schedule:
    """Trace ``fn(*example_args)`` to a jaxpr on CPU and extract its
    collective schedule.  ``example_args`` may be arrays or
    ``jax.ShapeDtypeStruct``s (pytrees of either)."""
    import jax
    closed = jax.make_jaxpr(
        fn, axis_env=[(n, int(s)) for n, s in axis_env])(*example_args)
    records: List[CollectiveRecord] = []
    _walk(closed.jaxpr, "", records)
    return Schedule(entry=entry, axis_env=list(axis_env), records=records)


# ---------------------------------------------------------------------------
# diffs and checks
# ---------------------------------------------------------------------------

def diff_schedules(expected: Schedule, actual: Schedule) -> List[str]:
    """Human-readable unified diff of two schedules' JSON forms
    (empty when identical)."""
    exp, act = expected.to_json().splitlines(), actual.to_json().splitlines()
    return list(difflib.unified_diff(
        exp, act, fromfile=f"expected/{expected.entry}",
        tofile=f"actual/{actual.entry}", lineterm=""))


def check_snapshot(snapshot_path: str, actual: Schedule) -> List[Finding]:
    """HVD211 when ``actual`` drifted from the committed snapshot."""
    try:
        with open(snapshot_path, "r", encoding="utf-8") as f:
            expected = Schedule.from_json(f.read())
    except FileNotFoundError:
        return [Finding("HVD211", snapshot_path, 1, 0,
                        f"no committed snapshot for entry "
                        f"'{actual.entry}' — record one with "
                        f"tools/hvdsched --update")]
    except (ValueError, KeyError) as exc:
        return [Finding("HVD211", snapshot_path, 1, 0,
                        f"unreadable snapshot: {exc}")]
    diff = diff_schedules(expected, actual)
    if not diff:
        return []
    head = next((l for l in diff if l.startswith(("+", "-"))
                 and not l.startswith(("+++", "---"))), "")
    return [Finding("HVD211", snapshot_path, 1, 0,
                    f"collective schedule for entry '{actual.entry}' "
                    f"drifted from its snapshot ({len(expected.records)} "
                    f"-> {len(actual.records)} records; first change: "
                    f"{head.strip()!r}) — intentional changes are "
                    f"re-recorded with tools/hvdsched --update")]


def check_consistency(variants: Sequence[Tuple[str, Schedule]]
                      ) -> List[Finding]:
    """HVD210 when any variant's canonical (shape/mesh-erased) schedule
    differs from the first — the cross-configuration invariant."""
    findings: List[Finding] = []
    if not variants:
        return findings
    base_label, base = variants[0]
    base_canon = base.canonical()
    for label, sched in variants[1:]:
        canon = sched.canonical()
        if canon == base_canon:
            continue
        detail = f"{len(base_canon)} vs {len(canon)} collectives"
        for i, (a, b) in enumerate(zip(base_canon, canon)):
            if a != b:
                detail = (f"record {i}: {a[0]} over {a[1]} vs "
                          f"{b[0]} over {b[1]}")
                break
        findings.append(Finding(
            "HVD210", base.entry, 1, 0,
            f"collective schedule differs between configuration "
            f"'{base_label}' and '{label}' ({detail}); every replica "
            f"must issue the same collectives in the same order, or the "
            f"compiled programs deadlock against each other"))
    return findings


# ---------------------------------------------------------------------------
# builtin entry points: the framework's in-jit bucketed reduction path
# ---------------------------------------------------------------------------

_AXIS = "workers"
#: Small threshold so the representative gradient pytree splits into
#: multiple buckets — the snapshot then pins bucket ORDER, not just count.
_THRESHOLD = 1024


def _grads_spec():
    """Representative mixed-dtype gradient pytree (ShapeDtypeStructs:
    nothing is materialized).  Sized so float32 splits across two
    buckets at ``_THRESHOLD`` while bfloat16 fuses into one."""
    import jax
    import jax.numpy as jnp
    sds = jax.ShapeDtypeStruct
    return {
        "dense/bias": sds((16,), jnp.float32),
        "dense/kernel": sds((8, 16), jnp.float32),
        "embed/table": sds((32, 8), jnp.bfloat16),
        "head/bias": sds((4,), jnp.bfloat16),
        "head/kernel": sds((64, 4), jnp.float32),
    }


def _entry_fused_reduce():
    """The in-jit fusion-buffer path: one psum per planned bucket."""
    from ..optim.distributed import fused_reduce_tree

    def step(grads):
        return fused_reduce_tree(grads, _AXIS, op="average",
                                 threshold_bytes=_THRESHOLD)
    return step, (_grads_spec(),)


def _entry_distopt_step():
    """A full DistributedOptimizer update (optax adam inner): the
    schedule users actually compile."""
    import jax
    import jax.numpy as jnp
    import optax
    from ..optim.distributed import DistributedOptimizer

    # sharded_update and wire_format pinned off: snapshots must not flip
    # with the operator's HOROVOD_SHARDED_UPDATE / HOROVOD_COMPRESSION
    # env (each rewrite has its own entry)
    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=_AXIS,
                              threshold_bytes=_THRESHOLD,
                              sharded_update=False, wire_format="none")
    spec = _grads_spec()
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), spec)
    state = tx.init(params)

    def step(grads, params):
        updates, _ = tx.update(grads, state, params)
        return updates
    return step, (spec, spec)


def _entry_jit_fused_reduce():
    """fused_reduce_tree under jax.jit: pins that the walk descends
    into pjit sub-jaxprs (the schedule must not go dark under jit)."""
    import jax
    from ..optim.distributed import fused_reduce_tree

    @jax.jit
    def inner(grads):
        return fused_reduce_tree(grads, _AXIS, op="sum",
                                 threshold_bytes=_THRESHOLD)

    def step(grads):
        return inner(grads)
    return step, (_grads_spec(),)


def _entry_sharded_distopt_step():
    """The ZeRO-style sharded step (HOROVOD_SHARDED_UPDATE): per bucket
    reduce_scatter → 1/N inner update → all_gather, never a full-gradient
    psum (arXiv:2004.13336; ROADMAP item 1)."""
    import optax
    from ..optim.distributed import DistributedOptimizer

    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=_AXIS,
                              threshold_bytes=_THRESHOLD,
                              sharded_update=True, wire_format="none")
    spec = _grads_spec()

    def step(grads, params):
        # the sharded optimizer state is per-worker (1/N bucket tiles),
        # so init runs INSIDE the mapped program, like real sharded
        # steps do; init issues no collectives, so the schedule is the
        # update's reduce_scatter/all_gather plan alone
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        return updates
    return step, (spec, spec)


def _entry_quantized_distopt_step():
    """The quantized-wire step (HOROVOD_COMPRESSION=int8): per bucket the
    full-width psum is rewritten into quantize → all_to_all int8 tiles +
    fp32 scales → fp32 accumulate → all_gather quantized tiles
    (EQuARX-class staging, error feedback in _DistState.residual;
    ROADMAP item 2).  The snapshot pins the wire dtype: int8 avals in
    the exchange records ARE the compressed-bytes claim."""
    import optax
    from ..optim.distributed import DistributedOptimizer

    # explicit format + block so the snapshot cannot flip with the
    # operator's HOROVOD_COMPRESSION / block-size env; block 16 keeps
    # the tiny representative pytree multi-block
    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=_AXIS,
                              threshold_bytes=_THRESHOLD,
                              sharded_update=False, wire_format="int8",
                              wire_block_size=16)
    spec = _grads_spec()

    def step(grads, params):
        # the error-feedback residual is per-worker state carried in
        # _DistState, so init runs inside the traced program; it issues
        # no collectives of its own
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        return updates
    return step, (spec, spec)


#: toy scanned-model geometry for the overlapped entry (layers, width,
#: vocab rows) — small enough to trace fast, deep enough that the
#: backward scan carries multiple per-layer dispatches.
_OVERLAP_L, _OVERLAP_D, _OVERLAP_V = 3, 8, 5


def _overlap_params_spec():
    """Representative scanned-model param pytree: a stacked fp32+bf16
    layer stack (two buckets per layer at ``_THRESHOLD``) plus
    non-scanned root leaves (embed, final_norm)."""
    import jax
    import jax.numpy as jnp
    sds = jax.ShapeDtypeStruct
    L, D, V = _OVERLAP_L, _OVERLAP_D, _OVERLAP_V
    return {
        "embed": sds((V, D), jnp.float32),
        "layers": {
            "b": sds((L, D), jnp.float32),
            "s": sds((L, D), jnp.bfloat16),
            "w": sds((L, D, D), jnp.float32),
        },
        "final_norm": sds((D,), jnp.float32),
    }


def _entry_overlapped_distopt_step():
    """The overlapped-dispatch step (HOROVOD_OVERLAP, ROADMAP item 3):
    the scanned toy model's grad taps fire each layer's fusion buckets
    INSIDE the backward scan (records sit in a scan sub-jaxpr path, in
    reverse layer order structurally), and the non-scanned root leaves
    reduce at the end of backprop — no post-backprop fused block.  The
    snapshot's record positions ARE the overlap claim."""
    import jax
    import jax.numpy as jnp
    import optax
    from ..optim import overlap as _ov
    from ..optim.distributed import DistributedOptimizer

    # overlap pinned on, everything else pinned off/none: the snapshot
    # must not flip with the operator's env (each rewrite has its own
    # entry)
    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=_AXIS,
                              threshold_bytes=_THRESHOLD,
                              sharded_update=False, wire_format="none",
                              overlap=True)

    def model_loss(params, x):
        params = _ov.tap_root(params)
        h = x @ params["embed"]

        def body(h, lp):
            lp = _ov.grad_tap(lp)
            return (jnp.tanh(h @ lp["w"] + lp["b"])
                    * lp["s"].astype(h.dtype), None)

        h, _ = jax.lax.scan(body, h, params["layers"])
        return (h * params["final_norm"]).sum()

    def step(params, x):
        # per-step state init inside the traced program (init issues no
        # collectives); the context arms the model taps for this trace
        state = tx.init(params)
        with _ov.overlapped_backprop(tx):
            _loss, grads = jax.value_and_grad(model_loss)(params, x)
        updates, _ = tx.update(grads, state, params)
        return updates

    spec = _overlap_params_spec()
    x = jax.ShapeDtypeStruct((2, _OVERLAP_V), jnp.float32)
    return step, (spec, x)


def _entry_health_distopt_step():
    """The health-tapped step (HOROVOD_HEALTH_TAPS; ISSUE 13): the
    per-bucket numerics taps are LOCAL reductions (no collectives of
    their own), but the divergence sentinel adds one ``all_gather`` of
    the per-bucket param/opt-state checksum vector under its cadence
    ``cond`` — that gather, and nothing else, is the schedule delta vs
    the plain ``distopt_step`` entry.  health pinned ON with
    ``health_check_every=1`` (env-independent: an explicit ``health=``
    wins over HOROVOD_HEALTH_TAPS, and the first step's count=1 takes
    the sentinel branch), everything else pinned off."""
    import jax
    import jax.numpy as jnp
    import optax
    from ..optim.distributed import DistributedOptimizer

    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=_AXIS,
                              threshold_bytes=_THRESHOLD,
                              sharded_update=False, wire_format="none",
                              health=True, health_check_every=1)
    spec = _grads_spec()
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), spec)
    state = tx.init(params)

    def step(grads, params):
        updates, _ = tx.update(grads, state, params)
        return updates
    return step, (spec, spec)


#: fixed model axis of the spec-aware (fsdp) entry: the consistency
#: check varies the DATA axis through ``_AXIS`` — mesh shapes 2x2 and
#: 4x2 — while the model-shard degree stays 2.
_FSDP_MODEL = 2


def _fsdp_grads_spec():
    """Representative spec-aware gradient pytree: LOCAL (model-shard)
    shapes for the sharded leaves, full shapes for the replicated ones,
    in both dtypes — so the plan carries a sharded and a replicated
    bucket per dtype (mixed-spec leaves must never fuse)."""
    import jax
    import jax.numpy as jnp
    sds = jax.ShapeDtypeStruct
    return {
        # full (8, 16) sharded dim0 over hvd_model=2 -> local (4, 16)
        "dense/kernel": sds((4, 16), jnp.float32),
        "dense/bias": sds((16,), jnp.float32),
        # full (32, 8) sharded dim0 -> local (16, 8)
        "embed/table": sds((16, 8), jnp.bfloat16),
        "head/bias": sds((4,), jnp.bfloat16),
        # full (64, 4) sharded dim1 -> local (64, 2)
        "head/kernel": sds((64, 2), jnp.float32),
    }


def _entry_fsdp_distopt_step():
    """The mesh-axis-aware composed step (ISSUE 14): param_specs over a
    2-D (data x model) mesh + ZeRO sharded update.  Model-sharded
    buckets reduce-scatter their LOCAL shard over the data axis alone —
    no model-axis collective, no full-width gradient anywhere;
    replicated buckets psum over the model axis first, then tile over
    data; every bucket's updates all_gather over data only.  Specs and
    model_axes pinned explicitly (env-independent: the snapshot must
    not flip with HOROVOD_MODEL_AXES or the mesh context)."""
    import optax
    from jax.sharding import PartitionSpec as P
    from ..optim.distributed import DistributedOptimizer

    specs = {
        "dense/kernel": P("hvd_model"),
        "dense/bias": P(),
        "embed/table": P("hvd_model"),
        "head/bias": P(),
        "head/kernel": P(None, "hvd_model"),
    }
    tx = DistributedOptimizer(optax.adam(1e-3), axis_name=_AXIS,
                              threshold_bytes=_THRESHOLD,
                              sharded_update=True, wire_format="none",
                              param_specs=specs,
                              model_axes=("hvd_model",))
    spec = _fsdp_grads_spec()

    def step(grads, params):
        # 1/N-tile state init runs inside the mapped program (issues no
        # collectives); grads arrive as the locally-owned shards,
        # pre-reduced over the model axis by the model's transposes
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        return updates
    return step, (spec, spec), (("hvd_model", _FSDP_MODEL),)


#: fixed local (ICI) axis of the hierarchical tail entry: the
#: consistency check varies the CROSS (DCN) axis — the one the tail
#: policy rewrites — through ``_AXIS``.
_TAIL_LOCAL = 2


def _entry_tail_distopt_step():
    """The tail-tolerant hierarchical step (HOROVOD_TAIL_POLICY; ISSUE
    11, OptiReduce arXiv:2310.06993): per bucket psum_scatter over the
    local (ICI) axis, then the REWRITTEN DCN stage — a pmin
    membership-agreement round over the mask plus an all_gather of
    per-group chunk contributions (the transpose-allreduce shape that
    makes a missing host's slot substitutable), never a cross-group
    psum — then the local all_gather.  Policy pinned to ``stale`` (the
    maximally rewritten schedule; ``bounded`` keeps the psum shape and
    is pinned by tests/test_tail.py), mask/state initialized inside the
    traced step so the snapshot cannot flip with the operator's
    HOROVOD_TAIL_* env."""
    import jax
    import jax.numpy as jnp
    import optax
    from ..compat import axis_size
    from ..optim.distributed import fused_tail_reduce_tree

    spec = _grads_spec()
    tx = optax.adam(1e-3)

    def step(grads, params):
        present = jnp.ones((axis_size(_AXIS),), jnp.float32)
        reduced, _state = fused_tail_reduce_tree(
            grads, _AXIS, "hvd_local", op="average",
            threshold_bytes=_THRESHOLD, tail_policy="stale",
            present=present, max_staleness=3)
        state = tx.init(params)
        updates, _ = tx.update(reduced, state, params)
        return updates
    return step, (spec, spec), (("hvd_local", _TAIL_LOCAL),)


def _entry_serve_forward_step():
    """The serving data path (ISSUE 15): one batched ragged KV-cache
    decode step (prefill + per-row-positioned greedy decode scan) of
    the llama family, traced under the worker mesh axis.  Serving is
    pure data parallelism — a forward must NEVER negotiate a gradient
    collective (a straggling replica must stall only its own leases,
    and a worker joining or leaving mid-traffic must not deadlock
    peers) — so the pinned schedule is EMPTY: a regression that routes
    serving through the gradient plane (a stray psum from a reused
    training step, a health tap's sentinel gather) adds records and
    fails HVD211 structurally."""
    import jax
    import jax.numpy as jnp
    from ..models import llama
    from ..models.generate import batched_greedy_decode

    cfg = llama.tiny(vocab=64, seq=32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    def step(tokens, lengths):
        return batched_greedy_decode(params, cfg, tokens, lengths,
                                     max_new_tokens=4, max_len=20)

    sds = jax.ShapeDtypeStruct
    return step, (sds((2, 16), jnp.int32), sds((2,), jnp.int32))


#: fixed model axis of the mesh-sliced serving entry: the consistency
#: check varies the worker axis through ``_AXIS`` (unused by the step,
#: like serve_forward_step) while the shard degree stays 2.
_SERVE_MP = 2


def _entry_serve_mp_forward_step():
    """The model-parallel serving data path (ISSUE 20): the same
    batched ragged decode as ``serve_forward_step``, but the weights
    arrive as mesh-slice local shards and are ``spec_all_gather``ed
    over the model axis inside the step (serving/worker.py
    MeshSlicedForward).  The pinned schedule contains ONLY the spec
    gather hops — weight movement, never gradient movement.  The
    ``serve_forward_step`` empty-schedule pin generalizes: a gradient
    collective appearing here (a stray psum from a reused training
    step, a health tap riding the serving mesh) changes the record set
    and fails HVD211 structurally, exactly like a non-empty schedule
    would fail the DP entry.  Specs come from ``fsdp_param_specs`` —
    serving shards the same way training's FSDP path does, so the
    snapshot also pins that the two planes agree on what a shard is."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..models import llama
    from ..models.generate import batched_greedy_decode
    from ..training import fsdp_param_specs, spec_all_gather

    cfg = llama.tiny(vocab=64, seq=32)
    shapes = jax.eval_shape(
        lambda: llama.init_params(cfg, jax.random.PRNGKey(0)))
    specs = fsdp_param_specs(shapes, _SERVE_MP, axis="hvd_serve_mp")

    def local_sds(spec, leaf):
        dims = list(leaf.shape)
        for dim, entry in enumerate(spec):
            axes = entry if isinstance(entry, tuple) else (entry,)
            if "hvd_serve_mp" in axes:
                dims[dim] //= _SERVE_MP
                break
        return jax.ShapeDtypeStruct(tuple(dims), leaf.dtype)

    shards = jax.tree_util.tree_map(local_sds, specs, shapes,
                                    is_leaf=lambda x: isinstance(x, P))

    def step(shards, tokens, lengths):
        full = spec_all_gather(shards, specs, "hvd_serve_mp")
        return batched_greedy_decode(full, cfg, tokens, lengths,
                                     max_new_tokens=4, max_len=20)

    sds = jax.ShapeDtypeStruct
    return (step,
            (shards, sds((2, 16), jnp.int32), sds((2,), jnp.int32)),
            (("hvd_serve_mp", _SERVE_MP),))


#: entry name -> builder returning (fn, example_args) or
#: (fn, example_args, extra_axes): ``extra_axes`` extends the trace's
#: axis_env past the varied ``_AXIS`` (hierarchical entries need a
#: second, fixed axis alongside the one the consistency check sweeps).
BUILTIN_ENTRIES = {
    "fused_reduce": _entry_fused_reduce,
    "distopt_step": _entry_distopt_step,
    "jit_fused_reduce": _entry_jit_fused_reduce,
    "sharded_distopt_step": _entry_sharded_distopt_step,
    "quantized_distopt_step": _entry_quantized_distopt_step,
    "overlapped_distopt_step": _entry_overlapped_distopt_step,
    "tail_distopt_step": _entry_tail_distopt_step,
    "health_distopt_step": _entry_health_distopt_step,
    "fsdp_distopt_step": _entry_fsdp_distopt_step,
    "serve_forward_step": _entry_serve_forward_step,
    "serve_mp_forward_step": _entry_serve_mp_forward_step,
}

#: Mesh sizes the consistency check traces every entry at (HVD210).
_CONSISTENCY_SIZES = (2, 4)


def builtin_schedule(name: str, axis_size: int = 2) -> Schedule:
    built = BUILTIN_ENTRIES[name]()
    fn, args = built[0], built[1]
    extra_axes = built[2] if len(built) > 2 else ()
    return trace_schedule(
        fn, args,
        axis_env=[(_AXIS, axis_size)] + [(n, int(s))
                                         for n, s in extra_axes],
        entry=name)


def snapshot_dir() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "tests", "schedules")


def snapshot_path(name: str, directory: Optional[str] = None) -> str:
    return os.path.join(directory or snapshot_dir(), f"{name}.json")


def check_builtin_snapshots(directory: Optional[str] = None,
                            entries: Optional[Iterable[str]] = None
                            ) -> List[Finding]:
    findings: List[Finding] = []
    for name in (entries or sorted(BUILTIN_ENTRIES)):
        findings.extend(check_snapshot(
            snapshot_path(name, directory), builtin_schedule(name)))
    return findings


def check_builtin_consistency(entries: Optional[Iterable[str]] = None
                              ) -> List[Finding]:
    findings: List[Finding] = []
    for name in (entries or sorted(BUILTIN_ENTRIES)):
        variants = [(f"{_AXIS}={size}", builtin_schedule(name, size))
                    for size in _CONSISTENCY_SIZES]
        findings.extend(check_consistency(variants))
    return findings


# ---------------------------------------------------------------------------
# CLI (tools/hvdsched)
# ---------------------------------------------------------------------------

_SHAPE_RE = re.compile(r"^(?:(\d+(?:x\d+)*))?:?([A-Za-z_]\w*)?$")


def _parse_shape(spec: str):
    """'8x16:float32' / '8x16' / ':bfloat16' -> ShapeDtypeStruct."""
    import jax
    import numpy as np
    m = _SHAPE_RE.match(spec)
    if not m:
        raise ValueError(f"bad --shape spec: {spec!r} "
                         f"(want e.g. 8x16:float32)")
    dims = tuple(int(d) for d in m.group(1).split("x")) if m.group(1) else ()
    dtype = np.dtype(m.group(2) or "float32")
    return jax.ShapeDtypeStruct(dims, dtype)


def _resolve_entry(spec: str):
    """'module:function' -> callable (for user step functions)."""
    import importlib
    mod_name, sep, fn_name = spec.partition(":")
    if not sep:
        raise ValueError(f"--entry {spec!r}: want module:function")
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="tools/hvdsched",
        description="hvdsched: static collective-schedule extractor — "
                    "traces step functions to jaxprs on CPU and "
                    "snapshots/checks the collective schedule "
                    "(docs/analysis.md 'Schedule snapshots')")
    parser.add_argument("--list", action="store_true",
                        help="list builtin entry points")
    parser.add_argument("--emit", metavar="ENTRY",
                        help="print the JSON schedule of a builtin entry")
    parser.add_argument("--check", action="store_true",
                        help="re-trace every builtin entry and diff "
                             "against the committed snapshots (CI mode; "
                             "exit 1 on drift, HVD211)")
    parser.add_argument("--update", action="store_true",
                        help="re-record the snapshots for every builtin "
                             "entry (the explicit, reviewed ratchet step)")
    parser.add_argument("--consistency", action="store_true",
                        help="trace every builtin entry at mesh sizes "
                             f"{list(_CONSISTENCY_SIZES)} and require "
                             "identical canonical schedules (HVD210)")
    parser.add_argument("--dir", metavar="DIR", default=None,
                        help="snapshot directory "
                             "(default: tests/schedules/)")
    parser.add_argument("--entry", metavar="MOD:FN",
                        help="trace a user step function instead of the "
                             "builtins (combine with --shape/--axis)")
    parser.add_argument("--shape", metavar="SPEC", action="append",
                        default=[],
                        help="example argument for --entry, e.g. "
                             "8x16:float32 (repeatable, one per arg)")
    parser.add_argument("--axis", metavar="NAME=SIZE", action="append",
                        default=[],
                        help="axis environment for --entry, e.g. "
                             "workers=2 (repeatable)")
    args = parser.parse_args(argv)

    if args.list:
        print("builtin schedule entries:")
        for name, builder in sorted(BUILTIN_ENTRIES.items()):
            print(f"  {name:18s} {(builder.__doc__ or '').strip().splitlines()[0]}")
        return 0

    if args.entry:
        fn = _resolve_entry(args.entry)
        shapes = [_parse_shape(s) for s in args.shape]
        axis_env = []
        for a in args.axis:
            name, sep, size = a.partition("=")
            if not sep:
                parser.error(f"--axis {a!r}: want NAME=SIZE")
            axis_env.append((name, int(size)))
        sched = trace_schedule(fn, shapes, axis_env=axis_env,
                               entry=args.entry)
        print(sched.to_json(), end="")
        return 0

    if args.emit:
        if args.emit not in BUILTIN_ENTRIES:
            parser.error(f"unknown entry {args.emit!r} (see --list)")
        print(builtin_schedule(args.emit).to_json(), end="")
        return 0

    if args.update:
        directory = args.dir or snapshot_dir()
        os.makedirs(directory, exist_ok=True)
        for name in sorted(BUILTIN_ENTRIES):
            path = snapshot_path(name, directory)
            sched = builtin_schedule(name)
            with open(path, "w", encoding="utf-8") as f:
                f.write(sched.to_json())
            print(f"hvdsched: recorded {path} "
                  f"({len(sched.records)} collective(s))")
        return 0

    if args.check or args.consistency:
        findings: List[Finding] = []
        if args.check:
            findings.extend(check_builtin_snapshots(args.dir))
        if args.consistency:
            findings.extend(check_builtin_consistency())
        for f in findings:
            print(f.format_text())
        if findings:
            print(f"\nhvdsched: {len(findings)} finding(s)")
            return 1
        kinds = [k for k, on in (("snapshots", args.check),
                                 ("consistency", args.consistency)) if on]
        print(f"hvdsched: {len(BUILTIN_ENTRIES)} entr"
              f"{'y' if len(BUILTIN_ENTRIES) == 1 else 'ies'} clean "
              f"({' + '.join(kinds)})")
        return 0

    parser.error("nothing to do (try --check, --update, --emit ENTRY, "
                 "--consistency or --list)")
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
