"""hvdlint CLI: file collection, engine dispatch, output formatting."""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import Iterable, List, Optional, Sequence

from . import lock_order, user_rules
from .report import (Finding, RULES, apply_suppressions, file_skipped,
                     iter_suppressions)

_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", "node_modules",
              ".pytest_cache", ".hypothesis"}


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    return out


def analyze_source(source: str, path: str = "<string>",
                   include_skipped: bool = False,
                   engines: Iterable[str] = ("user", "locks"),
                   ) -> List[Finding]:
    """Run the selected engines over one module's source."""
    if not include_skipped and file_skipped(source):
        return []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding("HVD000", path, exc.lineno or 1, exc.offset or 0,
                        f"could not parse: {exc.msg}")]
    findings: List[Finding] = []
    if "user" in engines:
        findings.extend(user_rules.check_module(tree, path))
    if "locks" in engines:
        findings.extend(lock_order.check_module(tree, path))
    findings = apply_suppressions(findings, iter_suppressions(source))
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    return findings


def analyze_paths(paths: Sequence[str], include_skipped: bool = False,
                  engines: Iterable[str] = ("user", "locks"),
                  select: Optional[Sequence[str]] = None,
                  ) -> List[Finding]:
    """Walk ``paths`` (files or directories) and analyze every .py file."""
    return analyze_files(collect_files(paths), include_skipped, engines,
                         select)


def analyze_files(files: Sequence[str], include_skipped: bool = False,
                  engines: Iterable[str] = ("user", "locks"),
                  select: Optional[Sequence[str]] = None,
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError as exc:
            findings.append(Finding("HVD000", path, 1, 0,
                                    f"could not read: {exc}"))
            continue
        findings.extend(analyze_source(
            source, path, include_skipped=include_skipped, engines=engines))
    if select:
        wanted = {c.strip().upper() for c in select}
        findings = [f for f in findings if f.code in wanted]
    return findings


def _list_rules() -> str:
    lines = ["hvdlint rules:"]
    for code, (title, fixit) in sorted(RULES.items()):
        lines.append(f"  {code}  {title}")
        lines.append(f"         fix: {fixit}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdlint: static collective-consistency and lock-order "
                    "analyzer for horovod_tpu training scripts")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--select", metavar="CODES",
                        help="comma-separated rule codes to report "
                             "(default: all)")
    parser.add_argument("--engine", choices=("user", "locks", "all"),
                        default="all",
                        help="user-script rules, framework lock-order "
                             "self-check, or both (default)")
    parser.add_argument("--include-skipped", action="store_true",
                        help="analyze files marked '# hvdlint: skip-file' "
                             "(for linting the lint fixtures themselves)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if not args.paths:
        parser.error("no paths given (try: horovod_tpu/ examples/)")

    engines = ("user", "locks") if args.engine == "all" else (args.engine,)
    select = None
    if args.select:
        select = [c.strip().upper() for c in args.select.split(",")
                  if c.strip()]
        unknown = [c for c in select if c not in RULES]
        if unknown:
            # a typo'd code would otherwise filter out every finding and
            # exit 0 — fatal in a CI gate
            parser.error(f"unknown rule code(s): {', '.join(unknown)} "
                         f"(see --list-rules)")
    files = collect_files(args.paths)
    findings = analyze_files(files, engines=engines,
                             include_skipped=args.include_skipped,
                             select=select)

    if args.format == "json":
        print(json.dumps({"findings": [f.as_dict() for f in findings],
                          "count": len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.format_text())
        n_files = len(files)
        if findings:
            print(f"\nhvdlint: {len(findings)} finding(s) in {n_files} "
                  f"file(s) — see docs/analysis.md for the rule catalog; "
                  f"suppress a false positive with "
                  f"'# hvdlint: disable=<code>'")
        else:
            print(f"hvdlint: {n_files} file(s) clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
