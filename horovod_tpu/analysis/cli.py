"""hvdlint CLI: file collection, engine dispatch, output formatting."""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import baseline as baseline_mod
from . import contracts as contracts_mod
from . import divergence, guarded_by, lifecycle, lock_order, user_rules
from .report import (Finding, RULES, apply_suppressions,
                     file_skipped, iter_suppressions)

_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", "node_modules",
              ".pytest_cache", ".hypothesis"}

#: All engines, in run order.  "guards" is the HVD110–115 guarded-by
#: race detector (guarded_by.py); "divergence" is the HVD200–HVD205
#: SPMD rank-divergence dataflow engine (divergence.py); "lifecycle"
#: is the HVD400–HVD407 concurrency-lifecycle engine (lifecycle.py:
#: blocking-under-lock, unbounded growth, clock mixing, shutdown
#: hygiene); "contracts" is the HVD300–HVD307 cross-artifact contract
#: checker (contracts.py) — the only engine that reasons repo-wide
#: instead of per-module, so it runs once per analyze_files() call,
#: not per file.
ENGINES = ("user", "locks", "guards", "divergence", "lifecycle",
           "contracts")

#: The per-module engines (everything except the repo-wide pass).
_MODULE_ENGINES = ("user", "locks", "guards", "divergence", "lifecycle")

#: Parsed-AST cache keyed by absolute path: every pass (user rules,
#: lock-order, guarded-by, divergence) and every re-run in one process
#: (e.g. the framework-wide pytest pins) reuses one parse per file
#: revision.  The entry is validated against the SOURCE CONTENT
#: (size + crc32), never against mtime — a file edited between read and
#: stat can not poison the cache with a stale tree.  The cache stores
#: ONLY the parse result, which depends on nothing but the source, so
#: it needs no ANALYZER_VERSION keying; findings are recomputed from
#: the AST on every call, and the version token guards the artifacts
#: that DO persist findings (the baseline files, baseline.py).
_AST_CACHE: Dict[str, Tuple[Tuple[int, int], ast.Module]] = {}


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    return out


def changed_files(base: str = "HEAD",
                  paths: Optional[Sequence[str]] = None) -> List[str]:
    """Python files changed in the working tree against ``base`` (the
    ``--changed`` pre-commit mode: ``git diff --name-only``).

    git emits repo-root-relative names; they are resolved against the
    repository toplevel so the mode works from any subdirectory."""
    import subprocess
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True)
    if top.returncode != 0:
        raise RuntimeError(
            f"not inside a git repository: {top.stderr.strip()}")
    toplevel = top.stdout.strip()
    proc = subprocess.run(
        ["git", "diff", "--name-only", "--diff-filter=d", base, "--"],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {base} failed: "
            f"{proc.stderr.strip() or proc.stdout.strip()}")
    roots = [os.path.abspath(p) for p in (paths or [])]
    out = []
    for name in proc.stdout.splitlines():
        name = name.strip()
        if not name.endswith(".py"):
            continue
        full = os.path.join(toplevel, name)
        if not os.path.exists(full):
            continue
        if roots and not any(
                full == r or full.startswith(r + os.sep) for r in roots):
            continue
        out.append(os.path.relpath(full))
    return sorted(out)


def analyze_source(source: str, path: str = "<string>",
                   include_skipped: bool = False,
                   engines: Iterable[str] = ENGINES,
                   tree: Optional[ast.Module] = None,
                   ) -> List[Finding]:
    """Run the selected PER-MODULE engines over one module's source.

    The repo-wide "contracts" engine cannot see a single module in
    isolation and is ignored here; it runs from analyze_files()."""
    if not include_skipped and file_skipped(source):
        return []
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            return [Finding("HVD000", path, exc.lineno or 1, exc.offset or 0,
                            f"could not parse: {exc.msg}")]
    findings: List[Finding] = []
    if "user" in engines:
        findings.extend(user_rules.check_module(tree, path))
    if "locks" in engines:
        findings.extend(lock_order.check_module(tree, path))
    if "guards" in engines:
        findings.extend(guarded_by.check_module(tree, path))
    if "divergence" in engines:
        findings.extend(divergence.check_module(tree, path))
    if "lifecycle" in engines:
        findings.extend(lifecycle.check_module(tree, path))
    findings = _dedupe_generalized(findings)
    findings = apply_suppressions(findings, iter_suppressions(source))
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    return findings


#: The divergence engine generalizes two user rules; when both fire on
#: the same line, the specific rule's message wins and the generalized
#: finding is dropped (one bug, one finding).
_GENERALIZES = {"HVD200": "HVD001", "HVD202": "HVD003"}


def _dedupe_generalized(findings: List[Finding]) -> List[Finding]:
    specific = {(f.code, f.path, f.line) for f in findings}
    return [f for f in findings
            if f.code not in _GENERALIZES
            or (_GENERALIZES[f.code], f.path, f.line) not in specific]


def analyze_paths(paths: Sequence[str], include_skipped: bool = False,
                  engines: Iterable[str] = ENGINES,
                  select: Optional[Sequence[str]] = None,
                  ) -> List[Finding]:
    """Walk ``paths`` (files or directories) and analyze every .py file."""
    return analyze_files(collect_files(paths), include_skipped, engines,
                         select)


def _parse_cached(path: str, source: str) -> Optional[ast.Module]:
    """Parse ``source``, reusing the cached AST while the content is
    unchanged (size + crc32 of the source actually read).  Returns None
    on syntax errors — the caller reports HVD000."""
    import zlib
    data = source.encode("utf-8", errors="surrogatepass")
    key = (len(data), zlib.crc32(data))
    cache_key = os.path.abspath(path)
    hit = _AST_CACHE.get(cache_key)
    if hit is not None and hit[0] == key:
        return hit[1]
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    _AST_CACHE[cache_key] = (key, tree)
    return tree


def _read_or_empty(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def analyze_files(files: Sequence[str], include_skipped: bool = False,
                  engines: Iterable[str] = ENGINES,
                  select: Optional[Sequence[str]] = None,
                  ) -> List[Finding]:
    findings: List[Finding] = []
    module_engines = [e for e in engines if e in _MODULE_ENGINES]
    inputs: List[Tuple[str, str, Optional[ast.Module]]] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError as exc:
            findings.append(Finding("HVD000", path, 1, 0,
                                    f"could not read: {exc}"))
            continue
        tree = _parse_cached(path, source)
        inputs.append((path, source, tree))
        findings.extend(analyze_source(
            source, path, include_skipped=include_skipped,
            engines=module_engines, tree=tree))
    if "contracts" in engines:
        # repo-wide pass: one extraction over the canonical scan set
        # (plus the explicit inputs), riding the shared AST cache
        findings.extend(contracts_mod.check_files(
            inputs, include_skipped=include_skipped, parse=_parse_cached))
    if select:
        wanted = {c.strip().upper() for c in select}
        findings = [f for f in findings if f.code in wanted]
    return findings


_RANGE_RE = re.compile(r"^HVD(\d+)-(?:HVD)?(\d+)$")


def expand_select(spec: str) -> Tuple[List[str], List[str]]:
    """Parse a ``--select`` spec with ranges (``HVD110-HVD115``).
    Returns (codes, unknown tokens)."""
    codes: List[str] = []
    unknown: List[str] = []
    for tok in spec.split(","):
        tok = tok.strip().upper()
        if not tok:
            continue
        m = _RANGE_RE.match(tok)
        if m:
            lo, hi = int(m.group(1)), int(m.group(2))
            hits = [f"HVD{n:03d}" for n in range(lo, hi + 1)
                    if f"HVD{n:03d}" in RULES]
            # a range may span a family's reserved band (HVD200-HVD215
            # selects the divergence+schedule family even though 206-209
            # and 212-215 are not yet assigned), but a range selecting
            # NOTHING is a typo — it would filter out every finding and
            # exit 0, fatal in a CI gate
            if hi < lo or not hits:
                unknown.append(tok)
                continue
            codes.extend(hits)
        elif tok in RULES:
            codes.append(tok)
        else:
            unknown.append(tok)
    return codes, unknown


def to_sarif(findings: Sequence[Finding]) -> dict:
    """SARIF 2.1.0 log for one run — the interchange format CI systems
    (GitHub code scanning, Gerrit checks) ingest to annotate diffs.

    One run, one driver ("hvdlint"), the full six-engine rule catalog in
    ``tool.driver.rules`` (so viewers can render titles/help for codes
    with zero results), one ``result`` per finding.  Columns are
    0-based internally; SARIF wants 1-based ``startColumn``.  Absolute
    finding paths are rewritten relative to the repo root (same walk-up
    the contracts engine uses), so a run over ``/abs/path/to/repo/...``
    emits the same SRCROOT-relative URIs as an in-repo run."""
    from .report import ANALYZER_VERSION
    root = contracts_mod.find_repo_root([f.path for f in findings])
    rules = [{
        "id": code,
        "shortDescription": {"text": title},
        "help": {"text": fixit},
    } for code, (title, fixit) in sorted(RULES.items())]
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = []
    for f in findings:
        uri = f.path
        if root and os.path.isabs(uri):
            ap = os.path.abspath(uri)
            if ap == root or ap.startswith(root + os.sep):
                uri = os.path.relpath(ap, root)
        results.append({
            "ruleId": f.code,
            "ruleIndex": index.get(f.code, -1),
            "level": "error",
            "message": {"text": f"{f.message}\nfix: {f.fixit}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": uri.replace(os.sep, "/"),
                        "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                }}],
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "hvdlint",
                "version": str(ANALYZER_VERSION),
                "informationUri": "docs/analysis.md",
                "rules": rules,
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }


def _list_rules() -> str:
    lines = ["hvdlint rules:"]
    for code, (title, fixit) in sorted(RULES.items()):
        lines.append(f"  {code}  {title}")
        lines.append(f"         fix: {fixit}")
    return "\n".join(lines)


def _docs_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "docs", "analysis.md")


def explain_rule(code: str) -> str:
    """The docs/analysis.md catalog entry for ``code`` (falls back to the
    built-in title + fix-it when the docs tree is not installed)."""
    code = code.strip().upper()
    if code not in RULES:
        return f"unknown rule code: {code} (see --list-rules)"
    section: List[str] = []
    try:
        with open(_docs_path(), "r", encoding="utf-8") as f:
            in_section = False
            for line in f:
                if line.startswith("### "):
                    if in_section:
                        break
                    in_section = line.startswith(f"### {code}")
                elif in_section and line.startswith("## "):
                    break
                if in_section:
                    section.append(line.rstrip("\n"))
    except OSError:
        section = []
    if section:
        return "\n".join(section).strip()
    title, fixit = RULES[code]
    return f"### {code} — {title}\n\nfix: {fixit}"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdlint: static collective-consistency, lock-order "
                    "and guarded-by race analyzer for horovod_tpu")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--sarif", metavar="OUT.json",
                        help="also write the findings as a SARIF 2.1.0 "
                             "log to this file (what CI code-scanning "
                             "ingests to annotate diffs); '-' writes to "
                             "stdout instead of the text report")
    parser.add_argument("--select", metavar="CODES",
                        help="comma-separated rule codes to report; "
                             "ranges allowed (HVD110-HVD115)")
    parser.add_argument("--engine",
                        choices=("user", "locks", "guards", "divergence",
                                 "lifecycle", "contracts", "all"),
                        default="all",
                        help="user-script rules, the lock-order "
                             "self-check, the guarded-by race detector, "
                             "the SPMD divergence dataflow engine, the "
                             "concurrency-lifecycle engine, the "
                             "cross-artifact contract checker, or all "
                             "six (default)")
    parser.add_argument("--include-skipped", action="store_true",
                        help="analyze files marked '# hvdlint: skip-file' "
                             "(for linting the lint fixtures themselves)")
    parser.add_argument("--baseline", metavar="FILE",
                        help="subtract findings recorded in this baseline "
                             "file; only NEW findings are reported "
                             "(tools/hvdlint_baseline.json in CI)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the --baseline file from the "
                             "current findings and exit 0")
    parser.add_argument("--changed", action="store_true",
                        help="lint only files changed against --base "
                             "(git diff --name-only); positional paths "
                             "then act as filters")
    parser.add_argument("--base", metavar="REF", default="HEAD",
                        help="base ref for --changed (default: HEAD)")
    parser.add_argument("--explain", metavar="CODE",
                        help="print the docs/analysis.md entry for a rule "
                             "and exit")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--contracts-json", action="store_true",
                        help="print the extracted registries (env knobs, "
                             "metric families, RPC methods, chaos sites) "
                             "as stable JSON and exit — the machine-"
                             "readable inventory downstream controllers "
                             "consume")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if args.explain:
        text = explain_rule(args.explain)
        print(text)
        return 0 if not text.startswith("unknown rule code") else 2
    if args.contracts_json:
        # registries only — no per-module findings pass needed; paths
        # (or the cwd) locate the repo root the scan anchors at
        repo = contracts_mod.build_repo(
            [], parse=_parse_cached) if not args.paths else \
            contracts_mod.build_repo(
                [(p, _read_or_empty(p), None)
                 for p in collect_files(args.paths)],
                include_skipped=args.include_skipped, parse=_parse_cached)
        print(json.dumps(contracts_mod.registries(repo), indent=2,
                         sort_keys=True))
        return 0
    if args.update_baseline and not args.baseline:
        parser.error("--update-baseline requires --baseline FILE")
    if args.update_baseline and (args.changed or args.select
                                 or args.engine != "all"):
        # rewriting the ratchet from a filtered subset would silently
        # drop every entry the filter excluded
        parser.error("--update-baseline must record a full run; drop "
                     "--changed/--select/--engine")
    if not args.paths and not args.changed:
        parser.error("no paths given (try: horovod_tpu/ examples/)")

    engines = ENGINES if args.engine == "all" else (args.engine,)
    select = None
    if args.select:
        select, unknown = expand_select(args.select)
        if unknown:
            # a typo'd code would otherwise filter out every finding and
            # exit 0 — fatal in a CI gate
            parser.error(f"unknown rule code(s): {', '.join(unknown)} "
                         f"(see --list-rules)")
    if args.changed:
        try:
            files = changed_files(args.base, args.paths)
        except RuntimeError as exc:
            parser.error(str(exc))
    else:
        files = collect_files(args.paths)
    findings = analyze_files(files, engines=engines,
                             include_skipped=args.include_skipped,
                             select=select)

    if args.update_baseline:
        n = baseline_mod.save(args.baseline, findings)
        print(f"hvdlint: baseline {args.baseline} updated "
              f"({n} entr{'y' if n == 1 else 'ies'}, "
              f"{len(findings)} finding(s))")
        return 0

    baselined = 0
    if args.baseline:
        try:
            allowed = baseline_mod.load(args.baseline)
        except OSError as exc:
            parser.error(f"could not read baseline {args.baseline}: {exc}")
        except (ValueError, KeyError) as exc:
            parser.error(f"malformed baseline {args.baseline}: {exc}")
        findings, baselined = baseline_mod.apply(findings, allowed)

    if args.sarif:
        sarif = to_sarif(findings)
        if args.sarif == "-":
            print(json.dumps(sarif, indent=2, sort_keys=True))
            return 1 if findings else 0
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(sarif, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.format == "json":
        print(json.dumps({"findings": [f.as_dict() for f in findings],
                          "count": len(findings),
                          "baselined": baselined}, indent=2))
    else:
        for f in findings:
            print(f.format_text())
        n_files = len(files)
        note = (f" ({baselined} baselined finding(s) not shown)"
                if baselined else "")
        if findings:
            new = "NEW " if args.baseline else ""
            print(f"\nhvdlint: {len(findings)} {new}finding(s) in "
                  f"{n_files} file(s){note} — see docs/analysis.md for "
                  f"the rule catalog; suppress a false positive with "
                  f"'# hvdlint: disable=<code>'")
        else:
            print(f"hvdlint: {n_files} file(s) clean{note}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
