"""Engine 4: interprocedural SPMD divergence dataflow (HVD200–HVD205).

Every rank of an SPMD job must submit the same collectives, in the same
order, with the same shapes and parameters.  Anything a process can
observe that its peers cannot — its rank, its environment, its clock,
its hostname, an unseeded RNG draw — is a **rank-divergent source**, and
letting such a value steer collective submission is the root cause of
the classic distributed-training deadlock/divergence families.  This
engine tracks divergent values through real dataflow, to a fixed point
over the module call graph (``callgraph.py``), generalizing the
one-helper-level syntactic checks HVD001/003/006 into:

* **HVD200** — a collective (direct, or via any helper chain that
  transitively submits one) under control flow conditioned on a
  divergent value;
* **HVD201** — a collective operand whose *shape* derives from a
  divergent value (``x[:rank]``, ``np.zeros(rank)``): reductions
  require identical shapes on every rank (allgather/alltoall legally
  carry ragged leading dimensions and are exempt);
* **HVD202** — a collective reached only by ranks that did not take an
  earlier divergent early-return/raise;
* **HVD203** — a divergent value published under a *shared* (non-
  rank-qualified) control-plane key: last-writer-wins state the ranks
  do not agree on.  A divergent *key* is the per-rank-namespace idiom
  and stays silent;
* **HVD204** — a divergent collective *parameter* (``name=``,
  ``root_rank=``, ``op=``, ``process_set=``): negotiation matches
  requests by these fields;
* **HVD205** — a collective inside a loop whose trip count is divergent
  (``for _ in range(rank())``): different submission counts per rank.

Dataflow facts per function, iterated to a fixed point:

* ``submits`` — does calling this function (transitively) submit a
  collective, and which base op;
* ``returns_divergent`` — is the return value divergent when called
  with non-divergent arguments (sources inside the body, or calls to
  other divergent-returning functions; a ``return`` *inside* a
  divergent branch is itself divergent — implicit flow).

**Sanitizers:** the result of any recognized collective call is, by
construction, agreed on by every rank — ``broadcast_object(rank())``,
``allreduce(local_stat)`` and friends clear both taints.  Reassignment
from a clean value clears a local's taint.

Static under-approximations, all in the quiet direction: taint does not
flow through object attributes, through function *parameters* at call
sites, or into closures; accesses the analysis cannot resolve are
clean.  The engine shares alias resolution with the user rules, so only
provably-horovod collectives and provably-divergent sources count.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import callgraph
from .report import Finding
from .user_rules import COLLECTIVES, RANK_FNS, UserScriptChecker

#: (module, dotted call) -> human label.  ``*`` matches any attr.
_SOURCE_CALLS: Dict[Tuple[str, str], str] = {}
for _mod, _names, _label in (
        ("os", ("getenv", "getpid"), "an environment/process read"),
        ("os.environ", ("get", "__getitem__", "setdefault"),
         "an environment variable"),
        ("time", ("time", "time_ns", "monotonic", "monotonic_ns",
                  "perf_counter", "perf_counter_ns"), "the wall clock"),
        ("datetime.datetime", ("now", "utcnow", "today"), "the wall clock"),
        ("socket", ("gethostname", "getfqdn"), "the hostname"),
        ("platform", ("node",), "the hostname"),
        ("os", ("uname",), "the hostname"),
        ("random", ("random", "randint", "randrange", "uniform", "choice",
                    "choices", "sample", "shuffle", "getrandbits",
                    "randbytes", "gauss"), "unseeded RNG"),
        ("numpy.random", ("rand", "randn", "randint", "random",
                          "random_sample", "choice", "permutation",
                          "normal", "uniform", "standard_normal"),
         "unseeded RNG"),
        ("uuid", ("uuid1", "uuid4"), "a fresh uuid"),
        ("secrets", ("token_hex", "token_bytes", "token_urlsafe",
                     "randbelow", "choice"), "unseeded RNG"),
):
    for _n in _names:
        _SOURCE_CALLS[(_mod, _n)] = _label

#: numpy module spellings the alias pre-pass normalizes to "numpy".
_NUMPY_NAMES = {"numpy", "np", "jnp"}  # jnp has no .random module; harmless

#: Ops whose operands must have identical shapes on every rank.
#: allgather/alltoall legally carry ragged leading dims (the eager API
#: pads/exchanges sizes), so shape divergence is only fatal for these.
_SHAPE_STRICT = frozenset({"allreduce", "reducescatter", "broadcast"})

#: Collective kwargs that negotiation matches requests by (HVD204).
_MATCHED_KWARGS = ("name", "root_rank", "op", "process_set", "average")

#: Array constructors whose every positional argument is a dimension.
_SHAPE_ALL_ARGS = frozenset({
    "zeros", "ones", "empty", "arange", "linspace", "eye", "randperm",
})
#: data-first constructors: shape arguments start at position 1.
_SHAPE_TAIL_ARGS = frozenset({
    "tile", "repeat", "reshape", "broadcast_to", "resize", "split",
    "array_split",
})
#: Methods (receiver is the data) whose positional args are dimensions.
_SHAPE_METHODS = frozenset({"reshape", "repeat", "resize", "split",
                            "expand", "view"})
#: Methods that collapse an array to a rank-invariant scalar/shape.
_SHAPE_REDUCERS = frozenset({
    "sum", "mean", "max", "min", "prod", "all", "any", "item", "size",
    "numel", "dim",
})
#: Builtins that produce a scalar: shape taint dies here (the VALUE may
#: still be divergent — ``len()`` of a rank-sharded array is).
_SCALAR_FNS = frozenset({"len", "int", "float", "bool", "str", "max",
                         "min", "sum", "abs", "round"})

#: Control-plane publish sinks: f(key, value) by name, or ``recv.set/put
#: (key, value)`` where the receiver smells like a KV/store client.
_PUBLISH_FNS = frozenset({"key_value_set", "kv_set", "_kv_set"})
_PUBLISH_METHODS = frozenset({"set", "put"})
_PUBLISH_RECV = re.compile(r"kv|store|coord", re.IGNORECASE)


@dataclasses.dataclass
class _Summary:
    """Fixed-point facts for one call-graph function."""
    submits: Optional[str] = None        # base collective op, or None
    submits_via: str = ""                # "" = direct, else callee qname
    returns_divergent: Optional[str] = None   # label, or None


class _Ctx:
    """Mutable per-function walk context (shared down the statement
    walk on purpose: a divergent early exit taints the REST of the
    function, not a lexical subtree)."""

    def __init__(self):
        self.branch: Optional[Tuple[str, int]] = None   # (label, line)
        self.loop: Optional[Tuple[str, int]] = None
        self.exit: Optional[Tuple[str, int]] = None
        #: a divergent break/continue: taints only the rest of the
        #: enclosing LOOP BODY (restored at the loop boundary), never
        #: the code after the loop
        self.loop_exit: Optional[Tuple[str, int]] = None


class DivergenceChecker:
    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.findings: List[Finding] = []
        # alias resolution shared with the user rules: hvd modules,
        # bare collective imports, bare rank fns, module rank vars
        self.usr = UserScriptChecker(tree, path)
        self.usr._collect_imports()
        self.usr._collect_rank_vars()
        self.graph = callgraph.build_graph(tree)
        self.summaries: Dict[str, _Summary] = {
            q: _Summary() for q in self.graph.functions}
        #: import alias -> dotted real module ("np" -> "numpy",
        #: "environ" -> "os.environ", "time" -> "time.time" for
        #: ``from time import time``)
        self.mod_alias: Dict[str, str] = {}
        #: module-level divergent names -> label
        self.module_env: Dict[str, str] = {}
        self._collect_module_aliases()

    # -- import pre-pass -----------------------------------------------------
    def _collect_module_aliases(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.name
                    top = name.split(".")[0]
                    if top in _NUMPY_NAMES:
                        name = "numpy" + name[len(top):]
                    self.mod_alias[a.asname or top] = \
                        name if a.asname else name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                mod = node.module
                top = mod.split(".")[0]
                if top in _NUMPY_NAMES:
                    mod = "numpy" + mod[len(top):]
                for a in node.names:
                    self.mod_alias[a.asname or a.name] = f"{mod}.{a.name}"

    def _dotted(self, node: ast.expr) -> Optional[str]:
        """Resolve ``a.b.c`` through the import alias map to a real
        dotted module path; None when the root is not an import."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.mod_alias.get(node.id)
        if root is None:
            return None
        parts.append(root)
        return ".".join(reversed(parts))

    # -- divergent-source predicates -----------------------------------------
    def _source_label(self, call: ast.Call) -> Optional[str]:
        """Label when ``call`` is a direct divergent source."""
        fn = call.func
        # hvd rank functions (alias-resolved, same as the user rules)
        if isinstance(fn, ast.Attribute) and fn.attr in RANK_FNS \
                and self.usr._is_hvd(fn.value):
            return "the process rank"
        if isinstance(fn, ast.Name) and fn.id in self.usr.bare_rank_fns:
            return "the process rank"
        # jax.process_index()
        if isinstance(fn, ast.Attribute) and fn.attr == "process_index":
            root = fn.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) \
                    and root.id in self.usr.jax_aliases:
                return "the process rank"
        # stdlib/numpy sources through the alias map
        dotted = self._dotted(fn)
        if dotted is not None and "." in dotted:
            mod, attr = dotted.rsplit(".", 1)
            label = _SOURCE_CALLS.get((mod, attr))
            if label is not None:
                return label
            # numpy.random.default_rng() is only divergent UNSEEDED
            if mod == "numpy.random" and attr == "default_rng" \
                    and not call.args and not call.keywords:
                return "unseeded RNG"
        return None

    def _is_environ_read(self, node: ast.Subscript) -> bool:
        return self._dotted(node.value) == "os.environ"

    def _is_sanitizer(self, call: ast.Call) -> bool:
        """Collective results are agreed on by every rank."""
        return self.usr._collective_name(call) is not None

    # -- expression taint ----------------------------------------------------
    def _div(self, node: ast.expr, env: Dict[str, str],
             shape_env: Optional[Dict[str, str]] = None) -> Optional[str]:
        """Label when the expression's VALUE can differ across ranks."""
        shape_env = shape_env if shape_env is not None else {}
        if isinstance(node, ast.Name):
            # NOT the user rules' scope-blind rank_vars: this engine's
            # own env is sanitizer-aware (broadcast_object(rank()) is
            # clean), and falling back would resurrect the taint
            if node.id in env:
                return env[node.id]
            return self.module_env.get(node.id)
        if isinstance(node, ast.Lambda):
            return None              # a value, not an evaluation
        if isinstance(node, ast.Attribute):
            # x.shape / x.ndim of a rank-sharded array is a divergent value
            if node.attr in ("shape", "ndim", "nbytes"):
                label = self._sdiv(node.value, env, shape_env)
                if label:
                    return label
            return self._div(node.value, env, shape_env)
        if isinstance(node, ast.Subscript):
            if self._is_environ_read(node):
                return "an environment variable"
            for child in (node.value, node.slice):
                label = self._div(child, env, shape_env)
                if label:
                    return label
            return None
        if isinstance(node, ast.Call):
            if self._is_sanitizer(node):
                return None          # broadcast/allreduce agree everywhere
            label = self._source_label(node)
            if label:
                return label
            fn = node.func
            # len()/size measurements of a rank-sharded array diverge
            if isinstance(fn, ast.Name) and fn.id == "len" and node.args:
                label = self._sdiv(node.args[0], env, shape_env)
                if label:
                    return label
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in _SHAPE_REDUCERS \
                    and fn.attr in ("size", "numel"):
                label = self._sdiv(fn.value, env, shape_env)
                if label:
                    return label
            callee = self._resolve_callee(node)
            if callee is not None:
                ret = self.summaries[callee].returns_divergent
                if ret:
                    return (f"helper '{_short(callee)}()' "
                            f"(returns {ret})")
            # taint propagates through arguments and the receiver
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.keyword):
                    child = child.value
                if isinstance(child, ast.expr):
                    label = self._div(child, env, shape_env)
                    if label:
                        return label
            return None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.keyword):
                child = child.value
            if isinstance(child, (ast.expr, ast.comprehension)):
                if isinstance(child, ast.comprehension):
                    label = self._div(child.iter, env, shape_env)
                else:
                    label = self._div(child, env, shape_env)
                if label:
                    return label
        return None

    def _sdiv(self, node: ast.expr, env: Dict[str, str],
              shape_env: Dict[str, str]) -> Optional[str]:
        """Label when the expression's SHAPE can differ across ranks.

        Propagation is structural, not blanket: scalar producers
        (``len``, ``float``, reductions) KILL shape taint — the value
        they yield may still diverge, which :meth:`_div` models — and a
        plain (non-slice) subscript follows the index's shape, not the
        receiver's."""
        if isinstance(node, ast.Name):
            return shape_env.get(node.id)
        if isinstance(node, ast.Lambda):
            return None
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Slice):
                # x[a:b:c] with a divergent bound changes the extent.
                # The batch-windowing idiom x[i:i+k] has extent k no
                # matter what i is: when the upper bound is literally
                # ``lower + k``, only k (and the step) can diverge it.
                bounds = [sl.lower, sl.upper, sl.step]
                if sl.lower is not None \
                        and isinstance(sl.upper, ast.BinOp) \
                        and isinstance(sl.upper.op, ast.Add):
                    low = ast.dump(sl.lower)
                    if ast.dump(sl.upper.left) == low:
                        bounds = [sl.upper.right, sl.step]
                    elif ast.dump(sl.upper.right) == low:
                        bounds = [sl.upper.left, sl.step]
                for bound in bounds:
                    if bound is not None:
                        label = self._div(bound, env, shape_env)
                        if label:
                            return label
                if sl.upper is not None:
                    # clean explicit upper bound: the extent is the
                    # bound, not the receiver's (divergent) length —
                    # x[i:i+batch] of a rank-sharded array is batch-sized
                    return None
                # open-ended (x[a:], x[:]) inherits the receiver's extent
                return self._sdiv(node.value, env, shape_env)
            # plain / advanced index: the result's shape follows the
            # INDEX (x[idx] has idx's extent), not the receiver's
            return self._sdiv(sl, env, shape_env)
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name in _SCALAR_FNS and isinstance(fn, ast.Name):
                return None          # scalar: no shape to diverge
            args = list(node.args)
            kwvals = [kw.value for kw in node.keywords
                      if kw.arg in ("shape", "size", "num", "reps",
                                    "repeats", "newshape")]
            shape_args: List[ast.expr] = list(kwvals)
            is_method = isinstance(fn, ast.Attribute) \
                and self._dotted(fn) is None
            if is_method:
                if name in _SHAPE_REDUCERS:
                    return None      # collapses to a rank-invariant shape
                if name in _SHAPE_METHODS:
                    shape_args += args
            elif name in _SHAPE_ALL_ARGS:
                shape_args += args
            elif name == "full":
                shape_args += args[:1]      # args[1] is the fill value
            elif name in _SHAPE_TAIL_ARGS:
                shape_args += args[1:]
            for child in shape_args:
                label = self._div(child, env, shape_env)
                if label:
                    return label
            if is_method:
                # method on a shape-divergent receiver propagates
                label = self._sdiv(fn.value, env, shape_env)
                if label:
                    return label
            for child in args:
                label = self._sdiv(child, env, shape_env)
                if label:
                    return label
            return None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                label = self._sdiv(child, env, shape_env)
                if label:
                    return label
        return None

    # -- call resolution -----------------------------------------------------
    def _resolve_callee(self, call: ast.Call,
                        cls: Optional[str] = None) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name) and fn.id in self.graph.functions:
            return fn.id
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self" and cls is not None:
            return self.graph.resolve_method(cls, fn.attr)
        return None

    # -- fixed point ---------------------------------------------------------
    def _direct_submits(self, qname: str) -> Optional[Tuple[str, int]]:
        """(base op, line) when the function body directly submits a
        collective (nested defs excluded — defining a closure submits
        nothing)."""
        node = self.graph.functions[qname].node

        def own_calls(n):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    yield child
                yield from own_calls(child)

        for call in own_calls(node):
            coll = self.usr._collective_name(call)
            if coll is not None:
                return COLLECTIVES[coll], call.lineno
        return None

    def _fixed_point(self):
        for qname in self.graph.functions:
            direct = self._direct_submits(qname)
            if direct is not None:
                self.summaries[qname].submits = direct[0]
        for _ in range(len(self.graph.functions) + 1):
            changed = False
            for qname, info in self.graph.functions.items():
                s = self.summaries[qname]
                if s.submits is None:
                    for callee in sorted(info.calls):
                        cs = self.summaries.get(callee)
                        if cs is not None and cs.submits is not None:
                            s.submits = cs.submits
                            s.submits_via = callee
                            changed = True
                            break
                if s.returns_divergent is None:
                    label = self._returns_divergent(qname)
                    if label:
                        s.returns_divergent = label
                        changed = True
            if not changed:
                break

    def _returns_divergent(self, qname: str) -> Optional[str]:
        info = self.graph.functions[qname]
        walker = _FnWalker(self, info, emit=False)
        walker.run()
        return walker.returns_divergent

    # -- driver --------------------------------------------------------------
    def _module_env_pass(self):
        """Module-level divergent names: scope-blind, like the user
        rules' rank_vars.  Run once before the fixed point (sources
        assigned at module scope seed the function walks) and once after
        (module assigns from divergent-returning helpers resolve)."""
        mod_walker = _FnWalker(self, None, emit=False)
        mod_walker.walk(self.tree.body)
        self.module_env = dict(mod_walker.env)

    def run(self) -> List[Finding]:
        self._module_env_pass()
        self._fixed_point()
        self._module_env_pass()
        # reporting pass: module level first, then every function
        _FnWalker(self, None, emit=True).walk(self.tree.body)
        for qname, info in self.graph.functions.items():
            _FnWalker(self, info, emit=True).run()
        self.findings.sort(key=lambda f: (f.line, f.col, f.code))
        return self.findings

    def _add(self, code: str, node: ast.AST, message: str):
        self.findings.append(Finding(
            code=code, path=self.path, line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0), message=message))


def _short(qname: str) -> str:
    return qname.split(".")[-1].strip("<>")


def _terminal_kind(stmts: Sequence[ast.stmt]) -> Optional[str]:
    """``"func"`` when the branch can leave the function (return/raise),
    ``"loop"`` when it can only leave the current loop iteration
    (break/continue), else None.  The distinction matters: a divergent
    ``continue`` makes some ranks skip the REST OF THE LOOP BODY, but
    every rank still reaches the code after the loop — conflating the
    two falsely convicts post-loop collectives (noise, which this
    engine must never produce)."""
    if any(isinstance(s, (ast.Return, ast.Raise)) for s in stmts):
        return "func"
    if any(isinstance(s, (ast.Break, ast.Continue)) for s in stmts):
        return "loop"
    return None


class _FnWalker:
    """One linear walk over a function (or the module body): tracks the
    local taint environments and the divergence context, and — in emit
    mode — reports HVD200–HVD205 at collective/publish call sites."""

    def __init__(self, checker: DivergenceChecker,
                 info: Optional[callgraph.FuncInfo], emit: bool):
        self.c = checker
        self.info = info
        self.cls = info.cls if info is not None else None
        self.emit = emit
        self.env: Dict[str, str] = {}
        self.shape_env: Dict[str, str] = {}
        self.ctx = _Ctx()
        self.returns_divergent: Optional[str] = None

    def run(self):
        assert self.info is not None
        body = getattr(self.info.node, "body", [])
        self.walk(body)

    # -- statements ----------------------------------------------------------
    def walk(self, stmts: Sequence[ast.stmt]):
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        c = self.c
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return            # walked as its own call-graph function
        if isinstance(stmt, ast.ClassDef):
            return            # methods are their own graph functions
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(stmt)
            return
        if isinstance(stmt, ast.If):
            self._scan(stmt.test)
            label = c._div(stmt.test, self.env, self.shape_env)
            saved = self.ctx.branch
            if label:
                self.ctx.branch = (label, stmt.lineno)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            self.ctx.branch = saved
            if label:
                kind = _terminal_kind(stmt.body + stmt.orelse)
                if kind == "func" and self.ctx.exit is None:
                    self.ctx.exit = (label, stmt.lineno)
                elif kind == "loop" and self.ctx.loop_exit is None:
                    self.ctx.loop_exit = (label, stmt.lineno)
            return
        if isinstance(stmt, ast.While):
            self._scan(stmt.test)
            label = c._div(stmt.test, self.env, self.shape_env)
            saved = self.ctx.loop
            saved_exit = self.ctx.loop_exit
            if label:
                self.ctx.loop = (label, stmt.lineno)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            self.ctx.loop = saved
            self.ctx.loop_exit = saved_exit
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan(stmt.iter)
            label = c._div(stmt.iter, self.env, self.shape_env)
            if label and isinstance(stmt.target, ast.Name):
                self.env[stmt.target.id] = label
            saved = self.ctx.loop
            saved_exit = self.ctx.loop_exit
            if label:
                self.ctx.loop = (label, stmt.lineno)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            self.ctx.loop = saved
            self.ctx.loop_exit = saved_exit
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for handler in stmt.handlers:
                self.walk(handler.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan(item.context_expr)
            self.walk(stmt.body)
            return
        if isinstance(stmt, ast.Match):
            self._scan(stmt.subject)
            label = c._div(stmt.subject, self.env, self.shape_env)
            saved = self.ctx.branch
            if label:
                self.ctx.branch = (label, stmt.lineno)
            for case in stmt.cases:
                if case.guard is not None:
                    self._scan(case.guard)
                self.walk(case.body)
            self.ctx.branch = saved
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan(stmt.value)
                label = c._div(stmt.value, self.env, self.shape_env)
                if label and self.returns_divergent is None:
                    self.returns_divergent = label
            if self.ctx.branch and self.returns_divergent is None:
                # implicit flow: WHICH return runs depends on the branch
                self.returns_divergent = self.ctx.branch[0]
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan(child)

    def _assign(self, stmt):
        c = self.c
        value = stmt.value
        if value is not None:
            self._scan(value)
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        # zipped tuple assignment taints element-wise: in
        # ``r, n = rank(), size()`` only r is divergent
        if isinstance(stmt, ast.Assign) and len(targets) == 1 \
                and isinstance(targets[0], ast.Tuple) \
                and isinstance(value, ast.Tuple) \
                and len(targets[0].elts) == len(value.elts):
            for t, v in zip(targets[0].elts, value.elts):
                self._assign_one([t], v)
            return
        self._assign_one(targets, value, stmt)

    def _assign_one(self, targets, value, stmt=None):
        c = self.c
        label = c._div(value, self.env, self.shape_env) if value is not None else None
        slabel = (c._sdiv(value, self.env, self.shape_env)
                  if value is not None else None)
        if label is None and self.ctx.branch is not None \
                and value is not None:
            # implicit flow: WHICH value lands here depends on the branch
            label = self.ctx.branch[0]
        if isinstance(stmt, ast.AugAssign):
            t = stmt.target
            if isinstance(t, ast.Name):
                if label:
                    self.env[t.id] = label
                if slabel:
                    self.shape_env[t.id] = slabel
            return
        for t in targets:
            for name_node in self._target_names(t):
                if label:
                    self.env[name_node] = label
                else:
                    self.env.pop(name_node, None)
                if slabel:
                    self.shape_env[name_node] = slabel
                else:
                    self.shape_env.pop(name_node, None)

    @staticmethod
    def _target_names(target: ast.expr) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for elt in target.elts:
                out.extend(_FnWalker._target_names(elt))
            return out
        return []

    # -- expressions / call sites --------------------------------------------
    def _scan(self, node: ast.expr):
        if isinstance(node, ast.Call):
            if self.emit:
                self._check_call(node)
        if isinstance(node, ast.Lambda):
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.keyword):
                self._scan(child.value)
            elif isinstance(child, ast.comprehension):
                self._scan(child.iter)
                for cond in child.ifs:
                    self._scan(cond)
            elif isinstance(child, ast.expr):
                self._scan(child)

    def _check_call(self, call: ast.Call):
        c = self.c
        coll = c.usr._collective_name(call)
        via = ""
        base_op = None
        if coll is not None:
            base_op = COLLECTIVES[coll]
        else:
            callee = c._resolve_callee(call, self.cls)
            if callee is not None:
                s = c.summaries[callee]
                if s.submits is not None:
                    base_op = s.submits
                    via = (f" via helper '{_short(callee)}' (line "
                           f"{c.graph.functions[callee].node.lineno}), "
                           f"which transitively submits it,")
        if base_op is not None:
            self._check_collective(call, base_op, via)
            return
        self._check_publish(call)

    def _check_collective(self, call: ast.Call, base_op: str, via: str):
        c = self.c
        if self.ctx.branch is not None:
            label, line = self.ctx.branch
            c._add("HVD200", call,
                   f"collective '{base_op}' submitted{via} inside a "
                   f"branch conditioned on {label} (branch at line "
                   f"{line}); ranks evaluating the condition differently "
                   f"never submit it and the rest deadlock")
        elif self.ctx.exit is not None:
            label, line = self.ctx.exit
            c._add("HVD202", call,
                   f"collective '{base_op}' submitted{via} after an "
                   f"early exit conditioned on {label} (line {line}); "
                   f"ranks that exited never reach this call and the "
                   f"rest block forever")
        elif self.ctx.loop_exit is not None:
            label, line = self.ctx.loop_exit
            c._add("HVD202", call,
                   f"collective '{base_op}' submitted{via} after a "
                   f"break/continue conditioned on {label} (line {line}); "
                   f"ranks that left the iteration submit fewer "
                   f"collectives than their peers expect")
        if self.ctx.loop is not None:
            label, line = self.ctx.loop
            c._add("HVD205", call,
                   f"collective '{base_op}' submitted{via} inside a loop "
                   f"whose trip count depends on {label} (loop at line "
                   f"{line}); ranks iterating fewer times submit fewer "
                   f"collectives than their peers expect")
        # HVD201: shape-divergent operands (direct submissions only —
        # helper operands were shaped at the helper's own site)
        if not via and base_op in _SHAPE_STRICT:
            for arg in call.args:
                slabel = c._sdiv(arg, self.env, self.shape_env)
                if slabel:
                    c._add("HVD201", call,
                           f"operand of '{base_op}' has a shape derived "
                           f"from {slabel}; reductions require "
                           f"identically-shaped operands on every rank, "
                           f"and a mismatched shape diverges the fused "
                           f"buffer layout")
                    break
        # HVD204: divergent matched parameters
        if not via:
            for kw in call.keywords:
                if kw.arg in _MATCHED_KWARGS:
                    label = c._div(kw.value, self.env, self.shape_env)
                    if label:
                        c._add("HVD204", call,
                               f"collective parameter '{kw.arg}=' "
                               f"depends on {label}; negotiation "
                               f"matches requests by this field, so "
                               f"per-rank values pair incompatible "
                               f"submissions")
            if base_op == "broadcast" and len(call.args) >= 2:
                label = c._div(call.args[1], self.env, self.shape_env)
                if label:
                    c._add("HVD204", call,
                           f"broadcast root_rank depends on {label}; "
                           f"every rank must name the SAME root, or N "
                           f"different one-to-all broadcasts are "
                           f"submitted at once")

    def _check_publish(self, call: ast.Call):
        c = self.c
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else None
        is_sink = name in _PUBLISH_FNS
        if not is_sink and name in _PUBLISH_METHODS \
                and isinstance(fn, ast.Attribute):
            recv = fn.value
            recv_name = recv.attr if isinstance(recv, ast.Attribute) \
                else recv.id if isinstance(recv, ast.Name) else ""
            is_sink = bool(_PUBLISH_RECV.search(recv_name))
        if not is_sink or len(call.args) < 2:
            return
        key_expr, val_expr = call.args[0], call.args[1]
        val_label = c._div(val_expr, self.env, self.shape_env)
        if val_label is None:
            return
        if c._div(key_expr, self.env, self.shape_env) is not None:
            return    # rank-qualified key: the per-rank-namespace idiom
        c._add("HVD203", call,
               f"value published under shared control-plane key depends "
               f"on {val_label}; every rank writes its own value to ONE "
               f"key and the survivors read last-writer-wins state they "
               f"do not agree on — qualify the key by rank or broadcast "
               f"the value first")


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    return DivergenceChecker(tree, path).run()
