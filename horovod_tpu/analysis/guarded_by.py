"""Engine 3: guarded-by inference and static race detection (HVD110–115).

Eraser-style lock-set analysis, run statically over the framework's own
threaded classes.  For every class that owns a ``threading.Lock`` /
``RLock`` / ``Condition`` (or is reachable from two or more thread entry
points — see ``callgraph.py``), each instance attribute's **candidate
guard** is inferred from the lock held at the majority of its access
sites; the lock held at every *write* site is the fallback when no lock
reaches a majority.  Accesses are tracked through ``with self._lock:``
blocks, ``acquire()``/``release()`` spans, ``Condition(self._lock)``
underlying-lock aliasing, and intra-class calls **to a fixed point**: a
private method only ever called with a lock held analyzes as if it held
that lock (the *ambient* set — the intersection over its call sites,
each call site contributing its syntactic held set plus its own
caller's ambient, iterated until stable), so ``caller must hold
self._lock`` helper chains of any depth do not false-positive.
Non-escaping nested defs (only ever called directly, never passed as a
value) analyze under the locks provably held at BOTH their definition
site and every direct call site, plus the enclosing method's ambient
set — the ``while not changed(): cv.wait()`` wait-predicate idiom
defines and calls its predicate inside ``with self._cond:``, so the
predicate and the private helpers it calls resolve the Condition's
underlying lock any number of call levels deeper, while a def merely
*defined* under a lock but called after its release still analyzes
bare, and a def that ESCAPES as a value (thread target, callback)
runs on an unknown thread: neither the syntactic nor the ambient held
set applies inside it.

Findings:

* **HVD110** — attribute written without its inferred guard on a
  multi-thread-reachable path;
* **HVD111** — non-atomic read-modify-write (``self.x += 1``, swap
  assignments reading the written attribute) outside the guard, or a
  check-then-act whose test runs unguarded while the act takes the lock;
* **HVD112** — a guarded mutable container escapes the lock scope by
  reference (returned bare, or stored into an unguarded attribute);
* **HVD113** — the guard is held for writes but not for reads (torn /
  stale reads; the symmetric case surfaces per-site as HVD110/111);
* **HVD114** — attribute first assigned in ``__init__`` *after* a thread
  that reads it was already started;
* **HVD115** — no majority lock and two locks each guard a large share
  of sites: split-guard ambiguity, nothing is actually protected.

Static under-approximation in the safe direction: attributes with no
guarded sites at all produce **no** findings (there is no inferred guard
to violate — that is the documented Eraser limitation), and accesses the
analysis cannot see (``outer.attr`` closures, cross-module calls) simply
do not count as sites.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import callgraph
from .lock_order import _LockDef, _lock_ctor
from .report import Finding

#: Attribute-method calls that mutate the receiver in place.
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "move_to_end", "sort", "reverse", "put", "put_nowait",
})

#: Constructor calls whose result is a mutable container (HVD112 scope).
_CONTAINER_CTORS = frozenset({
    "list", "dict", "set", "deque", "OrderedDict", "defaultdict",
    "Counter", "bytearray",
})

#: Majority threshold for guard inference.
_MAJORITY = 0.5
#: Split-guard share (HVD115): two locks each covering at least this.
_SPLIT_SHARE = 0.3


def _is_container_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else None
        return name in _CONTAINER_CTORS
    return False


def _self_attr(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _nested_escapes(root: ast.AST, name: str) -> bool:
    """Does the nested function ``name`` escape its enclosing method as a
    *value* (thread target, callback registration, return, assignment,
    container element)?  Only direct ``name(...)`` calls keep it local to
    the defining scope."""
    direct_callees: Set[int] = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == name:
            direct_callees.add(id(node.func))
    for node in ast.walk(root):
        if isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, ast.Load) \
                and id(node) not in direct_callees:
            return True
    return False


def _calls_name(root: ast.AST, name: str) -> bool:
    """Does this subtree contain a direct ``name(...)`` call?"""
    return any(isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
               and n.func.id == name for n in ast.walk(root))


def _reads_attr(node: ast.expr, attr: str) -> bool:
    """Does this expression read ``self.<attr>`` anywhere?"""
    for sub in ast.walk(node):
        if _self_attr(sub) == attr and isinstance(
                getattr(sub, "ctx", None), ast.Load):
            return True
    return False


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str                    # read | write | rmw | escape | cta
    held: FrozenSet[str]         # underlying lock names held at the site
    method: str                  # method name ("m" or "m.<nested>")
    line: int
    in_init: bool
    escape_to: Optional[str] = None   # HVD112: "" = returned, else attr name
    #: may this site inherit the enclosing method's ambient held set?
    #: True for the method body and non-escaping nested defs (they run
    #: on the defining thread, inside the method's dynamic extent);
    #: False inside an escaping nested def — it runs later, on an
    #: unknown thread, where the caller's ambient locks are NOT held.
    ambient_ok: bool = True


@dataclasses.dataclass
class _MergedClass:
    name: str
    node: ast.ClassDef
    path: str
    locks: Dict[str, _LockDef] = dataclasses.field(default_factory=dict)
    #: method name -> (defining class, FunctionDef); nearest override wins
    methods: Dict[str, Tuple[str, ast.AST]] = \
        dataclasses.field(default_factory=dict)
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    #: (caller method, held set, callee method name, line, ambient_ok)
    calls: List[Tuple[str, FrozenSet[str], str, int, bool]] = \
        dataclasses.field(default_factory=list)
    #: attr -> first __init__ assignment line
    init_assign_line: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: attrs whose __init__ value is a mutable container
    container_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: earliest line in __init__ at which a thread is already running
    init_spawn_line: Optional[int] = None
    init_spawn_desc: str = ""


class _MethodWalker:
    """Walk one method body tracking the held-lock set, recording
    attribute access sites and intra-class call sites."""

    def __init__(self, cls: _MergedClass, method: str, in_init: bool,
                 root: Optional[ast.AST] = None,
                 shared: Optional[dict] = None,
                 ambient_ok: bool = True):
        self.cls = cls
        self.method = method
        self.in_init = in_init
        #: the outermost method node — nested walkers share it so escape
        #: analysis for a nested def sees every use site in the method
        self.root = root
        #: False once inside an escaping nested def (and everything
        #: below it): those statements run on an unknown thread, so the
        #: enclosing method's ambient held set must not apply to them
        self.ambient_ok = ambient_ok
        #: method-scope state shared with nested walkers: deferred
        #: non-escaping nested defs ("defs": [(stmt, def_held, label,
        #: ambient_ok)]) and the running INTERSECTION of the held set at
        #: each direct call site of a nested name ("call_held":
        #: name -> fset|None)
        self.shared = shared if shared is not None \
            else {"defs": [], "call_held": {}}

    # -- held-set helpers ----------------------------------------------------
    def _underlying(self, attr: str) -> str:
        d = self.cls.locks.get(attr)
        return d.underlying if d else attr

    # -- access recording ----------------------------------------------------
    def _access(self, attr: str, kind: str, held: FrozenSet[str], line: int,
                escape_to: Optional[str] = None):
        if attr in self.cls.locks:
            return
        self.cls.accesses.append(_Access(
            attr=attr, kind=kind, held=held, method=self.method,
            line=line, in_init=self.in_init, escape_to=escape_to,
            ambient_ok=self.ambient_ok))
        if self.in_init and kind in ("write", "rmw") \
                and attr not in self.cls.init_assign_line:
            self.cls.init_assign_line[attr] = line

    # -- the walk ------------------------------------------------------------
    def walk(self, stmts, held: FrozenSet[str]):
        for stmt in stmts:
            held = self._walk_stmt(stmt, held)
        return held

    def _walk_stmt(self, stmt: ast.stmt, held: FrozenSet[str]
                   ) -> FrozenSet[str]:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in self.cls.locks:
                    inner = inner | {self._underlying(attr)}
                else:
                    self._scan_expr(item.context_expr, held)
            self.walk(stmt.body, inner)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def that never escapes as a value (no thread
            # target, callback registration, return or assignment — only
            # direct ``name()`` calls) runs on the defining thread, so it
            # analyzes under the locks provably held at BOTH its
            # definition site and every direct call site (analysis is
            # deferred to finish(), once every call site's held set has
            # been seen — a def inside ``with lock:`` that is only
            # CALLED after the block must NOT analyze as lock-held).
            # This is what resolves ``Condition(lock)`` aliasing one
            # call level deeper: the ``while not changed(): cv.wait()``
            # predicate idiom defines AND calls ``changed`` inside
            # ``with self._cond:``, and the predicate (plus any private
            # helper it calls) analyzes as holding the condition's
            # underlying lock.  An escaping nested def still analyzes
            # with an empty held set (it runs later, on an unknown
            # thread).
            inherits = (self.root is not None
                        and not stmt.decorator_list
                        and not _nested_escapes(self.root, stmt.name))
            if inherits:
                self.shared["defs"].append(
                    (stmt, held, f"{self.method}.<{stmt.name}>",
                     self.ambient_ok))
                self.shared["call_held"].setdefault(stmt.name, None)
            else:
                nested = _MethodWalker(
                    self.cls, f"{self.method}.<{stmt.name}>",
                    in_init=False, root=self.root, shared=self.shared,
                    ambient_ok=False)
                nested.walk(stmt.body, frozenset())
            return held
        if isinstance(stmt, ast.ClassDef):
            return held          # nested classes are opaque (callgraph.py)
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body, held)
            for handler in stmt.handlers:
                self.walk(handler.body, held)
            self.walk(stmt.orelse, held)
            self.walk(stmt.finalbody, held)
            return held
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, held)
            self._check_then_act(stmt, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            test = getattr(stmt, "test", None) or getattr(stmt, "iter", None)
            if test is not None:
                self._scan_expr(test, held)
            self.walk(stmt.body, held)
            self.walk(getattr(stmt, "orelse", []), held)
            return held
        if isinstance(stmt, ast.Match):
            self._scan_expr(stmt.subject, held)
            for case in stmt.cases:
                self.walk(case.body, held)
            return held
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                attr = _self_attr(stmt.value)
                if attr is not None:
                    self._access(attr, "escape", held, stmt.lineno,
                                 escape_to="")
                else:
                    self._scan_expr(stmt.value, held)
            return held
        return self._scan_leaf(stmt, held)

    def _check_then_act(self, stmt: ast.If, held: FrozenSet[str]):
        """``if self.x ...`` with a write to ``self.x`` in the body: the
        check-then-act pair (recorded as a ``cta`` pseudo-site; flagged
        when the *test* ran without the guard the *act* takes)."""
        read_attrs = {a for sub in ast.walk(stmt.test)
                      if (a := _self_attr(sub)) is not None
                      and a not in self.cls.locks}
        if not read_attrs:
            return
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    a = _self_attr(t)
                    if a in read_attrs:
                        self._access(a, "cta", held, stmt.lineno)

    def _walk_lock_ops(self, stmt: ast.stmt, held: FrozenSet[str]
                       ) -> FrozenSet[str]:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            recv = _self_attr(fn.value)
            if recv is not None and recv in self.cls.locks:
                if fn.attr == "acquire":
                    held = held | {self._underlying(recv)}
                elif fn.attr == "release":
                    held = held - {self._underlying(recv)}
        return held

    def _scan_leaf(self, stmt: ast.stmt, held: FrozenSet[str]
                   ) -> FrozenSet[str]:
        if isinstance(stmt, ast.Assign):
            rmw_attrs = set()
            for t in stmt.targets:
                self._scan_target(t, stmt, held, rmw_attrs)
            self._scan_expr(stmt.value, held, skip_attrs=rmw_attrs)
        elif isinstance(stmt, ast.AugAssign):
            attr = _self_attr(stmt.target)
            if attr is not None:
                self._access(attr, "rmw", held, stmt.lineno)
            elif isinstance(stmt.target, ast.Subscript):
                base = _self_attr(stmt.target.value)
                if base is not None:
                    self._access(base, "rmw", held, stmt.lineno)
                self._scan_expr(stmt.target.slice, held)
            self._scan_expr(stmt.value, held)
        elif isinstance(stmt, (ast.AnnAssign,)):
            attr = _self_attr(stmt.target)
            if attr is not None and stmt.value is not None:
                kind = "rmw" if _reads_attr(stmt.value, attr) else "write"
                self._access(attr, kind, held, stmt.lineno)
            if stmt.value is not None:
                self._scan_expr(stmt.value, held)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                attr = _self_attr(t)
                if attr is not None:
                    self._access(attr, "write", held, stmt.lineno)
                elif isinstance(t, ast.Subscript):
                    base = _self_attr(t.value)
                    if base is not None:
                        self._access(base, "write", held, stmt.lineno)
                    self._scan_expr(t.slice, held)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_expr(child, held)
        return self._walk_lock_ops(stmt, held)

    def _scan_target(self, target: ast.expr, stmt: ast.Assign,
                     held: FrozenSet[str], rmw_attrs: Set[str]):
        attr = _self_attr(target)
        if attr is not None:
            if _reads_attr(stmt.value, attr):
                self._access(attr, "rmw", held, stmt.lineno)
                rmw_attrs.add(attr)
            else:
                self._access(attr, "write", held, stmt.lineno)
            return
        if isinstance(target, ast.Subscript):
            base = _self_attr(target.value)
            if base is not None:
                kind = "rmw" if _reads_attr(stmt.value, base) else "write"
                self._access(base, kind, held, stmt.lineno)
                if kind == "rmw":
                    rmw_attrs.add(base)
                # HVD112: a guarded attr stored by reference into another
                # attribute's container
                stored = _self_attr(stmt.value)
                if stored is not None and stored != base:
                    self._access(stored, "escape", held, stmt.lineno,
                                 escape_to=base)
            else:
                self._scan_expr(target.value, held)
            self._scan_expr(target.slice, held)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._scan_target(elt, stmt, held, rmw_attrs)
            return
        if isinstance(target, ast.Attribute):
            self._scan_expr(target.value, held)

    def finish(self):
        """Analyze the deferred non-escaping nested defs.  Each runs
        under ``def-site held ∩ (∩ call-site helds)`` — never called
        directly means no provable context, so an empty set.  The queue
        drains with a cursor because a nested body may register deeper
        nested defs of its own.

        Order matters: a deferred def called from another deferred
        def's body (``def a(): ...`` / ``def b(): return a()``) must be
        analyzed AFTER its caller, or the call site inside the caller
        has not been recorded yet and the callee falsely analyzes bare.
        At each step, pick a remaining def not called by any other
        remaining def (callers drain first; mutual recursion falls back
        to definition order)."""
        done = 0
        defs = self.shared["defs"]
        while done < len(defs):
            remaining = defs[done:]
            pick = 0
            for j, (stmt_j, _, _, _) in enumerate(remaining):
                if not any(k != j and _calls_name(stmt_k, stmt_j.name)
                           for k, (stmt_k, _, _, _) in enumerate(remaining)):
                    pick = j
                    break
            defs[done], defs[done + pick] = defs[done + pick], defs[done]
            stmt, def_held, label, amb_ok = defs[done]
            done += 1
            call_held = self.shared["call_held"].get(stmt.name)
            effective = def_held & call_held if call_held is not None \
                else frozenset()
            nested = _MethodWalker(self.cls, label, in_init=False,
                                   root=self.root, shared=self.shared,
                                   ambient_ok=amb_ok)
            nested.walk(stmt.body, effective)

    def _scan_expr(self, node: ast.expr, held: FrozenSet[str],
                   skip_attrs: Set[str] = frozenset()):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) \
                    and fn.id in self.shared["call_held"]:
                prev = self.shared["call_held"][fn.id]
                self.shared["call_held"][fn.id] = \
                    held if prev is None else (prev & held)
            handled_fn = False
            if isinstance(fn, ast.Attribute):
                if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                    # self.m(...): intra-class call edge; self._fn(...):
                    # a callable-attribute read
                    handled_fn = True
                    if fn.attr in self.cls.locks:
                        pass
                    elif fn.attr in self.cls.methods:
                        self.cls.calls.append(
                            (self.method, held, fn.attr, node.lineno,
                             self.ambient_ok))
                    else:
                        self._access(fn.attr, "read", held, node.lineno)
                else:
                    # self.X.m(...): lock op on a lock attr; otherwise a
                    # mutator method is a write on X, anything else a read
                    recv = _self_attr(fn.value)
                    if recv is not None:
                        handled_fn = True
                        if recv in self.cls.locks:
                            pass
                        elif fn.attr in MUTATORS:
                            self._access(recv, "write", held, node.lineno)
                        else:
                            self._access(recv, "read", held, node.lineno)
            if not handled_fn:
                self._scan_expr(fn, held, skip_attrs)
            for arg in node.args:
                self._scan_expr(arg, held, skip_attrs)
            for kw in node.keywords:
                self._scan_expr(kw.value, held, skip_attrs)
            return
        attr = _self_attr(node)
        if attr is not None:
            if attr not in skip_attrs:
                kind = "write" if isinstance(node.ctx, ast.Store) else "read"
                self._access(attr, kind, held, node.lineno)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._scan_expr(child, held, skip_attrs)
            elif isinstance(child, ast.keyword):
                self._scan_expr(child.value, held, skip_attrs)
            elif isinstance(child, ast.comprehension):
                self._scan_expr(child.iter, held, skip_attrs)
                for cond in child.ifs:
                    self._scan_expr(cond, held, skip_attrs)


def _merge_class(name: str, graph: callgraph.ModuleCallGraph,
                 path: str) -> _MergedClass:
    """Flatten a class with its same-module bases (nearest override wins)
    so base-class helpers analyze with the subclass's locks."""
    merged = _MergedClass(name=name, node=graph.classes[name], path=path)
    for cls_name in graph.mro_classes(name):
        cls_node = graph.classes[cls_name]
        for stmt in cls_node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name not in merged.methods:
                merged.methods[stmt.name] = (cls_name, stmt)
        for node in ast.walk(cls_node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            ctor = _lock_ctor(node.value)
            if ctor is not None and attr not in merged.locks:
                kind, under = ctor
                merged.locks[attr] = _LockDef(
                    name=attr, kind=kind, underlying=under or attr,
                    line=node.lineno)
    return merged


def _is_thread_ctor(node: ast.expr) -> bool:
    return (isinstance(node, ast.Call)
            and (node.func.attr if isinstance(node.func, ast.Attribute)
                 else node.func.id if isinstance(node.func, ast.Name)
                 else None) == "Thread")


def _collect_init_facts(merged: _MergedClass):
    """Container-valued attrs and the earliest thread-spawn line, from
    every ``__init__`` in the merged chain.  Only ``.start()`` on a
    receiver assigned a ``Thread(...)`` counts as a spawn — servers,
    timers and profilers also have ``.start()`` methods."""
    init = merged.methods.get("__init__")
    if init is None:
        return
    _, fn = init
    thread_receivers: Set[str] = set()       # "self.X" or local name
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            attr = _self_attr(node.targets[0])
            if attr is not None and _is_container_expr(node.value):
                merged.container_attrs.add(attr)
            if _is_thread_ctor(node.value):
                if attr is not None:
                    thread_receivers.add(f"self.{attr}")
                elif isinstance(node.targets[0], ast.Name):
                    thread_receivers.add(node.targets[0].id)
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"):
            continue
        recv = node.func.value
        attr = _self_attr(recv)
        spawns = (
            (attr is not None and f"self.{attr}" in thread_receivers)
            or (isinstance(recv, ast.Name)
                and recv.id in thread_receivers)
            or _is_thread_ctor(recv))        # Thread(...).start() chained
        if spawns and (merged.init_spawn_line is None
                       or node.lineno < merged.init_spawn_line):
            merged.init_spawn_line = node.lineno
            merged.init_spawn_desc = "a thread .start()"


def _ambient_held(merged: _MergedClass, root_methods: Set[str]
                  ) -> Dict[str, FrozenSet[str]]:
    """Locks guaranteed held on entry to each *private* method: the
    intersection over its intra-class call sites, to a fixpoint.  Public
    methods and thread roots are externally callable — ambient empty (a
    thread entry point runs with no lock held no matter who else calls
    it intra-class)."""
    all_locks = frozenset(d.underlying for d in merged.locks.values())
    callers: Dict[str, List[Tuple[Optional[str], FrozenSet[str]]]] = {}
    for caller, held, callee, _line, amb_ok in merged.calls:
        # a call made inside an ESCAPING nested def runs on an unknown
        # thread: the enclosing method's ambient locks are not held
        # there, so that call site contributes only its syntactic held
        # set (caller recorded as None — no ambient lookup)
        base = caller.split(".")[0] if amb_ok else None
        callers.setdefault(callee, []).append((base, held))
    ambient: Dict[str, FrozenSet[str]] = {}
    for m in merged.methods:
        private = m.startswith("_") and not m.startswith("__")
        ambient[m] = all_locks if (private and m in callers
                                   and m not in root_methods) \
            else frozenset()
    for _ in range(len(merged.methods) + 1):
        changed = False
        for m in merged.methods:
            if not ambient[m]:
                continue
            acc = None
            for caller, held in callers.get(m, ()):
                eff = held | (ambient.get(caller, frozenset())
                              if caller is not None else frozenset())
                acc = eff if acc is None else (acc & eff)
            acc = acc if acc is not None else frozenset()
            if acc != ambient[m]:
                ambient[m] = acc
                changed = True
        if not changed:
            break
    return ambient


def _entry_points(graph: callgraph.ModuleCallGraph, cls: str):
    roots = graph.thread_roots(cls)
    reaches = {r.qname: graph.reachable(r.qname) for r in roots}
    public = [q for q, f in graph.functions.items()
              if f.cls in graph.mro_classes(cls)
              and "." not in q.split(".", 1)[1]
              and not q.split(".", 1)[1].startswith("_")]
    main_reach: Set[str] = set()
    for q in public:
        main_reach |= graph.reachable(q)
    return roots, reaches, main_reach


class _ClassCheck:
    def __init__(self, merged: _MergedClass,
                 graph: callgraph.ModuleCallGraph):
        self.m = merged
        self.graph = graph
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        merged, graph = self.m, self.graph
        roots, reaches, main_reach = _entry_points(graph, merged.name)
        if not merged.locks and len(roots) < 2:
            return []
        for mname, (cls_name, fn) in merged.methods.items():
            walker = _MethodWalker(merged, mname,
                                   in_init=(mname == "__init__"), root=fn)
            walker.walk(fn.body, frozenset())
            walker.finish()
        _collect_init_facts(merged)
        # handler-table / executor registrations in __init__ spawn their
        # thread at construction (e.g. an RPC server starting its serve
        # thread inside its own __init__) — a .start() call is not the
        # only way a thread is already running
        init_qnames = {f"{c}.__init__" for c in graph.mro_classes(
            merged.name)}
        for _cls, func, line, via in graph.spawn_sites:
            if func in init_qnames and via in ("handler_table", "executor"):
                if merged.init_spawn_line is None \
                        or line < merged.init_spawn_line:
                    merged.init_spawn_line = line
                    merged.init_spawn_desc = (
                        "a handler-table registration"
                        if via == "handler_table" else "an executor submit")
        root_methods = {r.qname.split(".", 1)[1] for r in roots
                        if r.cls is not None and "." in r.qname}
        ambient = _ambient_held(merged, root_methods)
        # the ambient set applies to the method body AND its
        # non-escaping nested defs (they run inside the method's
        # dynamic extent on the same thread — the second call level of
        # the ``while not pred(): cv.wait()`` idiom, where the
        # predicate lives in a helper whose callers hold the lock);
        # sites inside an ESCAPING nested def run on an unknown thread
        # and stay bare (ambient_ok=False)
        for a in merged.accesses:
            base = a.method.split(".")[0]
            if a.ambient_ok:
                a.held = a.held | ambient.get(base, frozenset())

        by_attr: Dict[str, List[_Access]] = {}
        for a in merged.accesses:
            by_attr.setdefault(a.attr, []).append(a)

        root_reach: Set[str] = set()
        for r in roots:
            root_reach |= reaches[r.qname]

        for attr in sorted(by_attr):
            self._check_attr(attr, by_attr[attr], roots, reaches,
                             main_reach, root_reach)
        return self.findings

    # -- per-attribute verdicts ---------------------------------------------
    def _qname(self, method: str) -> Optional[str]:
        base = method.split(".")[0]
        q = self.graph.resolve_method(self.m.name, base)
        if q is None:
            return None
        if "." in method.replace(base, "", 1):
            # nested context keeps its own identity
            return q + method[len(base):]
        return q

    def _contexts(self, method: str, roots, reaches, main_reach
                  ) -> Set[str]:
        if ".<" in method:
            return {f"nested:{method}"}
        q = self._qname(method)
        ctxs = {r.qname for r in roots
                if q is not None and q in reaches[r.qname]}
        if q is None or q in main_reach or not ctxs:
            ctxs = ctxs | {"main"}
        return ctxs

    def _check_attr(self, attr: str, sites: List[_Access], roots, reaches,
                    main_reach, root_reach: Set[str]):
        live = [s for s in sites if not s.in_init]
        if not live:
            return
        self._check_init_publication(attr, sites, roots, root_reach)

        contexts: Set[str] = set()
        for s in live:
            contexts |= self._contexts(s.method, roots, reaches, main_reach)
        # shared: seen from two thread contexts, or — in a class that
        # owns a lock, the module-visible evidence of concurrency — from
        # two or more sites (any method may run on several threads)
        shared = len(contexts) >= 2 or (bool(self.m.locks)
                                        and len(live) >= 2)
        if not shared:
            return
        if not any(s.kind in ("write", "rmw", "cta") for s in live):
            return          # read-only after __init__: nothing can race

        # lock coverage over the live sites
        cover: Dict[str, int] = {}
        for s in live:
            for lock in s.held:
                cover[lock] = cover.get(lock, 0) + 1
        n = len(live)
        ranked = sorted(cover.items(), key=lambda kv: (-kv[1], kv[0]))
        guard = None
        if ranked and ranked[0][1] / n > _MAJORITY:
            guard = ranked[0][0]
        elif len([1 for _, c in ranked if c / n >= _SPLIT_SHARE]) >= 2:
            a, b = ranked[0], ranked[1]
            self._add("HVD115", live[0].line,
                      f"{self.m.name}: attribute 'self.{attr}' has no "
                      f"majority guard — 'self.{a[0]}' is held at "
                      f"{a[1]}/{n} access sites and 'self.{b[0]}' at "
                      f"{b[1]}/{n}; a split guard protects nothing")
            return
        else:
            # write-lockset fallback: every write under one common lock
            writes = [s for s in live if s.kind in ("write", "rmw")]
            if writes:
                common = frozenset.intersection(
                    *[s.held for s in writes])
                if common:
                    guard = sorted(common)[0]
        if guard is None:
            return

        guarded = sum(1 for s in live if guard in s.held)
        for s in live:
            if guard in s.held:
                continue
            if s.kind == "rmw":
                self._add("HVD111", s.line,
                          f"{self.m.name}.{s.method}: read-modify-write of "
                          f"'self.{attr}' without inferred guard "
                          f"'self.{guard}' (held at {guarded}/{n} access "
                          f"sites); interleaving threads lose an update")
            elif s.kind == "write":
                self._add("HVD110", s.line,
                          f"{self.m.name}.{s.method}: write to "
                          f"'self.{attr}' without inferred guard "
                          f"'self.{guard}' (held at {guarded}/{n} access "
                          f"sites) on a multi-thread-reachable path")
            elif s.kind == "cta":
                # the act is guarded (an unguarded act already reported
                # above); the *check* ran outside the guard
                acts = [t for t in live
                        if t.kind in ("write", "rmw") and guard in t.held]
                if acts:
                    self._add("HVD111", s.line,
                              f"{self.m.name}.{s.method}: check-then-act "
                              f"on 'self.{attr}' — the test runs without "
                              f"inferred guard 'self.{guard}' but the "
                              f"update takes it; the decision can be "
                              f"stale by the time the lock is acquired")

        # HVD112: guarded container escaping by reference
        for s in live:
            if s.kind != "escape" or attr not in self.m.container_attrs:
                continue
            if s.escape_to == "":
                self._add("HVD112", s.line,
                          f"{self.m.name}.{s.method}: returns guarded "
                          f"container 'self.{attr}' by reference; the "
                          f"caller iterates/mutates it after "
                          f"'self.{guard}' is released — return a copy")
            elif s.escape_to is not None:
                dest = s.escape_to
                self._add("HVD112", s.line,
                          f"{self.m.name}.{s.method}: stores guarded "
                          f"container 'self.{attr}' by reference into "
                          f"'self.{dest}', which 'self.{guard}' does not "
                          f"guard — store a copy")

        # HVD113: writes guarded, reads not (the torn-read asymmetry).
        # Bare-return escapes read the attribute too; the container case
        # is HVD112's, reported above.
        writes = [s for s in live if s.kind in ("write", "rmw")]
        reads = [s for s in live
                 if s.kind == "read"
                 or (s.kind == "escape"
                     and attr not in self.m.container_attrs)]
        if writes and reads and all(guard in s.held for s in writes):
            bare = [s for s in reads if guard not in s.held]
            if bare:
                s = min(bare, key=lambda x: x.line)
                self._add("HVD113", s.line,
                          f"{self.m.name}.{s.method}: 'self.{attr}' is "
                          f"written under 'self.{guard}' but read here "
                          f"without it ({len(bare)}/{len(reads)} reads "
                          f"unguarded); the read can observe a torn or "
                          f"stale update")

    def _check_init_publication(self, attr: str, sites: List[_Access],
                                roots, root_reach: Set[str]):
        """HVD114: first assignment after a thread was already started."""
        if not roots or self.m.init_spawn_line is None:
            return
        first = self.m.init_assign_line.get(attr)
        if first is None or first <= self.m.init_spawn_line:
            return
        read_by_thread = any(
            s for s in sites
            if not s.in_init and s.kind in ("read", "rmw", "escape")
            and (q := self._qname(s.method)) is not None
            and q in root_reach)
        if read_by_thread:
            names = ", ".join(sorted(r.qname for r in roots))
            self._add("HVD114", first,
                      f"{self.m.name}.__init__: 'self.{attr}' is first "
                      f"assigned after {self.m.init_spawn_desc} on line "
                      f"{self.m.init_spawn_line} already launched a "
                      f"thread ({names}) that reads it; the thread can "
                      f"observe the attribute missing")

    def _add(self, code: str, line: int, message: str):
        self.findings.append(Finding(code, self.m.path, line, 0, message))


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    graph = callgraph.build_graph(tree)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for name in graph.classes:
        merged = _merge_class(name, graph, path)
        for f in _ClassCheck(merged, graph).run():
            key = (f.code, f.line)
            if key in seen:
                continue        # same base-class line via several subclasses
            seen.add(key)
            findings.append(f)
    return findings
