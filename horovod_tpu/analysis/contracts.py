"""Engine 5: cross-artifact contract checker (HVD300–HVD307).

The other four engines reason about ONE module at a time.  This one
reasons about the REPO: it AST-extracts the registries the operator
surfaces are built from — the ``HOROVOD_*`` env knobs, the metric
families, the JSON-RPC method tables, the chaos injection sites, and
the controller's negotiation-token field schema — and diffs them
against each other and against the docs tables (``docs/env.md``,
``docs/metrics.md``) plus the native extension (``native/core.cpp``).
Every divergence the runtime would only surface as a stale doc, a
silently-dropped metric label, a 404'd RPC, an inert chaos seed, or a
job-merge ``ValueError`` becomes a static finding instead:

====== ==========================================================
HVD300 env var read in code with no config.py row / env.md entry
HVD301 config.py row <-> docs/env.md table drift (both directions)
HVD302 metric family <-> docs/metrics.md drift (both directions)
HVD303 one histogram family declared with two different lo/hi edges
HVD304 RPC method with no handler / handler no client ever calls
HVD305 chaos site drift: fired vs documented vs seeded in tests/CI
HVD306 negotiation-token / EntrySig field-schema drift vs consumers
HVD307 metric call-site labels outside the family's declared labels
====== ==========================================================

Extraction is always repo-wide and anchored at the repo root (found by
walking up from the analyzed files to the directory holding
``docs/env.md``), independent of which paths were passed on the
command line — a ``json_request`` client in one file resolves against
a handler table in another, whether or not both were passed.  Facts
from ``tests/`` join the RESOLUTION sets (a handler a test exercises
is not an orphan) but, with the single exception of HVD305 inert-seed
findings, never anchor findings of their own: tests legitimately read
ad-hoc env vars and register throwaway local handler tables.

Files marked ``# hvdlint: skip-file`` are excluded from extraction —
the antipatterns fixture must not dirty (or silently satisfy!) the
real tree's registries — unless they are explicitly passed as inputs
under ``--include-skipped``, which is how the fixture convicts itself.

The extracted registries are also emitted as stable JSON
(``tools/hvdlint --contracts-json``) for downstream consumers — the
ROADMAP item-3 telemetry->knob controller reads the knob and metric
inventory from here instead of re-scraping the docs.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .report import ANALYZER_VERSION, Finding, apply_suppressions, \
    file_skipped, iter_suppressions

_ENV_RE = re.compile(r"^(?:HOROVOD|HVD)_[A-Z0-9_]+$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")
#: A chaos site name: two+ dot-separated lower_snake segments, none
#: starting with an underscore (filters Python dotted names such as
#: ``os._exit`` out of the docs prose).
_SITE_RE = re.compile(r"^[a-z][a-z0-9_]*(?:\.[a-z][a-z0-9_]*)+$")
#: Fallback action vocabulary when the tree under analysis does not
#: ship chaos/schedule.py (unit-test mini-repos).
_DEFAULT_ACTIONS = frozenset((
    "delay", "drop", "reset", "http500", "error", "crash",
    "dup", "stale", "flap", "drop-reply", "nan", "scale",
))
#: Metric mutator kwargs that are values, not labels.
_VALUE_KWARGS = {"amount", "value"}
#: Histogram bucket-edge defaults (metrics.registry.Registry.histogram).
_HIST_LO, _HIST_HI = -17, 6


# --------------------------------------------------------------------------
# markdown table parsing
# --------------------------------------------------------------------------

def parse_md_tables(text: str) -> List[List[Tuple[int, List[str]]]]:
    """Parse every pipe table in a markdown document.

    Returns a list of tables; each table is a list of
    ``(lineno, cells)`` rows (1-based line numbers, header row
    included, ``|---|`` separator rows dropped).  Tolerances the repo's
    docs actually exercise:

    * escaped pipes (``hit\\|miss\\|stale``) stay inside their cell;
    * leading/trailing ``|`` optional;
    * a non-table continuation line directly under a row (a hand-
      wrapped cell) is folded into that row's last cell;
    * any number of tables per file, prose in between.
    """
    tables: List[List[Tuple[int, List[str]]]] = []
    current: Optional[List[Tuple[int, List[str]]]] = None
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if stripped.startswith("|"):
            cells = _split_row(stripped)
            if all(re.fullmatch(r":?-+:?", c) for c in cells if c):
                continue                      # |---|---| separator
            if current is None:
                current = []
                tables.append(current)
            current.append((lineno, cells))
        elif current is not None and stripped and not stripped.startswith(
                ("#", "```")):
            # wrapped cell: fold the continuation into the last cell
            row = current[-1]
            row[1][-1] = (row[1][-1] + " " + stripped).strip()
        else:
            current = None
    return [t for t in tables if t]


def _split_row(line: str) -> List[str]:
    """Split one ``| a | b |`` row into stripped cells, honoring
    ``\\|`` escapes."""
    cells: List[str] = []
    buf: List[str] = []
    escaped = False
    for ch in line:
        if escaped:
            buf.append(ch)
            escaped = False
        elif ch == "\\":
            escaped = True
        elif ch == "|":
            cells.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
    cells.append("".join(buf).strip())
    if cells and cells[0] == "":
        cells = cells[1:]
    if cells and cells[-1] == "":
        cells = cells[:-1]
    return cells


def _first_backticked(cell: str) -> Optional[str]:
    m = _BACKTICK_RE.search(cell)
    return m.group(1) if m else None


# --------------------------------------------------------------------------
# chaos seed parsing (lightweight re-parse of the rule grammar)
# --------------------------------------------------------------------------

def parse_seed_rules(text: str) -> List[Tuple[str, str]]:
    """``(site, action_kind)`` per rule line in a chaos seed string.

    Mirrors ``chaos.schedule.FaultRule.parse`` just enough to name the
    site and the action kind: rules split on newlines/";", comments
    and blanks skipped, site = first token (":<method>" stripped),
    action = the last ``action=`` token's kind (its ":<arg>" may
    contain anything).  Only dotted sites are returned — the grammar
    unit tests deliberately use sites like ``"a"`` that exist nowhere.
    """
    out: List[Tuple[str, str]] = []
    for raw in re.split(r"[;\n]", text):
        rule = raw.strip()
        if not rule or rule.startswith("#") or " action=" not in rule:
            continue
        site = rule.split()[0].split(":")[0]
        if not _SITE_RE.match(site):
            continue
        idx = rule.rfind(" action=")
        kind = rule[idx + len(" action="):].split(":")[0].split(",")[0]
        kind = kind.split()[0] if kind.split() else kind
        out.append((site, kind))
    return out


# --------------------------------------------------------------------------
# per-module fact extraction
# --------------------------------------------------------------------------

class ModuleFacts:
    """Everything one module contributes to the repo registries."""

    def __init__(self, path: str) -> None:
        self.path = path
        # (env name, line, strict) — strict=True for actual read sites
        # (environ.get / getenv / _env_* helper / environ["X"] loads);
        # strict=False for any other env-shaped string literal (the
        # loose "referenced somewhere" set that keeps doc rows alive).
        self.env_refs: List[Tuple[str, int, bool]] = []
        # (family, kind, labels|None, lo, hi, var|None, line)
        self.metric_decls: List[Tuple[str, str, Optional[Tuple[str, ...]],
                                      int, int, Optional[str], int]] = []
        # (var, mutator, label kwargs, line)
        self.metric_uses: List[Tuple[str, str, Tuple[str, ...], int]] = []
        self.rpc_calls: List[Tuple[str, int]] = []
        self.rpc_handlers: List[Tuple[str, int]] = []
        self.chaos_fires: List[Tuple[str, int]] = []
        self.chaos_seeds: List[Tuple[str, str, int]] = []
        # entry_token producer arity (sig-row list length), if defined
        self.token_producer: Optional[Tuple[int, int]] = None  # (arity, line)
        # token_fields consumers: (func name, max subscript index, line)
        self.token_consumers: List[Tuple[str, int, int]] = []
        self.entry_sig_fields: List[Tuple[str, int]] = []
        self.known_actions: Optional[Set[str]] = None
        self.config_envs: List[Tuple[str, int]] = []


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(func: ast.AST) -> str:
    """Terminal name of a call target (``a.b.c(...)`` -> ``c``)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_environ_ish(node: ast.AST) -> bool:
    """``os.environ`` / ``environ`` / ``env`` / ``base_env`` — the
    receivers env reads go through in this repo."""
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    return name in ("environ", "env", "base_env", "os")


def _resolve_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _resolve_int(node.operand)
        return -inner if inner is not None else None
    return None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = [_const_str(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)  # type: ignore[arg-type]
    return None


class _Extractor(ast.NodeVisitor):
    def __init__(self, facts: ModuleFacts, is_config: bool) -> None:
        self.f = facts
        self.is_config = is_config
        self._func_stack: List[str] = []
        # inside a ``from_env`` body, ``_env_*`` helper calls are the
        # validated-config layer even outside config.py itself
        self._from_env_depth = 0

    # -- helpers ----------------------------------------------------------

    def _note_env(self, name: Optional[str], line: int,
                  strict: bool) -> None:
        if name and _ENV_RE.match(name):
            self.f.env_refs.append((name, line, strict))

    def _handler_keys(self, node: ast.AST, line: int) -> None:
        if isinstance(node, ast.Dict):
            for k in node.keys:
                key = _const_str(k) if k is not None else None
                if key:
                    self.f.rpc_handlers.append((key, line))

    # -- generic fact sweeps ----------------------------------------------

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            v = node.value
            if _ENV_RE.match(v):
                self.f.env_refs.append((v, node.lineno, False))
            if " action=" in v or v.lstrip().startswith("action="):
                for site, kind in parse_seed_rules(v):
                    self.f.chaos_seeds.append((site, kind, node.lineno))

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        # f-string chaos seeds ("... action=delay:{d}"): parse the
        # constant skeleton with the holes blanked out
        parts = [p.value if isinstance(p, ast.Constant)
                 and isinstance(p.value, str) else "0"
                 for p in node.values]
        text = "".join(parts)
        if " action=" in text:
            for site, kind in parse_seed_rules(text):
                self.f.chaos_seeds.append((site, kind, node.lineno))
        self.generic_visit(node)

    # -- assignments ------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        var = None
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            var = node.targets[0].id
        if isinstance(node.value, ast.Call):
            self._maybe_metric_decl(node.value, var)
        if var == "KNOWN_ACTIONS":
            vals = None
            v = node.value
            if isinstance(v, ast.Call) and _call_name(v.func) == "frozenset" \
                    and v.args:
                vals = _str_tuple(v.args[0])
            else:
                vals = _str_tuple(v)
            if vals:
                self.f.known_actions = set(vals)
        self.generic_visit(node)

    # -- calls ------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node.func)
        # env reads: os.environ.get / environ.get / os.getenv
        if name in ("get", "getenv", "pop", "setdefault") \
                and isinstance(node.func, ast.Attribute) \
                and _is_environ_ish(node.func.value) and node.args:
            self._note_env(_const_str(node.args[0]), node.lineno, True)
        # env reads through validated helpers (_env_int & friends)
        elif name.startswith("_env") and node.args:
            env = _const_str(node.args[0])
            self._note_env(env, node.lineno, True)
            if env and _ENV_RE.match(env) \
                    and (self.is_config or self._from_env_depth):
                self.f.config_envs.append((env, node.lineno))
        # metric family declaration outside an assignment (assignment
        # forms were already captured, with the target var, from
        # visit_Assign — the _hvd_decl_done marker prevents doubles)
        if name in ("counter", "gauge", "histogram") \
                and not getattr(node, "_hvd_decl_done", False):
            self._maybe_metric_decl(node, None)
        # metric mutators
        if name in ("inc", "set", "observe") \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name):
            labels = tuple(sorted(
                kw.arg for kw in node.keywords
                if kw.arg and kw.arg not in _VALUE_KWARGS))
            self.f.metric_uses.append(
                (node.func.value.id, name, labels, node.lineno))
        # RPC clients
        if name in ("json_request", "request") and len(node.args) >= 3:
            m = _const_str(node.args[2])
            if m:
                self.f.rpc_calls.append((m, node.lineno))
        elif name == "_call" and isinstance(node.func, ast.Attribute) \
                and node.args:
            m = _const_str(node.args[0])
            if m:
                self.f.rpc_calls.append((m, node.lineno))
        # RPC handler tables
        if name == "JsonRpcServer" and node.args:
            self._handler_keys(node.args[0], node.lineno)
        elif name == "add_handlers" and node.args:
            self._handler_keys(node.args[0], node.lineno)
        # chaos fire sites
        if name == "fire" and isinstance(node.func, ast.Attribute) \
                and node.args:
            site = _const_str(node.args[0])
            if site:
                self.f.chaos_fires.append((site, node.lineno))
        self.generic_visit(node)

    def _maybe_metric_decl(self, call: ast.Call, var: Optional[str]) -> None:
        kind = _call_name(call.func)
        if kind not in ("counter", "gauge", "histogram"):
            return
        if not call.args:
            return
        fam = _const_str(call.args[0])
        if not fam:
            return
        call._hvd_decl_done = True  # type: ignore[attr-defined]
        labels: Optional[Tuple[str, ...]] = ()
        lo, hi = _HIST_LO, _HIST_HI
        # positional: (name, help, labels, lo, hi)
        if len(call.args) >= 3:
            labels = _str_tuple(call.args[2])
        if len(call.args) >= 4:
            lo = _resolve_int(call.args[3]) if _resolve_int(
                call.args[3]) is not None else lo
        if len(call.args) >= 5:
            hi = _resolve_int(call.args[4]) if _resolve_int(
                call.args[4]) is not None else hi
        for kw in call.keywords:
            if kw.arg == "labels":
                labels = _str_tuple(kw.value)
            elif kw.arg == "lo":
                v = _resolve_int(kw.value)
                lo = v if v is not None else lo
            elif kw.arg == "hi":
                v = _resolve_int(kw.value)
                hi = v if v is not None else hi
        self.f.metric_decls.append(
            (fam, kind, labels, lo, hi, var, call.lineno))

    # -- subscripts (environ["X"] loads and stores) -----------------------

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _is_environ_ish(node.value):
            env = _const_str(node.slice)
            strict = isinstance(node.ctx, ast.Load)
            self._note_env(env, node.lineno, strict)
        self.generic_visit(node)

    # -- defs: handler factories, token producers/consumers, EntrySig -----

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._function(node)

    def _function(self, node) -> None:
        if node.name.endswith("handlers"):
            # only THIS function's returns — the nested per-method
            # handler defs return payload dicts, not handler tables
            for sub in _walk_own(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    self._handler_keys(sub.value, sub.lineno)
        if node.name == "entry_token":
            arity = 0
            for sub in ast.walk(node):
                if isinstance(sub, ast.List) and len(sub.elts) >= 4:
                    arity = max(arity, len(sub.elts))
            if arity:
                self.f.token_producer = (arity, node.lineno)
        calls_token_fields = any(
            isinstance(sub, ast.Call)
            and _call_name(sub.func) == "token_fields"
            for sub in ast.walk(node))
        if calls_token_fields:
            max_idx = -1
            at_line = node.lineno
            for sub in ast.walk(node):
                if isinstance(sub, ast.Subscript):
                    idx = _resolve_int(sub.slice)
                    if idx is not None and idx > max_idx:
                        max_idx, at_line = idx, sub.lineno
            if max_idx >= 0:
                self.f.token_consumers.append((node.name, max_idx, at_line))
        if node.name == "from_env":
            self._from_env_depth += 1
            try:
                self.generic_visit(node)
            finally:
                self._from_env_depth -= 1
        else:
            self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if node.name == "EntrySig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    self.f.entry_sig_fields.append(
                        (stmt.target.id, stmt.lineno))
        self.generic_visit(node)


def _walk_own(func) -> Iterable[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    or class definitions."""
    stack: List[ast.AST] = list(func.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def extract_module_facts(tree: ast.Module, path: str) -> ModuleFacts:
    facts = ModuleFacts(path)
    is_config = os.path.basename(path) == "config.py"
    _Extractor(facts, is_config).visit(tree)
    return facts


# --------------------------------------------------------------------------
# repo root + artifact discovery
# --------------------------------------------------------------------------

def find_repo_root(paths: Sequence[str]) -> Optional[str]:
    """Nearest ancestor of the first analyzed path that carries
    ``docs/env.md`` (the cross-artifact anchor); falls back to this
    package's own repo when none of the inputs live inside one."""
    candidates = list(paths) or [os.getcwd()]
    for p in candidates:
        d = os.path.abspath(p)
        if os.path.isfile(d):
            d = os.path.dirname(d)
        for _ in range(40):
            if os.path.isfile(os.path.join(d, "docs", "env.md")):
                return d
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    own = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isfile(os.path.join(own, "docs", "env.md")):
        return own
    return None


_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", "node_modules",
              ".pytest_cache", ".hypothesis", "related"}


def _scan_files(root: str) -> List[str]:
    out: List[str] = []
    for base, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs
                         if d not in _SKIP_DIRS and not d.startswith("."))
        for f in sorted(files):
            if f.endswith(".py"):
                out.append(os.path.join(base, f))
    return out


def _read(path: str) -> Optional[str]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


# --------------------------------------------------------------------------
# the repo-wide registry view
# --------------------------------------------------------------------------

class RepoContracts:
    """Merged registries + doc/native artifacts for one repo root."""

    def __init__(self, root: Optional[str]) -> None:
        self.root = root
        self.modules: Dict[str, ModuleFacts] = {}
        self.sources: Dict[str, str] = {}
        self.is_test: Dict[str, bool] = {}
        self.is_example: Dict[str, bool] = {}
        # docs/env.md
        self.env_doc_rows: List[Tuple[str, int]] = []   # table rows
        self.env_doc_any: Set[str] = set()              # any backtick
        self.chaos_doc_sites: List[Tuple[str, int]] = []
        self.env_doc_path: Optional[str] = None
        # docs/metrics.md
        self.metric_doc_rows: List[Tuple[str, int]] = []
        self.metric_doc_path: Optional[str] = None
        # native/core.cpp parse_sig attrs
        self.cpp_sig_attrs: List[Tuple[str, int]] = []
        self.cpp_path: Optional[str] = None

    # -- module ingestion -------------------------------------------------

    def add_module(self, path: str, source: str, tree: ast.Module) -> None:
        apath = os.path.abspath(path)
        rel = (os.path.relpath(apath, self.root)
               if self.root else os.path.basename(apath))
        self.modules[apath] = extract_module_facts(tree, path)
        self.sources[apath] = source
        self.is_test[apath] = rel.split(os.sep)[0] in ("tests", "test")
        self.is_example[apath] = "examples" in rel.split(os.sep)

    # -- artifact ingestion -----------------------------------------------

    def load_artifacts(self) -> None:
        if not self.root:
            return
        env_md = os.path.join(self.root, "docs", "env.md")
        text = _read(env_md)
        if text is not None:
            self.env_doc_path = env_md
            self._parse_env_doc(text)
        met_md = os.path.join(self.root, "docs", "metrics.md")
        text = _read(met_md)
        if text is not None:
            self.metric_doc_path = met_md
            self._parse_metric_doc(text)
        for cand in (os.path.join(self.root, "horovod_tpu", "native",
                                  "core.cpp"),
                     os.path.join(self.root, "native", "core.cpp")):
            text = _read(cand)
            if text is not None:
                self.cpp_path = cand
                self._parse_cpp(text)
                break

    def _parse_env_doc(self, text: str) -> None:
        for table in parse_md_tables(text):
            for lineno, cells in table:
                if not cells:
                    continue
                name = _first_backticked(cells[0])
                if name and _ENV_RE.match(name):
                    self.env_doc_rows.append((name, lineno))
        in_chaos = False
        seen_sites: Set[str] = set()
        for lineno, line in enumerate(text.splitlines(), start=1):
            if line.startswith("## "):
                in_chaos = "chaos" in line.lower()
            for tok in _BACKTICK_RE.findall(line):
                tok = tok.strip()
                # prose documents boolean knobs as `HOROVOD_X=0` — the
                # value tail is not part of the name
                env_tok = tok.split("=", 1)[0]
                if _ENV_RE.match(env_tok):
                    self.env_doc_any.add(env_tok)
                # chaos site grammar: dotted lower_snake tokens in the
                # chaos section only; file names (`bench.py`) and
                # module paths (`horovod_tpu.chaos`) do not qualify
                if in_chaos and " " not in tok and _SITE_RE.match(tok) \
                        and tok.rsplit(".", 1)[1] not in (
                            "py", "cc", "cpp", "md", "sh", "json", "h") \
                        and not tok.startswith("horovod_tpu.") \
                        and tok not in seen_sites:
                    seen_sites.add(tok)
                    self.chaos_doc_sites.append((tok, lineno))

    def _parse_metric_doc(self, text: str) -> None:
        for table in parse_md_tables(text):
            for lineno, cells in table:
                if not cells:
                    continue
                name = _first_backticked(cells[0])
                if name and re.match(r"^hvd_[a-z0-9_]+$", name):
                    self.metric_doc_rows.append((name, lineno))

    def _parse_cpp(self, text: str) -> None:
        # restrict to the parse_sig function body: from its definition
        # to the next line starting with "}" at column 0
        lines = text.splitlines()
        start = None
        for i, line in enumerate(lines):
            if "parse_sig" in line and "(" in line and ";" not in line:
                start = i
                break
        if start is None:
            return
        attr_re = re.compile(
            r'(?:get_(?:str|ll|bool|opt_double)_attr|'
            r'PyObject_GetAttrString)\s*\(\s*\w+\s*,\s*"(\w+)"')
        depth = 0
        opened = False
        for i in range(start, len(lines)):
            for m in attr_re.finditer(lines[i]):
                self.cpp_sig_attrs.append((m.group(1), i + 1))
            depth += lines[i].count("{") - lines[i].count("}")
            if "{" in lines[i]:
                opened = True
            if opened and depth <= 0:
                break

    # -- merged registry accessors ----------------------------------------

    def _iter_mods(self, tests: Optional[bool] = None
                   ) -> Iterable[Tuple[str, ModuleFacts]]:
        for path, facts in sorted(self.modules.items()):
            if tests is not None and self.is_test[path] != tests:
                continue
            yield path, facts

    def config_envs(self) -> Dict[str, Tuple[str, int]]:
        out: Dict[str, Tuple[str, int]] = {}
        for path, facts in self._iter_mods():
            for name, line in facts.config_envs:
                out.setdefault(name, (path, line))
        return out

    def env_reads(self, strict: bool) -> Dict[str, List[Tuple[str, int]]]:
        out: Dict[str, List[Tuple[str, int]]] = {}
        for path, facts in self._iter_mods():
            for name, line, s in facts.env_refs:
                if strict and not s:
                    continue
                out.setdefault(name, []).append((path, line))
        return out

    def metric_decls(self) -> List[Tuple[str, str, Optional[Tuple[str, ...]],
                                         int, int, Optional[str],
                                         str, int]]:
        out = []
        for path, facts in self._iter_mods():
            base = os.path.basename(path)
            parent = os.path.basename(os.path.dirname(path))
            # the registry/factory layer declares nothing itself
            if parent == "metrics" and base in ("registry.py",
                                                "__init__.py"):
                continue
            for fam, kind, labels, lo, hi, var, line in facts.metric_decls:
                out.append((fam, kind, labels, lo, hi, var, path, line))
        return out

    def rpc_methods(self) -> Tuple[Dict[str, List[Tuple[str, int]]],
                                   Dict[str, List[Tuple[str, int]]]]:
        calls: Dict[str, List[Tuple[str, int]]] = {}
        handlers: Dict[str, List[Tuple[str, int]]] = {}
        for path, facts in self._iter_mods():
            for m, line in facts.rpc_calls:
                calls.setdefault(m, []).append((path, line))
            for m, line in facts.rpc_handlers:
                handlers.setdefault(m, []).append((path, line))
        return calls, handlers

    def chaos(self) -> Tuple[Dict[str, List[Tuple[str, int]]],
                             Dict[str, List[Tuple[str, int]]],
                             List[Tuple[str, str, str, int]], Set[str]]:
        """``(all_fires, pkg_fires, seeds, actions)``: tests fire ad-hoc
        sites to unit-test the schedule machinery, so only PACKAGE fire
        sites define the documented-site contract — but a seed aimed at
        a test-fired site is still live (not inert)."""
        fires: Dict[str, List[Tuple[str, int]]] = {}
        pkg_fires: Dict[str, List[Tuple[str, int]]] = {}
        seeds: List[Tuple[str, str, str, int]] = []
        actions: Optional[Set[str]] = None
        for path, facts in self._iter_mods():
            for site, line in facts.chaos_fires:
                fires.setdefault(site, []).append((path, line))
                if not self.is_test[path]:
                    pkg_fires.setdefault(site, []).append((path, line))
            for site, kind, line in facts.chaos_seeds:
                seeds.append((site, kind, path, line))
            if facts.known_actions is not None:
                actions = facts.known_actions
        return fires, pkg_fires, seeds, (actions or set(_DEFAULT_ACTIONS))


# --------------------------------------------------------------------------
# the checks
# --------------------------------------------------------------------------

def _rel(repo: RepoContracts, path: str) -> str:
    if repo.root:
        try:
            return os.path.relpath(path, repo.root)
        except ValueError:
            pass
    return path


def _emit_for(repo: RepoContracts, path: str, code: str) -> bool:
    """Should a finding anchored at ``path`` be reported?  Test files
    only anchor HVD305 (inert chaos seeds ARE a test-suite bug; ad-hoc
    env reads and local handler tables are not)."""
    # finding paths are repo-root-relative, NOT cwd-relative
    base = repo.root or os.getcwd()
    if repo.is_test.get(os.path.abspath(os.path.join(base, path)), False):
        return code == "HVD305"
    return True


def check_repo(repo: RepoContracts) -> List[Finding]:
    findings: List[Finding] = []
    findings += _check_env(repo)
    findings += _check_metrics(repo)
    findings += _check_rpc(repo)
    findings += _check_chaos(repo)
    findings += _check_token(repo)
    findings = [f for f in findings if _emit_for(repo, f.path, f.code)]
    # per-file suppression comments apply to contract findings too
    # (finding paths are repo-root-relative, NOT cwd-relative)
    base = repo.root or os.getcwd()
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(
            os.path.abspath(os.path.join(base, f.path)), []).append(f)
    out: List[Finding] = []
    for apath, fs in by_path.items():
        src = repo.sources.get(apath)
        if src is not None:
            fs = apply_suppressions(fs, iter_suppressions(src))
        out.extend(fs)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def _check_env(repo: RepoContracts) -> List[Finding]:
    out: List[Finding] = []
    config = repo.config_envs()
    documented = repo.env_doc_any
    strict_reads = repo.env_reads(strict=True)
    loose_refs = repo.env_reads(strict=False)
    if repo.env_doc_path is not None:
        # HVD300: undocumented, unvalidated env read
        for name in sorted(strict_reads):
            if name in config or name in documented:
                continue
            for path, line in strict_reads[name]:
                out.append(Finding(
                    "HVD300", _rel(repo, path), line, 0,
                    f"env var '{name}' is read here but has no validated "
                    f"config.py row and no docs/env.md entry"))
        # HVD301a: config row undocumented
        for name in sorted(config):
            if name not in documented:
                path, line = config[name]
                out.append(Finding(
                    "HVD301", _rel(repo, path), line, 0,
                    f"config.py validates '{name}' but docs/env.md does "
                    f"not document it"))
        # HVD301b: doc table row nothing reads
        doc_rel = _rel(repo, repo.env_doc_path)
        for name, line in repo.env_doc_rows:
            if name not in loose_refs and name not in config:
                out.append(Finding(
                    "HVD301", doc_rel, line, 0,
                    f"docs/env.md documents '{name}' but no code "
                    f"references it"))
    return out


def _check_metrics(repo: RepoContracts) -> List[Finding]:
    out: List[Finding] = []
    decls = repo.metric_decls()
    declared = {d[0] for d in decls}
    doc_names = {n for n, _ in repo.metric_doc_rows}
    if repo.metric_doc_path is not None:
        # HVD302: created-but-undocumented / documented-but-never-created
        seen: Set[str] = set()
        for fam, kind, _labels, _lo, _hi, _var, path, line in decls:
            if fam in doc_names or fam in seen:
                continue
            seen.add(fam)
            out.append(Finding(
                "HVD302", _rel(repo, path), line, 0,
                f"metric family '{fam}' ({kind}) is created here but "
                f"docs/metrics.md does not list it"))
        doc_rel = _rel(repo, repo.metric_doc_path)
        for fam, line in repo.metric_doc_rows:
            if fam not in declared:
                out.append(Finding(
                    "HVD302", doc_rel, line, 0,
                    f"docs/metrics.md lists metric family '{fam}' but no "
                    f"code creates it"))
    # HVD303: one histogram family, two different edge sets
    edges: Dict[str, Tuple[int, int, str, int]] = {}
    for fam, kind, _labels, lo, hi, _var, path, line in decls:
        if kind != "histogram":
            continue
        prev = edges.get(fam)
        if prev is None:
            edges[fam] = (lo, hi, path, line)
        elif (lo, hi) != prev[:2]:
            out.append(Finding(
                "HVD303", _rel(repo, path), line, 0,
                f"histogram family '{fam}' declared here with edges "
                f"lo={lo}, hi={hi} but with lo={prev[0]}, hi={prev[1]} at "
                f"{_rel(repo, prev[2])}:{prev[3]} — the job-level merge "
                f"raises on mismatched buckets"))
    # HVD307: mutator labels outside the family's declared labels
    for path, facts in repo._iter_mods():
        by_var: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        for fam, _kind, labels, _lo, _hi, var, _line in facts.metric_decls:
            if var is not None and labels is not None:
                by_var[var] = (fam, labels)
        for var, mut, kwargs, line in facts.metric_uses:
            decl = by_var.get(var)
            if decl is None:
                continue
            fam, labels = decl
            extra = [k for k in kwargs if k not in labels]
            for k in extra:
                out.append(Finding(
                    "HVD307", _rel(repo, path), line, 0,
                    f"label '{k}' passed to {var}.{mut}() is not among "
                    f"family '{fam}' declared labels {list(labels)} — the "
                    f"registry silently drops unknown labels"))
    return out


def _check_rpc(repo: RepoContracts) -> List[Finding]:
    out: List[Finding] = []
    calls, handlers = repo.rpc_methods()
    for m in sorted(calls):
        if m in handlers:
            continue
        for path, line in calls[m]:
            out.append(Finding(
                "HVD304", _rel(repo, path), line, 0,
                f"RPC method '{m}' is requested here but registered in no "
                f"JsonRpcServer/add_handlers table anywhere in the repo"))
    for m in sorted(handlers):
        if m in calls:
            continue
        for path, line in handlers[m]:
            out.append(Finding(
                "HVD304", _rel(repo, path), line, 0,
                f"RPC handler '{m}' is registered here but no client ever "
                f"requests it"))
    return out


def _check_chaos(repo: RepoContracts) -> List[Finding]:
    out: List[Finding] = []
    fires, pkg_fires, seeds, actions = repo.chaos()
    documented = {s for s, _ in repo.chaos_doc_sites}
    # HVD305: inert seeds + unknown actions (any file, tests included —
    # an inert seed IS a test-suite bug)
    for site, kind, path, line in seeds:
        if site not in fires:
            out.append(Finding(
                "HVD305", _rel(repo, path), line, 0,
                f"chaos seed targets site '{site}' which no code path "
                f"fires — the rule can never inject (inert seed)"))
        if kind not in actions:
            out.append(Finding(
                "HVD305", _rel(repo, path), line, 0,
                f"chaos seed uses unknown action '{kind}' (known: "
                f"{', '.join(sorted(actions))})"))
    if repo.env_doc_path is not None:
        doc_rel = _rel(repo, repo.env_doc_path)
        for site in sorted(pkg_fires):
            if site not in documented:
                path, line = pkg_fires[site][0]
                out.append(Finding(
                    "HVD305", _rel(repo, path), line, 0,
                    f"chaos site '{site}' is fired here but docs/env.md's "
                    f"chaos site list omits it"))
        for site, line in sorted(repo.chaos_doc_sites):
            if site not in pkg_fires:
                out.append(Finding(
                    "HVD305", doc_rel, line, 0,
                    f"docs/env.md documents chaos site '{site}' but no "
                    f"code fires it"))
    return out


def _check_token(repo: RepoContracts) -> List[Finding]:
    out: List[Finding] = []
    # the framework producer: any non-test, non-example module defining
    # entry_token (the antipatterns fixture ships a deliberately-short
    # producer that must never pair with real consumers)
    framework: Optional[Tuple[int, str, int]] = None
    for path, facts in repo._iter_mods(tests=False):
        if repo.is_example.get(path, False):
            continue
        if facts.token_producer is not None:
            arity, line = facts.token_producer
            framework = (arity, path, line)
            break
    for path, facts in repo._iter_mods():
        producer = facts.token_producer
        if producer is not None:
            prod = (producer[0], path, producer[1])
        else:
            prod = framework
        if prod is None:
            continue
        arity, ppath, _pline = prod
        for func, max_idx, line in facts.token_consumers:
            if max_idx >= arity:
                out.append(Finding(
                    "HVD306", _rel(repo, path), line, 0,
                    f"{func}() reads sig field [{max_idx}] but the "
                    f"entry_token producer in {_rel(repo, ppath)} emits "
                    f"only {arity} fields [0..{arity - 1}]"))
    # EntrySig dataclass <-> native core.cpp parse_sig attr parity
    sig_fields: List[Tuple[str, str, int]] = []
    for path, facts in repo._iter_mods(tests=False):
        for name, line in facts.entry_sig_fields:
            sig_fields.append((name, path, line))
    if sig_fields and repo.cpp_sig_attrs and repo.cpp_path:
        py_names = {n for n, _p, _l in sig_fields}
        cpp_names = {n for n, _l in repo.cpp_sig_attrs}
        cpp_rel = _rel(repo, repo.cpp_path)
        for name, path, line in sig_fields:
            if name not in cpp_names:
                out.append(Finding(
                    "HVD306", _rel(repo, path), line, 0,
                    f"EntrySig field '{name}' is not parsed by "
                    f"{cpp_rel}'s parse_sig — the native planner would "
                    f"ignore a negotiated field"))
        seen: Set[str] = set()
        for name, line in repo.cpp_sig_attrs:
            if name not in py_names and name not in seen:
                seen.add(name)
                out.append(Finding(
                    "HVD306", cpp_rel, line, 0,
                    f"native parse_sig reads attr '{name}' which EntrySig "
                    f"does not define — the extension would fail at "
                    f"runtime"))
    return out


# --------------------------------------------------------------------------
# engine entry points
# --------------------------------------------------------------------------

def build_repo(inputs: Sequence[Tuple[str, str, Optional[ast.Module]]],
               include_skipped: bool = False,
               parse=None) -> RepoContracts:
    """Assemble the repo-wide registry view.

    ``inputs`` are the explicitly-analyzed modules as
    ``(path, source, tree)``; the canonical scan set under the repo
    root is added automatically (honoring ``# hvdlint: skip-file``).
    ``parse`` is the shared content-keyed AST cache hook
    (``cli._parse_cached``); plain ``ast.parse`` when absent.
    """
    if parse is None:
        def parse(path, source):           # pragma: no cover - default
            try:
                return ast.parse(source, filename=path)
            except SyntaxError:
                return None
    root = find_repo_root([p for p, _s, _t in inputs])
    repo = RepoContracts(root)
    seen: Set[str] = set()
    for path, source, tree in inputs:
        apath = os.path.abspath(path)
        if apath in seen:
            continue
        seen.add(apath)
        if not include_skipped and file_skipped(source):
            continue
        if tree is None:
            tree = parse(path, source)
        if tree is not None:
            repo.add_module(path, source, tree)
    if root:
        for path in _scan_files(root):
            apath = os.path.abspath(path)
            if apath in seen:
                continue
            seen.add(apath)
            source = _read(path)
            if source is None or file_skipped(source):
                continue
            tree = parse(path, source)
            if tree is not None:
                repo.add_module(path, source, tree)
    repo.load_artifacts()
    return repo


def check_files(inputs: Sequence[Tuple[str, str, Optional[ast.Module]]],
                include_skipped: bool = False,
                parse=None) -> List[Finding]:
    """The contracts engine: repo-wide extraction + all HVD300s."""
    repo = build_repo(inputs, include_skipped=include_skipped, parse=parse)
    return check_repo(repo)


# --------------------------------------------------------------------------
# stable JSON registry emission (tools/hvdlint --contracts-json)
# --------------------------------------------------------------------------

def registries(repo: RepoContracts) -> dict:
    """The extracted registries as one schema-stable dict (sorted keys,
    sorted entries) — the machine-readable knob/metric/RPC/chaos
    inventory downstream controllers consume."""
    config = repo.config_envs()
    strict = repo.env_reads(strict=True)
    documented = repo.env_doc_any
    env_names = sorted(set(config) | set(strict)
                       | {n for n, _ in repo.env_doc_rows})
    env = [{"name": n,
            "validated": n in config,
            "documented": n in documented
            or n in {d for d, _ in repo.env_doc_rows},
            "read_sites": len(strict.get(n, []))}
           for n in env_names]
    fams: Dict[str, dict] = {}
    for fam, kind, labels, lo, hi, _var, _path, _line in \
            repo.metric_decls():
        entry = fams.setdefault(fam, {
            "name": fam, "type": kind,
            "labels": sorted(labels or ()),
            "documented": fam in {n for n, _ in repo.metric_doc_rows},
        })
        if kind == "histogram":
            entry["lo"], entry["hi"] = lo, hi
    calls, handlers = repo.rpc_methods()
    rpc = [{"name": m,
            "handlers": len(handlers.get(m, [])),
            "call_sites": len(calls.get(m, []))}
           for m in sorted(set(calls) | set(handlers))]
    fires, pkg_fires, seeds, actions = repo.chaos()
    chaos = {
        "sites": sorted(set(pkg_fires)),
        "documented_sites": sorted({s for s, _ in repo.chaos_doc_sites}),
        "actions": sorted(actions),
        "seeded_sites": sorted({s for s, _k, _p, _l in seeds}),
    }
    return {
        "analyzer_version": ANALYZER_VERSION,
        "root": repo.root,
        "env": env,
        "metrics": [fams[k] for k in sorted(fams)],
        "rpc": rpc,
        "chaos": chaos,
    }
