"""Finding type, rule catalog, and suppression-comment handling."""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Set, Tuple

#: Analyzer-generation token.  Bump on ANY rule-engine change that can
#: alter what a given source revision produces (new rules, changed
#: inference, changed messages): the AST/finding cache and the baseline
#: fingerprints are keyed on it, so a stale cache entry or an outdated
#: baseline can never silently mask (or resurrect) findings across an
#: analyzer upgrade.  v3 = schedule extractor + divergence dataflow
#: engine (HVD200–HVD215) + nested-def held-set inheritance.  v4 =
#: cross-artifact contract engine (HVD300–HVD307, contracts.py).  v5 =
#: concurrency-lifecycle engine (HVD400–HVD407, lifecycle.py) + ambient
#: held sets reaching nested defs in guarded_by.
ANALYZER_VERSION = 5

# code -> (title, default fix-it).  The fix-it is the actionable half of
# every message: what to change so the job cannot deadlock/diverge.
RULES: Dict[str, Tuple[str, str]] = {
    "HVD000": (
        "file could not be parsed",
        "fix the syntax error so the analyzer (and Python) can read it"),
    "HVD001": (
        "collective inside a rank-conditional branch",
        "hoist the collective out of the `if hvd.rank()` branch — every "
        "process must submit the same collectives in the same order, or "
        "the other ranks deadlock waiting for this one"),
    "HVD002": (
        "DistributedOptimizer without an initial-state broadcast",
        "call hvd.broadcast_parameters(...) (or broadcast_object / an "
        "elastic State) after hvd.init() so every worker starts from "
        "rank 0's weights; without it the replicas silently diverge"),
    "HVD003": (
        "collective on a path not executed by all ranks",
        "move the collective out of the except/early-return path — an "
        "exception or early exit taken on a subset of ranks leaves the "
        "others blocked in the collective"),
    "HVD004": (
        "grouped collective fed from an unordered iteration",
        "sort the tensors (e.g. sorted(names)) before the grouped call — "
        "set/dict iteration order can differ across processes, and the "
        "fusion planner requires an identical submission order everywhere"),
    "HVD005": (
        "tensor name reused with a different op/reduction",
        "give each distinct collective its own name= — the negotiation "
        "matches tensors by name, and one name with two signatures "
        "diverges the ranks"),
    "HVD006": (
        "blocking collective/sync inside a jit-traced function",
        "use the in-jit forms (hvd.allreduce_p etc.) inside jax.jit / "
        "shard_map — the eager API blocks on the background engine, which "
        "deadlocks under tracing; handles cannot be awaited in-graph"),
    "HVD101": (
        "inconsistent lock acquisition order",
        "acquire these locks in one global order everywhere (document it "
        "next to the lock definitions) — opposite nestings on two threads "
        "deadlock"),
    "HVD102": (
        "condition wait while holding another lock",
        "release the outer lock before cv.wait() — wait() only releases "
        "the condition's own lock, so the notifier blocks on the outer "
        "lock and neither thread proceeds"),
    "HVD103": (
        "re-acquiring a non-reentrant lock already held",
        "use threading.RLock, or restructure so the inner path does not "
        "re-enter — a plain Lock self-deadlocks on re-acquisition"),
    "HVD110": (
        "shared attribute written without its inferred guard",
        "hold the lock that guards this attribute's other access sites "
        "around the write, or suppress with an inline justification if "
        "the write provably cannot race (e.g. before any thread starts)"),
    "HVD111": (
        "non-atomic read-modify-write outside the inferred guard",
        "wrap the increment / swap / check-then-act in 'with <guard>:' — "
        "two threads interleaving between the read and the write lose an "
        "update (or act on a stale decision)"),
    "HVD112": (
        "guarded container escapes its lock scope by reference",
        "return or store a copy (list(x), dict(x)) — handing out the raw "
        "container lets callers iterate/mutate it after the guard is "
        "released"),
    "HVD113": (
        "guard held for writes but not for reads",
        "take the same lock on the read side — an unguarded read can "
        "observe a torn or stale update; if the racy read is intentional, "
        "add an inline disable comment stating why it is safe"),
    "HVD114": (
        "attribute published after a thread already started in __init__",
        "assign every attribute the thread reads BEFORE Thread.start() / "
        "server construction — the new thread can run before __init__ "
        "finishes and observe the attribute missing"),
    "HVD115": (
        "split guard: no lock protects a majority of access sites",
        "pick ONE lock to guard this attribute and hold it at every "
        "access site; two locks each covering part of the accesses "
        "exclude nothing"),
    "HVD200": (
        "collective guarded by rank-divergent control flow",
        "hoist the collective out of the branch — the condition "
        "(rank, env var, clock, hostname, unseeded RNG) can evaluate "
        "differently per process, so some ranks never submit it and the "
        "rest deadlock; if every rank must agree, broadcast the decision "
        "from rank 0 first"),
    "HVD201": (
        "collective operand whose shape can diverge across ranks",
        "make the operand shape rank-invariant (pad to a fixed size, or "
        "broadcast the size from rank 0) — reductions require "
        "identically-shaped operands on every rank, and a shape built "
        "from rank/env/RNG mismatches the fused buffer layout"),
    "HVD202": (
        "collective after a rank-divergent early exit",
        "move the divergent return/raise below the collective (or make "
        "every rank take the same path) — ranks that exited early never "
        "submit the collective and the rest block forever"),
    "HVD203": (
        "rank-divergent value published under a shared control-plane key",
        "publish per-rank values under rank-qualified keys, or broadcast "
        "the value from rank 0 before publishing — a shared key written "
        "with different values per rank leaves the control plane in a "
        "last-writer-wins state the ranks don't agree on"),
    "HVD204": (
        "rank-divergent collective parameter",
        "pass the same name/root_rank/op/process_set on every rank — "
        "negotiation matches collectives by these fields, and a "
        "per-rank value (e.g. root_rank=hvd.rank()) pairs incompatible "
        "requests or broadcasts from N different roots"),
    "HVD205": (
        "collective inside a loop with a rank-divergent trip count",
        "make the loop bound identical on every rank (broadcast it from "
        "rank 0) — a rank iterating fewer times submits fewer "
        "collectives, and the peers deadlock on the missing ones"),
    "HVD210": (
        "collective schedule differs across configurations",
        "make the step function's collective sequence independent of "
        "rank and mesh size — every replica must issue the same "
        "collectives in the same order, or the compiled programs "
        "deadlock against each other (see tools/hvdsched --consistency)"),
    "HVD211": (
        "collective schedule drifted from its committed snapshot",
        "if the change is intentional, re-record with tools/hvdsched "
        "--update and commit the snapshot diff for review; otherwise the "
        "fusion plan changed by accident and multi-host jobs may "
        "diverge"),
    "HVD300": (
        "env var read with no validated config row or docs entry",
        "add the knob to config.py's from_env() (validated) or at least "
        "a docs/env.md row — an operator cannot discover or trust a knob "
        "that exists only as a raw os.environ read"),
    "HVD301": (
        "config.py row and docs/env.md table drifted apart",
        "add the missing docs/env.md row (or delete the dead one) — the "
        "env table is the operator contract, and a knob that parses but "
        "isn't documented (or vice versa) WILL be set wrong"),
    "HVD302": (
        "metric family and docs/metrics.md table drifted apart",
        "add the family to the docs/metrics.md table (or drop the stale "
        "row) — dashboards and the job-level merge are built from that "
        "table"),
    "HVD303": (
        "histogram family declared with two different bucket edges",
        "use one (lo, hi) for every declaration of the family — the "
        "driver's job-level merge sums buckets edge-wise and raises on "
        "mismatched edges, so this is a guaranteed runtime ValueError"),
    "HVD304": (
        "RPC method with no handler, or handler no client calls",
        "register the method in a JsonRpcServer({...})/add_handlers "
        "table (or delete the dead handler) — an unregistered method is "
        "a guaranteed 'unknown method' error on first use"),
    "HVD305": (
        "chaos site drift between code, docs and fault seeds",
        "fire the site, fix the seed's site/action string, or update "
        "docs/env.md's site list — an inert seed turns its chaos "
        "regression test into a silent no-op"),
    "HVD306": (
        "negotiation-token / EntrySig field schema drift",
        "keep entry_token's sig row, every token_fields consumer, "
        "EntrySig and native parse_sig in lockstep (append-only fields) "
        "— a consumer indexing past the producer's arity is an "
        "IndexError at negotiation time"),
    "HVD307": (
        "metric call-site labels outside the family's declared labels",
        "pass only the labels the family declared (or extend the "
        "declaration) — the registry silently drops unknown labels, so "
        "the series you meant to split never materializes"),
    "HVD400": (
        "blocking call reached while a lock is held",
        "move the RPC/sleep/join/get outside the critical section "
        "(snapshot what you need under the lock, block after releasing "
        "it) — every other thread needing the lock stalls for the full "
        "wait, a self-inflicted tail no deadline knob can fix"),
    "HVD401": (
        "Condition.wait() outside a while-predicate loop",
        "wrap the wait in `while not predicate(): cv.wait()` — spurious "
        "wakeups and stolen notifications make a bare wait return with "
        "the predicate still false"),
    "HVD402": (
        "job-lifetime container grows with no eviction or bound",
        "add a maxlen/LRU bound or a prune pass keyed on what retires "
        "the entries (request done, worker dead, epoch rolled) — a "
        "per-request append into a long-lived container is a leak that "
        "kills the job at day, not minute, timescales"),
    "HVD403": (
        "non-daemon thread started but never joined",
        "join the thread on the close/stop/__exit__ path (or pass "
        "daemon=True if it holds no state worth flushing) — interpreter "
        "shutdown blocks on every live non-daemon thread"),
    "HVD404": (
        "wall-clock value mixed with monotonic-clock value",
        "derive both sides of the comparison/subtraction from the same "
        "clock — time.time() steps under NTP, so a span against "
        "time.monotonic() can go negative or jump by hours; use "
        "monotonic for durations, wall time for display only"),
    "HVD405": (
        "user callback invoked while holding an internal lock",
        "snapshot the callback list under the lock, call it after "
        "releasing — user code that re-enters the API deadlocks on the "
        "very lock the framework still holds"),
    "HVD406": (
        "shutdown flag cannot wake the loop it stops",
        "make the stop path signal the primitive the loop parks on "
        "(put a sentinel, set the event, or wait with a timeout) — "
        "flipping the flag alone leaves the loop parked forever"),
    "HVD407": (
        "edge-trigger state set on fire but never cleared",
        "clear the key when the condition recovers (or bound the set "
        "with an LRU) — a once-set membership test fires at most once "
        "per process lifetime and the set leaks besides"),
}


@dataclasses.dataclass
class Finding:
    code: str
    path: str
    line: int
    col: int
    message: str

    @property
    def fixit(self) -> str:
        return RULES.get(self.code, ("", ""))[1]

    def format_text(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.code} "
                f"{self.message}\n    fix: {self.fixit}")

    def as_dict(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "fixit": self.fixit}


_DISABLE_RE = re.compile(r"#\s*hvdlint:\s*disable=([A-Za-z0-9,\s]+)")
_SKIP_FILE_RE = re.compile(r"#\s*hvdlint:\s*skip-file\b")


def _comments(source: str):
    """Yield ``(lineno, text, own_line)`` for every REAL comment token.

    Tokenizing (instead of regexing raw source) keeps markers quoted in
    docstrings or string literals inert — otherwise a file merely
    *documenting* ``# hvdlint: skip-file`` would disable its own
    analysis.  Tokenization errors (bad encoding, unterminated strings)
    yield whatever comments were seen before the error; the parse error
    itself is reported separately as HVD000.
    """
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                line_prefix = tok.line[:tok.start[1]]
                yield tok.start[0], tok.string, line_prefix.strip() == ""
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        return


def file_skipped(source: str) -> bool:
    """True when the file opts out wholesale (``# hvdlint: skip-file``)."""
    return any(_SKIP_FILE_RE.search(text) for _, text, _ in _comments(source))


def iter_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> suppressed codes for that line.

    ``# hvdlint: disable=HVD001`` at the end of a line suppresses that
    line; on a line of its own it suppresses the next line (matching the
    ``# noqa`` idiom users already know).  ``disable=all`` suppresses
    every rule.
    """
    out: Dict[int, Set[str]] = {}
    for lineno, text, own_line in _comments(source):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        codes = {c.strip().upper() for c in m.group(1).split(",")
                 if c.strip()}
        out.setdefault(lineno + 1 if own_line else lineno,
                       set()).update(codes)
    return out


def apply_suppressions(findings: Iterable[Finding],
                       suppressions: Dict[int, Set[str]]) -> List[Finding]:
    kept = []
    for f in findings:
        codes = suppressions.get(f.line, set())
        if "ALL" in codes or f.code.upper() in codes:
            continue
        kept.append(f)
    return kept
