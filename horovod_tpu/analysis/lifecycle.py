"""hvdlint engine 6: concurrency-lifecycle checks (HVD400-HVD407).

The framework is a background-thread machine — cycle loop, controller,
RPC pool, lease reaper, sampler/watchdog daemons — and CHANGES.md shows
the same defect classes recurring across PRs faster than review catches
them: the serving dedup-id set that grew per request forever (PR 15),
KvStore stamps leaking per seq (PR 5), ``_tensor_tids`` unbounded
(PR 12), dead workers' rotation EWMAs and ghost gauges accreting
(PR 15), mixed monotonic/wall-clock spans (PR 12), edge-triggered
verdicts that could never re-arm (PR 13).  None of engines 1-5 can see
them: they are not races or contract drift, they are *lifecycle* bugs —
state and threads that outlive the cycle that created them, or waits
that outlive the shutdown that should end them.

Rules
-----

* **HVD400** — a blocking call (``json_request``, socket ops,
  ``time.sleep``, ``Thread.join``, ``subprocess``, timeout-less
  ``queue.get`` / ``Event.wait``) reached **while a lock is held**,
  propagated interprocedurally: a helper that blocks is convicted when
  any caller (transitively) calls it inside ``with self._lock:``.
  OptiReduce's framing applies — tail latency is the production metric,
  and an RPC under the engine lock is a self-inflicted tail no deadline
  knob can fix.  ``Condition.wait`` is exempt (it *releases* the lock;
  HVD401/HVD102 govern it), as are bounded ``join(timeout)`` /
  ``wait(timeout)`` / ``get(timeout=...)``.  A lock acquired at exactly
  ONE site in the module is also exempt: it is a serialization mutex
  (the controller's ``_round_lock`` pattern) — only identical
  operations queue behind it, and that stall is the design; the hazard
  needs a *second* acquisition site whose (possibly quick) path can
  stall behind the blocking one.
* **HVD401** — ``Condition.wait()`` not wrapped in a ``while``-predicate
  loop: spurious wakeups and stolen notifications make a bare ``wait``
  return with the predicate still false.
* **HVD402** — job-lifetime growth: a container attribute on a class
  that owns a thread root or RPC handler table, grown (``append`` /
  ``add`` / subscript-store / ``setdefault``) on a path reachable from
  that root, with **no** eviction, ``maxlen``, reassignment, or prune
  anywhere in the class.  The exact shape of the five leaks above.
* **HVD403** — a non-daemon thread started but never ``join``-ed by any
  method of the owning class (or, for locals, in the spawning
  function): interpreter shutdown hangs waiting for it.
* **HVD404** — clock-domain mixing: a ``time.time()``-derived value
  compared or subtracted against a ``time.monotonic()``-derived one
  (dataflow over locals and self attributes).  NTP steps make such
  spans jump backwards or by hours (the PR-12 buffer-clock incident).
* **HVD405** — a user callback/hook (``on_*``, ``*_hook``,
  ``*_callback``, handler-dict values) invoked while holding an
  internal lock: user code re-entering the API deadlocks on the very
  lock the framework still holds.
* **HVD406** — a shutdown-flag loop (``while not self._stop: ...``)
  parked on a timeout-less ``Event.wait`` / ``Queue.get`` /
  ``lock.acquire()`` whose stop method flips the flag but never signals
  the primitive: the flag changes, the loop never wakes to see it.
* **HVD407** — edge-trigger state set on fire (``if key not in
  self.X: <action>; self.X.add(key)``) with no clearing store anywhere
  in the class: the trigger can fire once per process lifetime (the
  PR-13 stuck-verdict class) — and the set is a leak besides.  The
  guarded body must contain an *action* (a statement-level call beyond
  the arming store itself); a guard around nothing but the store is
  first-write-wins memoization, not an edge trigger.

Like the guarded-by engine this is deliberately module-local and
under-approximating: a lock we cannot resolve contributes no held set,
a receiver we cannot type produces no blocking site, a class whose
threads are spawned from another module is not "long-lived" here.
Missing a finding is acceptable; crying wolf gets linters deleted.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import ModuleCallGraph, build_graph
from .lock_order import _lock_ctor
from .report import Finding

# --------------------------------------------------------------------------
# small shared predicates
# --------------------------------------------------------------------------

#: constructor name -> receiver type tag used by the blocking tables
_CTOR_TYPES = {
    "Thread": "thread",
    "Event": "event",
    "Queue": "queue", "LifoQueue": "queue", "PriorityQueue": "queue",
    "SimpleQueue": "queue", "JoinableQueue": "queue",
    "Condition": "condition",
    "Popen": "popen",
    "socket": "socket", "create_connection": "socket",
}

#: monotonic-domain calls in the ``time`` module
_MONO_FNS = frozenset({"monotonic", "monotonic_ns",
                       "perf_counter", "perf_counter_ns", "thread_time"})
#: wall-clock-domain calls in the ``time`` module
_WALL_FNS = frozenset({"time", "time_ns"})

_SUBPROCESS_BLOCKING = frozenset({"run", "call", "check_call",
                                  "check_output"})
#: attribute calls that are sockets blocking regardless of receiver —
#: these names are specific enough that a false receiver is unlikely
_SOCKET_BLOCKING = frozenset({"accept", "recv", "recvfrom", "recv_into"})

#: method-name fragments that mark a method as being on a shutdown path
_SHUTDOWN_FRAGMENTS = ("close", "stop", "shutdown", "join", "term",
                       "finali", "abort", "quit", "__exit__", "__del__")

_GROW_LIST = frozenset({"append", "appendleft", "extend", "insert"})
_GROW_SET = frozenset({"add"})
_GROW_DICT = frozenset({"setdefault"})
_GROW_ALL = _GROW_LIST | _GROW_SET | _GROW_DICT
_SHRINK = frozenset({"pop", "popleft", "popitem", "clear", "remove",
                     "discard"})


def _self_attr(node: ast.expr) -> Optional[str]:
    """'attr' for a literal ``self.attr`` expression."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _call_name(fn: ast.expr) -> Optional[str]:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _has_timeout(call: ast.Call) -> bool:
    """Any positional arg or a timeout= kwarg bounds the wait."""
    if call.args:
        return True
    return any(kw.arg in ("timeout", "deadline") for kw in call.keywords)


def _hookish(name: str) -> bool:
    """Does this name look like a user-supplied callback slot?"""
    return name.startswith("on_") or \
        name.endswith(("_hook", "_callback", "_cb"))


def _tableish(name: str) -> bool:
    """Does this attribute look like a table of user callbacks?"""
    low = name.lower()
    return "hook" in low or "callback" in low or "listener" in low


def _iter_own(node: ast.AST):
    """``ast.walk`` that does not descend into nested function/class
    bodies — facts inside a nested def belong to that def's own walk."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _ctor_type(expr: ast.expr) -> Optional[str]:
    """Receiver type tag for ``x = Ctor(...)`` style assignments."""
    if not isinstance(expr, ast.Call):
        return None
    name = _call_name(expr.func)
    return _CTOR_TYPES.get(name or "")


def _container_kind(expr: ast.expr) -> Optional[str]:
    """'list' / 'dict' / 'set' / 'deque' for an unbounded container
    initializer; None for anything bounded or unrecognized."""
    if isinstance(expr, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(expr, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(expr, ast.Call):
        name = _call_name(expr.func)
        if name in ("dict", "OrderedDict", "defaultdict", "Counter"):
            return "dict"
        if name == "list":
            return "list"
        if name == "set":
            return "set"
        if name == "deque":
            bounded = any(kw.arg == "maxlen" for kw in expr.keywords) \
                or len(expr.args) > 1
            return None if bounded else "deque"
    return None


# --------------------------------------------------------------------------
# per-class facts (pass 1)
# --------------------------------------------------------------------------

class _ClassFacts:
    """Everything HVD402/403/406/407 need to know about one class."""

    def __init__(self, cls: ast.ClassDef):
        self.name = cls.name
        #: lock attr -> canonical label (conditions resolve to their
        #: underlying lock so ``with self._cond`` == ``with self._lock``)
        self.locks: Dict[str, str] = {}
        self.attr_types: Dict[str, str] = {}       # attr -> type tag
        self.attr_domains: Dict[str, Optional[str]] = {}  # clock domains
        self.containers: Dict[str, str] = {}       # attr -> kind
        #: attr -> (daemonized, ctor line)
        self.threads: Dict[str, Tuple[bool, int]] = {}
        self.started: Set[str] = set()             # thread attrs .start()ed
        self.joined: Set[str] = set()              # thread attrs .join()ed
        #: container growth: attr -> [(method, line, col, guarded)]
        self.grow_sites: Dict[str, List[Tuple[str, int, int, bool]]] = {}
        self.shrunk: Set[str] = set()              # attrs with eviction
        self.reassigned: Set[str] = set()          # reassigned outside init
        #: method -> flag attrs it writes (assign / .set() / .clear())
        self.flag_writes: Dict[str, Set[str]] = {}
        #: method -> attrs it signals (.set() / .put*() / .release() /
        #: .notify*())
        self.signals: Dict[str, Set[str]] = {}
        self._collect(cls)

    # -- collection ----------------------------------------------------------
    def _collect(self, cls: ast.ClassDef):
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for m in methods:
            self._collect_assigns(m)
        for m in methods:
            self._collect_mutations(m)

    def _collect_assigns(self, method: ast.AST):
        in_init = getattr(method, "name", "") == "__init__"
        for node in ast.walk(method):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            attr = _self_attr(target)
            if attr is None:
                # thread daemonization after construction:
                # ``self._t.daemon = True``
                if isinstance(target, ast.Attribute) and \
                        target.attr == "daemon" and \
                        _self_attr(target.value) in self.threads and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value is True:
                    t = _self_attr(target.value)
                    self.threads[t] = (True, self.threads[t][1])
                continue
            lock = _lock_ctor(node.value)
            if lock is not None:
                kind, under = lock
                self.locks[attr] = under or attr
                if kind == "condition":
                    self.attr_types[attr] = "condition"
                continue
            ctype = _ctor_type(node.value)
            if ctype is not None:
                self.attr_types.setdefault(attr, ctype)
                if ctype == "thread" and attr not in self.threads:
                    daemon = any(
                        kw.arg == "daemon" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is True
                        for kw in node.value.keywords)
                    self.threads[attr] = (daemon, node.lineno)
            ckind = _container_kind(node.value)
            if ckind is not None:
                if in_init:
                    self.containers.setdefault(attr, ckind)
                else:
                    # reassignment outside __init__ is a reset — the
                    # container's lifetime is bounded by whatever calls it
                    self.reassigned.add(attr)
            elif not in_init and attr in self.containers:
                self.reassigned.add(attr)
            dom = _expr_domain(node.value, {}, {})
            if dom in ("wall", "mono"):
                prev = self.attr_domains.get(attr, dom)
                self.attr_domains[attr] = dom if prev == dom else None
            # flag writes: ``self._stop = True/False``
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, (bool, type(None))):
                mname = getattr(method, "name", "")
                self.flag_writes.setdefault(mname, set()).add(attr)

    def _collect_mutations(self, method: ast.AST):
        mname = getattr(method, "name", "")
        in_init = mname == "__init__"
        guarded = self._membership_guarded_lines(method)
        for node in ast.walk(method):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                attr = _self_attr(node.func.value)
                verb = node.func.attr
                if attr is None:
                    continue
                if verb in _SHRINK:
                    self.shrunk.add(attr)
                elif verb in _GROW_ALL and not in_init:
                    self.grow_sites.setdefault(attr, []).append(
                        (mname, node.lineno, node.col_offset,
                         node.lineno in guarded.get(attr, set())))
                if verb == "start" and attr in self.threads:
                    self.started.add(attr)
                elif verb == "join" and attr in self.threads:
                    self.joined.add(attr)
                if verb in ("set", "clear") and not node.args:
                    self.flag_writes.setdefault(mname, set()).add(attr)
                if verb in ("set", "put", "put_nowait", "release",
                            "notify", "notify_all"):
                    self.signals.setdefault(mname, set()).add(attr)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript):
                attr = _self_attr(node.targets[0].value)
                if attr is not None and not in_init:
                    self.grow_sites.setdefault(attr, []).append(
                        (mname, node.lineno, node.col_offset,
                         node.lineno in guarded.get(attr, set())))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                    else:
                        attr = _self_attr(t)
                    if attr is not None:
                        self.shrunk.add(attr)

    def _membership_guarded_lines(self, method: ast.AST) \
            -> Dict[str, Set[int]]:
        """attr -> line numbers inside ``if key not in self.attr:``
        bodies — the edge-trigger shape HVD407 convicts (and HVD402
        then leaves to it).

        The body must contain an *action*: a statement-level call other
        than the arming store on the guarded attribute itself.  Without
        one the guard is plain first-write-wins memoization (``if k not
        in self.cache: self.cache[k] = build()``) — idempotent, not an
        edge trigger."""
        out: Dict[str, Set[int]] = {}
        for node in ast.walk(method):
            if not isinstance(node, ast.If):
                continue
            for cmp_node in ast.walk(node.test):
                if not isinstance(cmp_node, ast.Compare) or \
                        len(cmp_node.ops) != 1 or \
                        not isinstance(cmp_node.ops[0], ast.NotIn):
                    continue
                attr = _self_attr(cmp_node.comparators[0])
                if attr is None:
                    continue
                if not self._has_action(node.body, attr):
                    continue
                lines = out.setdefault(attr, set())
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if hasattr(sub, "lineno"):
                            lines.add(sub.lineno)
        return out

    @staticmethod
    def _has_action(body, attr: str) -> bool:
        for stmt in body:
            for sub in ast.walk(stmt):
                if not (isinstance(sub, ast.Expr) and
                        isinstance(sub.value, ast.Call)):
                    continue
                fn = sub.value.func
                if isinstance(fn, ast.Attribute) and \
                        _self_attr(fn.value) == attr and \
                        fn.attr in _GROW_ALL:
                    continue            # the arming store itself
                return True
        return False


# --------------------------------------------------------------------------
# clock-domain evaluation (HVD404)
# --------------------------------------------------------------------------

def _call_domain(call: ast.Call, time_imports: Dict[str, str]) \
        -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time":
        if fn.attr in _WALL_FNS:
            return "wall"
        if fn.attr in _MONO_FNS:
            return "mono"
    if isinstance(fn, ast.Name):
        return time_imports.get(fn.id)
    return None


def _expr_domain(expr: ast.expr, env: Dict[str, Optional[str]],
                 attr_domains: Dict[str, Optional[str]],
                 time_imports: Optional[Dict[str, str]] = None,
                 violations: Optional[List[ast.AST]] = None) \
        -> Optional[str]:
    """'wall' / 'mono' / None for an expression; mixing inside a BinOp
    is appended to ``violations``."""
    time_imports = time_imports or {}
    if isinstance(expr, ast.Call):
        return _call_domain(expr, time_imports)
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    attr = _self_attr(expr)
    if attr is not None:
        return attr_domains.get(attr)
    if isinstance(expr, ast.BinOp) and \
            isinstance(expr.op, (ast.Add, ast.Sub)):
        d1 = _expr_domain(expr.left, env, attr_domains, time_imports,
                         violations)
        d2 = _expr_domain(expr.right, env, attr_domains, time_imports,
                         violations)
        if {d1, d2} == {"wall", "mono"}:
            if violations is not None:
                violations.append(expr)
            return None
        if isinstance(expr.op, ast.Sub) and d1 == d2 and d1 is not None:
            return None        # t1 - t0: a duration, domain-free
        return d1 or d2        # deadline arithmetic: t0 + 5 stays t0's
    return None


def _check_clocks(func: ast.AST, qname: str, path: str,
                  attr_domains: Dict[str, Optional[str]],
                  time_imports: Dict[str, str]) -> List[Finding]:
    """Flow-insensitive per-function pass: type the locals from their
    assignments (conflicts degrade to None), then convict any BinOp or
    Compare that puts a wall value against a monotonic one."""
    env: Dict[str, Optional[str]] = {}
    # the env pass is flow-insensitive, so derived assignments
    # (``deadline = t0 + 5``) may be visited before their sources —
    # iterate to the (tiny) fixpoint instead of relying on visit order
    changed = True
    while changed:
        changed = False
        for node in _iter_own(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                dom = _expr_domain(node.value, env, attr_domains,
                                   time_imports)
                name = node.targets[0].id
                if dom is not None:
                    new = dom if env.get(name, dom) == dom else None
                    if env.get(name, "?") != new:
                        env[name] = new
                        changed = True
    findings: List[Finding] = []
    seen: Set[int] = set()

    def convict(node: ast.AST, d1: str, d2: str):
        if node.lineno in seen:
            return
        seen.add(node.lineno)
        findings.append(Finding(
            "HVD404", path, node.lineno, node.col_offset,
            f"{qname}: {d1}-clock value mixed with {d2}-clock value — "
            f"time.time() can step under NTP; derive both sides from "
            f"the same clock (time.monotonic() for spans)"))

    for node in _iter_own(func):
        violations: List[ast.AST] = []
        if isinstance(node, ast.Compare):
            doms = [_expr_domain(e, env, attr_domains, time_imports,
                                 violations)
                    for e in [node.left] + node.comparators]
            for a, b in zip(doms, doms[1:]):
                if {a, b} == {"wall", "mono"}:
                    convict(node, a, b)
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            _expr_domain(node, env, attr_domains, time_imports, violations)
        for v in violations:
            convict(v, "wall", "mono")
    return findings


# --------------------------------------------------------------------------
# per-function walker with syntactic held sets (pass 2)
# --------------------------------------------------------------------------

class _FuncWalker:
    """Walk one function's statements tracking which locks are held,
    recording blocking sites, call edges, hook invocations, condition
    waits, and shutdown-flag parks.  Nested defs are walked as their
    own graph entries; their direct call sites carry the caller's held
    set into the interprocedural fixpoint, so a nested def handed to a
    ``Thread`` (no call edge) correctly starts bare."""

    def __init__(self, mod: "_Module", qname: str, func: ast.AST,
                 cls: Optional[str]):
        self.mod = mod
        self.qname = qname
        self.func = func
        self.cls = cls
        self.facts = mod.class_facts.get(cls) if cls else None
        self.local_types: Dict[str, str] = {}
        self.hook_aliases: Set[str] = set()
        self.while_depth = 0
        #: flag attrs of every enclosing shutdown-flag while loop
        self.flag_stack: List[FrozenSet[str]] = []
        self._pretype()

    # -- typing --------------------------------------------------------------
    def _pretype(self):
        for node in _iter_own(self.func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = _ctor_type(node.value)
                if t is not None:
                    self.local_types[node.targets[0].id] = t
                src = _self_attr(node.value)
                if src is not None and _hookish(src) and self.facts and \
                        src not in self.mod.defined_methods.get(
                            self.cls or "", set()):
                    self.hook_aliases.add(node.targets[0].id)

    def _recv_type(self, recv: ast.expr) -> Optional[str]:
        attr = _self_attr(recv)
        if attr is not None and self.facts:
            return self.facts.attr_types.get(attr)
        if isinstance(recv, ast.Name):
            t = self.local_types.get(recv.id)
            if t is not None:
                return t
            return self.mod.module_types.get(recv.id)
        return None

    # -- lock labels ---------------------------------------------------------
    def _lock_label(self, expr: ast.expr) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and self.facts and attr in self.facts.locks:
            return f"self.{self.facts.locks[attr]}"
        if isinstance(expr, ast.Name) and expr.id in self.mod.module_locks:
            return expr.id
        return None

    # -- statement walk ------------------------------------------------------
    def walk(self):
        body = getattr(self.func, "body", [])
        self._walk_block(body, frozenset())

    def _walk_block(self, stmts, held: FrozenSet[str]):
        for stmt in stmts:
            held = self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: FrozenSet[str]) \
            -> FrozenSet[str]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return held                      # walked as its own entry
        if isinstance(stmt, ast.With):
            acquired = set()
            for item in stmt.items:
                lbl = self._lock_label(item.context_expr)
                if lbl is not None:
                    acquired.add(lbl)
                    self.mod.lock_sites[lbl] = \
                        self.mod.lock_sites.get(lbl, 0) + 1
                self._scan_expr(item.context_expr, held)
            self._walk_block(stmt.body, held | acquired)
            return held
        if isinstance(stmt, (ast.If,)):
            self._scan_expr(stmt.test, held)
            self._walk_block(stmt.body, held)
            self._walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, held)
            flags = self._flag_attrs(stmt.test)
            self.while_depth += 1
            self.flag_stack.append(frozenset(flags))
            self._walk_block(stmt.body, held)
            self.flag_stack.pop()
            self.while_depth -= 1
            self._walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, held)
            self._mark_hook_loop_var(stmt)
            self._walk_block(stmt.body, held)
            self._walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            self._walk_block(stmt.body, held)
            for handler in stmt.handlers:
                self._walk_block(handler.body, held)
            self._walk_block(stmt.orelse, held)
            self._walk_block(stmt.finalbody, held)
            return held
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            lbl = self._acq_rel(stmt.value)
            if lbl is not None:
                verb, label = lbl
                if verb == "acquire":
                    self.mod.lock_sites[label] = \
                        self.mod.lock_sites.get(label, 0) + 1
                self._scan_expr(stmt.value, held, skip_block=True)
                return held | {label} if verb == "acquire" \
                    else held - {label}
        for field in ast.iter_child_nodes(stmt):
            if isinstance(field, ast.expr):
                self._scan_expr(field, held)
        return held

    def _acq_rel(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("acquire", "release"):
            lbl = self._lock_label(fn.value)
            if lbl is not None:
                return fn.attr, lbl
        return None

    def _flag_attrs(self, test: ast.expr) -> Set[str]:
        """Self attrs read by a while test — candidate shutdown flags.
        ``self._stop.is_set()`` counts as reading ``_stop``."""
        flags: Set[str] = set()
        for node in ast.walk(test):
            attr = _self_attr(node)
            if attr is not None:
                flags.add(attr)
        return flags

    def _mark_hook_loop_var(self, stmt: ast.For):
        """``for cb in self._hooks...: cb(...)`` — the loop var is a
        user callback."""
        if not isinstance(stmt.target, ast.Name):
            return
        for node in ast.walk(stmt.iter):
            attr = _self_attr(node)
            if attr is not None and _tableish(attr):
                self.hook_aliases.add(stmt.target.id)
                return

    # -- expression scan -----------------------------------------------------
    def _scan_expr(self, expr: ast.expr, held: FrozenSet[str],
                   skip_block: bool = False):
        for node in _iter_own(expr):
            if isinstance(node, ast.Call):
                self._on_call(node, held, skip_block=skip_block)

    def _on_call(self, call: ast.Call, held: FrozenSet[str],
                 skip_block: bool = False):
        fn = call.func
        # call edges into the module graph, with the syntactic held set
        callee = None
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and self.cls is not None:
            callee = self.mod.graph.resolve_method(self.cls, fn.attr)
        elif isinstance(fn, ast.Name):
            nested = f"{self.qname}.<{fn.id}>"
            if nested in self.mod.graph.functions:
                callee = nested
            elif fn.id in self.mod.graph.functions:
                callee = fn.id
        if callee is not None:
            self.mod.call_edges.append(
                (self.qname, callee, held, call.lineno))
        if not skip_block:
            blk = self._blocking(call)
            if blk is not None:
                self.mod.block_sites.append(
                    (self.qname, held, call.lineno, call.col_offset, blk))
        self._on_cond_wait(call)
        self._on_hook_call(call, held)
        self._on_park(call)

    # -- HVD400 recognizers --------------------------------------------------
    def _blocking(self, call: ast.Call) -> Optional[str]:
        fn = call.func
        name = _call_name(fn)
        if name == "json_request":
            return "json_request() RPC"
        if name == "urlopen":
            return "urlopen()"
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "time" and \
                    fn.attr == "sleep":
                return "time.sleep()"
            if isinstance(recv, ast.Name) and recv.id == "subprocess" and \
                    fn.attr in _SUBPROCESS_BLOCKING:
                return f"subprocess.{fn.attr}()"
            if fn.attr == "communicate":
                return "Popen.communicate()"
            if fn.attr in _SOCKET_BLOCKING:
                return f"socket .{fn.attr}()"
            rtype = self._recv_type(recv)
            if fn.attr == "join" and rtype == "thread" and \
                    not _has_timeout(call):
                return "Thread.join()"
            if fn.attr == "wait":
                if rtype == "event" and not _has_timeout(call):
                    return "Event.wait()"
                if rtype == "popen":
                    return "Popen.wait()"
            if fn.attr == "get" and rtype == "queue" and \
                    not _has_timeout(call):
                return "queue.get()"
            if fn.attr in ("connect", "sendall") and rtype == "socket":
                return f"socket .{fn.attr}()"
        elif isinstance(fn, ast.Name):
            if fn.id == "sleep" and \
                    self.mod.time_imports.get("sleep") == "sleep":
                return "time.sleep()"
        return None

    # -- HVD401 --------------------------------------------------------------
    def _on_cond_wait(self, call: ast.Call):
        fn = call.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "wait"):
            return
        if self._recv_type(fn.value) != "condition":
            return
        if _has_timeout(call):
            return               # a bounded wait is an interruptible sleep
        if self.while_depth == 0:
            self.mod.bare_waits.append(
                (self.qname, call.lineno, call.col_offset))

    # -- HVD405 --------------------------------------------------------------
    def _on_hook_call(self, call: ast.Call, held: FrozenSet[str]):
        fn = call.func
        label = None
        attr = _self_attr(fn)
        if attr is not None and _hookish(attr) and \
                attr not in self.mod.defined_methods.get(self.cls or "",
                                                         set()):
            label = f"self.{attr}"
        elif isinstance(fn, ast.Subscript):
            table = _self_attr(fn.value)
            if table is not None and _tableish(table):
                label = f"self.{table}[...]"
        elif isinstance(fn, ast.Name) and fn.id in self.hook_aliases:
            label = fn.id
        if label is not None:
            self.mod.hook_calls.append(
                (self.qname, held, call.lineno, call.col_offset, label))

    # -- HVD406 --------------------------------------------------------------
    def _on_park(self, call: ast.Call):
        if not self.flag_stack or not self.flag_stack[-1]:
            return
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return
        attr = _self_attr(fn.value)
        if attr is None:
            return
        rtype = self.facts.attr_types.get(attr) if self.facts else None
        kind = None
        if fn.attr == "wait" and rtype == "event" and not _has_timeout(call):
            kind = "Event.wait()"
        elif fn.attr == "get" and rtype == "queue" and \
                not _has_timeout(call):
            kind = "Queue.get()"
        elif fn.attr == "acquire" and attr in (self.facts.locks
                                               if self.facts else {}) \
                and not call.args and not call.keywords:
            kind = "lock.acquire()"
        if kind is not None:
            flags = frozenset().union(*self.flag_stack)
            self.mod.parks.append(
                (self.qname, self.cls, call.lineno, call.col_offset,
                 kind, attr, flags))


# --------------------------------------------------------------------------
# module orchestration
# --------------------------------------------------------------------------

class _Module:
    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.graph: ModuleCallGraph = build_graph(tree)
        self.class_facts: Dict[str, _ClassFacts] = {
            name: _ClassFacts(node)
            for name, node in self.graph.classes.items()}
        self.defined_methods: Dict[str, Set[str]] = {
            name: {f.qname.split(".", 1)[1]
                   for f in self.graph.functions.values()
                   if f.cls == name and "." not in f.qname.split(".", 1)[1]}
            for name in self.graph.classes}
        self.module_locks: Set[str] = set()
        self.module_types: Dict[str, str] = {}
        self.time_imports: Dict[str, str] = {}
        self._collect_module_scope(tree)
        # walker output
        self.block_sites: List[Tuple[str, FrozenSet[str], int, int,
                                     str]] = []
        #: lock label -> acquisition-site count (With items + acquire())
        self.lock_sites: Dict[str, int] = {}
        self.call_edges: List[Tuple[str, str, FrozenSet[str], int]] = []
        self.bare_waits: List[Tuple[str, int, int]] = []
        self.hook_calls: List[Tuple[str, FrozenSet[str], int, int,
                                    str]] = []
        self.parks: List[Tuple[str, Optional[str], int, int, str, str,
                               FrozenSet[str]]] = []

    def _collect_module_scope(self, tree: ast.Module):
        for stmt in tree.body:
            if isinstance(stmt, ast.ImportFrom) and stmt.module == "time":
                for alias in stmt.names:
                    name = alias.asname or alias.name
                    if alias.name in _WALL_FNS:
                        self.time_imports[name] = "wall"
                    elif alias.name in _MONO_FNS:
                        self.time_imports[name] = "mono"
                    elif alias.name == "sleep":
                        self.time_imports[name] = "sleep"
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                if _lock_ctor(stmt.value) is not None:
                    self.module_locks.add(stmt.targets[0].id)
                t = _ctor_type(stmt.value)
                if t is not None:
                    self.module_types[stmt.targets[0].id] = t

    # -- interprocedural may-hold fixpoint (HVD400/405) ----------------------
    def entry_held(self) -> Tuple[Dict[str, FrozenSet[str]],
                                  Dict[Tuple[str, str], Tuple[str, int]]]:
        """For each function, the union of lock sets its callers hold at
        their call sites (transitively).  This is a *may*-hold union —
        one locked path to a blocking helper is a hazard even if other
        paths are bare — dual to guarded_by's must-hold intersection."""
        entry: Dict[str, FrozenSet[str]] = {}
        witness: Dict[Tuple[str, str], Tuple[str, int]] = {}
        changed = True
        while changed:
            changed = False
            for caller, callee, held, line in self.call_edges:
                eff = held | entry.get(caller, frozenset())
                cur = entry.get(callee, frozenset())
                if not eff <= cur:
                    entry[callee] = cur | eff
                    for lock in eff - cur:
                        witness.setdefault((callee, lock), (caller, line))
                    changed = True
        return entry, witness


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    mod = _Module(tree, path)
    for qname, info in mod.graph.functions.items():
        _FuncWalker(mod, qname, info.node, info.cls).walk()
    entry, witness = mod.entry_held()
    findings: List[Finding] = []
    findings += _verdict_400(mod, entry, witness)
    findings += _verdict_401(mod)
    edge_attrs = _verdict_407(mod, findings)
    findings += _verdict_402(mod, edge_attrs)
    findings += _verdict_403(mod)
    findings += _verdict_404(mod)
    findings += _verdict_405(mod, entry)
    findings += _verdict_406(mod)
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    return findings


# --------------------------------------------------------------------------
# verdicts
# --------------------------------------------------------------------------

def _fmt_locks(locks) -> str:
    return ", ".join(f"'{x}'" for x in sorted(locks))


def _verdict_400(mod, entry, witness) -> List[Finding]:
    out = []
    for qname, held, line, col, desc in mod.block_sites:
        ambient = entry.get(qname, frozenset())
        # a lock with a single acquisition site is a serialization
        # mutex: only identical operations queue behind it, and that
        # stall is the design (controller._round_lock) — the tail
        # hazard needs a second site that can stall behind this one
        eff = {lk for lk in held | ambient
               if mod.lock_sites.get(lk, 0) >= 2}
        if not eff:
            continue
        via = ""
        for lock in sorted(eff - held):
            w = witness.get((qname, lock))
            if w is not None:
                via = f" (reached from {w[0]}:{w[1]}, which holds it)"
                break
        out.append(Finding(
            "HVD400", mod.path, line, col,
            f"{qname}: blocking {desc} while holding "
            f"{_fmt_locks(eff)}{via} — every other thread needing the "
            f"lock stalls for the full wait; move the call outside the "
            f"critical section"))
    return out


def _verdict_401(mod) -> List[Finding]:
    return [Finding(
        "HVD401", mod.path, line, col,
        f"{qname}: Condition.wait() outside a while-predicate loop — "
        f"spurious wakeups and stolen notifications return with the "
        f"predicate still false; use `while not pred(): cv.wait()`")
        for qname, line, col in mod.bare_waits]


def _verdict_402(mod, edge_attrs: Set[Tuple[str, str]]) -> List[Finding]:
    out = []
    for cls, facts in mod.class_facts.items():
        roots = mod.graph.thread_roots(cls)
        if not roots:
            continue          # not provably long-lived in this module
        reach: Set[str] = set()
        for r in roots:
            reach |= mod.graph.reachable(r.qname)
        for attr, kind in facts.containers.items():
            if attr in facts.shrunk or attr in facts.reassigned:
                continue
            if (cls, attr) in edge_attrs:
                continue      # HVD407 already owns this attribute
            for method, line, col, _guarded in facts.grow_sites.get(
                    attr, []):
                q = f"{cls}.{method}"
                if q not in reach and not any(
                        r.qname == q for r in roots):
                    continue
                out.append(Finding(
                    "HVD402", mod.path, line, col,
                    f"{cls}.{method}: grows job-lifetime {kind} "
                    f"'self.{attr}' on a thread-root path with no "
                    f"eviction/maxlen/prune anywhere in {cls} — this "
                    f"is unbounded for the life of the job; add an LRU "
                    f"bound, a maxlen, or a prune pass"))
                break         # one finding per attribute is enough
    return out


def _verdict_403(mod) -> List[Finding]:
    out = []
    for cls, facts in mod.class_facts.items():
        for attr, (daemon, line) in facts.threads.items():
            if daemon or attr not in facts.started:
                continue
            if attr in facts.joined:
                continue
            out.append(Finding(
                "HVD403", mod.path, line, 0,
                f"{cls}: non-daemon thread 'self.{attr}' is started but "
                f"no method of {cls} ever joins it — interpreter "
                f"shutdown blocks on it forever; join it on the "
                f"close/stop path or mark it daemon=True"))
    # local fire-and-forget: threading.Thread(...).start() inline with
    # no daemon=True — never joinable at all
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "start" and \
                isinstance(node.func.value, ast.Call) and \
                _call_name(node.func.value.func) == "Thread":
            ctor = node.func.value
            daemon = any(kw.arg == "daemon" and
                         isinstance(kw.value, ast.Constant) and
                         kw.value.value is True for kw in ctor.keywords)
            if not daemon:
                out.append(Finding(
                    "HVD403", mod.path, node.lineno, node.col_offset,
                    "fire-and-forget non-daemon thread: "
                    "Thread(...).start() inline keeps no handle, so "
                    "nothing can ever join it and shutdown hangs on "
                    "it; keep the handle and join, or pass daemon=True"))
    return out


def _verdict_404(mod) -> List[Finding]:
    out = []
    for qname, info in mod.graph.functions.items():
        attr_domains = mod.class_facts[info.cls].attr_domains \
            if info.cls in mod.class_facts else {}
        out += _check_clocks(info.node, qname, mod.path, attr_domains,
                             mod.time_imports)
    return out


def _verdict_405(mod, entry) -> List[Finding]:
    out = []
    for qname, held, line, col, label in mod.hook_calls:
        eff = held | entry.get(qname, frozenset())
        if not eff:
            continue
        out.append(Finding(
            "HVD405", mod.path, line, col,
            f"{qname}: user callback {label} invoked while holding "
            f"{_fmt_locks(eff)} — a callback that re-enters the API "
            f"deadlocks on the lock the framework still holds; snapshot "
            f"under the lock, invoke after releasing it"))
    return out


def _verdict_406(mod) -> List[Finding]:
    out = []
    for qname, cls, line, col, kind, attr, flags in mod.parks:
        facts = mod.class_facts.get(cls or "")
        if facts is None:
            continue
        # is the park parked *on* the flag itself?  then flipping the
        # flag (Event.set) IS the wakeup — nothing to convict.
        if attr in flags:
            continue
        writers = [m for m, written in facts.flag_writes.items()
                   if written & flags and m != "__init__"]
        if not writers:
            continue          # flag not stop-controlled in this module
        if any(attr in facts.signals.get(m, set()) for m in writers):
            continue          # stop path signals the parked primitive
        out.append(Finding(
            "HVD406", mod.path, line, col,
            f"{qname}: {kind} on 'self.{attr}' parks a loop that "
            f"'{_fmt_locks(flags)}' is supposed to stop, but "
            f"{', '.join(sorted(set(writers)))} only flips the flag — "
            f"the loop never wakes to see it; signal the primitive "
            f"(put a sentinel / set the event) or wait with a timeout"))
    return out


def _verdict_407(mod, findings: List[Finding]) -> Set[Tuple[str, str]]:
    """Returns the (cls, attr) pairs convicted, so HVD402 skips them."""
    owned: Set[Tuple[str, str]] = set()
    for cls, facts in mod.class_facts.items():
        for attr, sites in facts.grow_sites.items():
            guarded = [s for s in sites if s[3]]
            if not guarded:
                continue
            if attr in facts.shrunk or attr in facts.reassigned:
                continue
            owned.add((cls, attr))
            method, line, col, _ = guarded[0]
            findings.append(Finding(
                "HVD407", mod.path, line, col,
                f"{cls}.{method}: edge-trigger state 'self.{attr}' is "
                f"set on fire (membership-guarded add) but no path in "
                f"{cls} ever clears it — the trigger fires at most once "
                f"per process and the set leaks besides; clear the key "
                f"when the condition recovers, or bound it with an LRU"))
    return owned
