"""Intra-module call graph with thread-entry-point detection.

The guarded-by engine (``guarded_by.py``) and the one-level helper
expansion in the user rules both need the same structural facts about a
module: which functions exist (module functions, methods, nested defs),
who calls whom, and which functions are **thread roots** — entry points
that run on a thread other than the one that constructed the object.

Thread roots recognized (the framework's own idioms, all of which appear
in ``ops/engine.py`` / ``elastic/driver.py`` / ``runner/rpc.py``):

* ``threading.Thread(target=X)`` — the classic background loop;
* ``<executor>.submit(X, ...)`` — concurrent.futures style submission;
* **handler tables** — a dict literal mapping names to bound methods
  passed into a constructor-like call (``JsonRpcServer({"result":
  self._handle_result})``): each value runs on an RPC server thread.
  Keyword dict arguments (``get_routes={...}``) count too.

Resolution is deliberately module-local and name-based: ``self.m()``
resolves within the enclosing class (and its same-module bases),
``f()`` resolves to a module-level function, nested defs resolve within
their enclosing function.  Anything else (imported callables, attribute
chains on non-self objects) is outside the graph — a *static under-*
approximation, which is the safe direction for the race detector: a
method we cannot prove thread-reachable produces no finding.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

#: Attribute-call names that submit work to another thread.
_SUBMIT_NAMES = frozenset({"submit"})


@dataclasses.dataclass
class FuncInfo:
    """One function/method/nested-def node in the graph."""
    qname: str                      # "f", "Cls.m", "Cls.m.<nested>"
    node: ast.AST
    cls: Optional[str] = None       # owning class name, if a method
    calls: Set[str] = dataclasses.field(default_factory=set)
    #: how this function became a thread root ("" = not a root)
    entry_via: str = ""
    entry_line: int = 0


class ModuleCallGraph:
    """Call graph of one module's AST (build with :func:`build_graph`)."""

    def __init__(self):
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        #: class name -> same-module base class names, nearest first
        self.bases: Dict[str, List[str]] = {}
        #: (cls, line) of each thread-spawning call found in a method —
        #: used by guarded_by's HVD114 (publication before spawn)
        self.spawn_sites: List[Tuple[Optional[str], str, int, str]] = []

    # -- queries -------------------------------------------------------------
    def mro_classes(self, cls: str) -> List[str]:
        """``cls`` plus its same-module ancestors, nearest first."""
        out, queue = [], [cls]
        while queue:
            c = queue.pop(0)
            if c in out or c not in self.classes:
                continue
            out.append(c)
            queue.extend(self.bases.get(c, []))
        return out

    def resolve_method(self, cls: str, name: str) -> Optional[str]:
        """Qualified name of ``self.<name>`` seen from class ``cls``."""
        for c in self.mro_classes(cls):
            q = f"{c}.{name}"
            if q in self.functions:
                return q
        return None

    def thread_roots(self, cls: Optional[str] = None) -> List[FuncInfo]:
        """All thread entry points, optionally restricted to methods of
        ``cls`` (including same-module bases)."""
        roots = [f for f in self.functions.values() if f.entry_via]
        if cls is not None:
            wanted = set(self.mro_classes(cls))
            roots = [f for f in roots if f.cls in wanted]
        return roots

    def reachable(self, qname: str) -> Set[str]:
        """Qualified names reachable from ``qname`` (inclusive)."""
        seen: Set[str] = set()
        queue = [qname]
        while queue:
            q = queue.pop()
            if q in seen or q not in self.functions:
                continue
            seen.add(q)
            queue.extend(self.functions[q].calls)
        return seen


def _func_ref(node: ast.expr, cls: Optional[str], enclosing: str,
              graph: ModuleCallGraph) -> Optional[str]:
    """Resolve an expression used as a callable *value* (thread target,
    submit arg, handler-table value) to a graph qname."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self" and cls is not None:
        return graph.resolve_method(cls, node.attr)
    if isinstance(node, ast.Name):
        # nested def in the enclosing function shadows a module function
        if enclosing:
            nested = f"{enclosing}.<{node.id}>"
            if nested in graph.functions:
                return nested
        if node.id in graph.functions:
            return node.id
    return None


def _callee_name(fn: ast.expr) -> Optional[str]:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


class _ScopedVisitor(ast.NodeVisitor):
    """Shared scope bookkeeping for both passes: one qname scheme
    (module ``f``, method ``Cls.m``, nested ``outer.<inner>``), one
    top-level-class-only rule.  Subclasses hook ``on_class`` /
    ``on_func`` — keeping registration (pass 1) and edge resolution
    (pass 2) on exactly the same naming."""

    def __init__(self, graph: ModuleCallGraph):
        self.graph = graph
        self._cls: Optional[str] = None
        self._func: str = ""

    def on_class(self, node: ast.ClassDef):
        pass

    def on_func(self, node, qname: str):
        pass

    def visit_ClassDef(self, node: ast.ClassDef):
        if self._cls is None and not self._func:
            self.on_class(node)
            prev, self._cls = self._cls, node.name
            for stmt in node.body:
                self.visit(stmt)
            self._cls = prev
        # nested classes: opaque to the graph (rare, and under-approx is safe)

    def _enter(self, node):
        if self._func:
            qname = f"{self._func}.<{node.name}>"
        elif self._cls:
            qname = f"{self._cls}.{node.name}"
        else:
            qname = node.name
        self.on_func(node, qname)
        prev, self._func = self._func, qname
        for stmt in node.body:
            self.visit(stmt)
        self._func = prev

    def visit_FunctionDef(self, node):
        self._enter(node)

    def visit_AsyncFunctionDef(self, node):
        self._enter(node)


class _Collector(_ScopedVisitor):
    """Pass 1: register every class and function/method/nested def."""

    def on_class(self, node: ast.ClassDef):
        self.graph.classes[node.name] = node
        self.graph.bases[node.name] = [
            b.id for b in node.bases if isinstance(b, ast.Name)]

    def on_func(self, node, qname: str):
        self.graph.functions[qname] = FuncInfo(
            qname=qname, node=node, cls=self._cls)


class _EdgeVisitor(_ScopedVisitor):
    """Pass 2: call edges + thread-entry registration."""

    def _mark_entry(self, target: ast.expr, via: str, line: int):
        q = _func_ref(target, self._cls, self._func, self.graph)
        if q is not None:
            info = self.graph.functions[q]
            if not info.entry_via:
                info.entry_via, info.entry_line = via, line
            self.graph.spawn_sites.append((self._cls, self._func, line, via))

    def visit_Call(self, node: ast.Call):
        callee = _callee_name(node.func)
        # threading.Thread(target=X) / Thread(target=X)
        if callee == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self._mark_entry(kw.value, "thread", node.lineno)
        # <executor>.submit(X, ...)
        elif callee in _SUBMIT_NAMES and isinstance(node.func, ast.Attribute) \
                and node.args:
            self._mark_entry(node.args[0], "executor", node.lineno)
        # handler tables: dict literals with function-ref values passed
        # into any call (JsonRpcServer({...}, get_routes={...}))
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Dict):
                for v in arg.values:
                    if v is not None and _func_ref(
                            v, self._cls, self._func, self.graph):
                        self._mark_entry(v, "handler_table", node.lineno)
        # plain call edges
        if self._func:
            src = self.graph.functions[self._func]
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "self" and self._cls is not None:
                q = self.graph.resolve_method(self._cls, fn.attr)
                if q is not None:
                    src.calls.add(q)
            elif isinstance(fn, ast.Name):
                q = _func_ref(fn, self._cls, self._func, self.graph)
                if q is not None:
                    src.calls.add(q)
        self.generic_visit(node)


def build_graph(tree: ast.Module) -> ModuleCallGraph:
    """Two-pass construction: collect every def, then resolve edges and
    thread entry points (a target can be defined after its spawn site)."""
    graph = ModuleCallGraph()
    collector = _Collector(graph)
    for stmt in tree.body:
        collector.visit(stmt)
    edges = _EdgeVisitor(graph)
    for stmt in tree.body:
        edges.visit(stmt)
    return graph
