"""hvdlint: static collective-consistency and lock-order analysis.

The runtime controller (``ops/controller.py``) diagnoses rank divergence
only after a job has stalled for ``HOROVOD_STALL_CHECK_TIME`` seconds on
real hardware.  The classic Horovod failure classes — collectives under
rank-conditional branches, missing initial-state broadcast, mismatched
submission order — are statically detectable in user scripts, so this
package catches them in CI instead of on a TPU reservation.

Six engines:

* **user-script rules** (``user_rules.py``): HVD001–HVD006, AST checks
  over training scripts for the deadlock/divergence hazard taxonomy —
  rank/except/jit hazards see through one level of helper functions.
* **lock-order self-check** (``lock_order.py``): HVD101–HVD103, a
  lock-acquisition-graph deadlock detector over our own threaded modules
  (engine, controller, elastic driver, stall inspector).
* **guarded-by self-check** (``guarded_by.py`` over ``callgraph.py``):
  HVD110–HVD115, Eraser-style lock-set race detection — each shared
  attribute's guard is inferred from the lock held at the majority of
  its access sites, and unguarded writes / read-modify-writes / torn
  reads / init-time publication races are reported.  A findings
  baseline (``tools/hvdlint_baseline.json``, ``--baseline`` /
  ``--update-baseline``) lets CI fail only on NEW findings.
* **SPMD divergence dataflow** (``divergence.py``): HVD200–HVD211,
  rank-divergent control flow / operand shapes / collective parameters,
  plus the committed collective-schedule snapshot checks.
* **cross-artifact contracts** (``contracts.py``): HVD300–HVD307, the
  repo-wide pass keeping config rows, docs tables, metric families,
  RPC handler tables, chaos sites and the negotiation token schema in
  lockstep.
* **concurrency lifecycle** (``lifecycle.py``): HVD400–HVD407,
  blocking-under-lock (interprocedural over the call graph), unbounded
  job-lifetime growth, wall/monotonic clock mixing, and shutdown
  hygiene (unjoined threads, unwakeable stop loops, stuck
  edge-triggers).

CLI::

    python -m horovod_tpu.analysis horovod_tpu/ examples/
    tools/hvdlint --format=json path/to/train.py

Suppress a finding with ``# hvdlint: disable=HVD001`` on (or directly
above) the flagged line, or skip a whole file with
``# hvdlint: skip-file``.  See docs/analysis.md for the rule catalog.

The analysis modules themselves import only the standard library (no
jax, no numpy), so a lint run costs AST parsing, nothing more.  (The
``horovod_tpu`` parent package still imports its runtime deps on entry,
so the CLI needs the normal install — as in CI.)
"""

from .report import Finding, RULES, iter_suppressions  # noqa: F401
from .cli import analyze_paths, analyze_source, main  # noqa: F401

__all__ = [
    "Finding", "RULES", "analyze_paths", "analyze_source", "main",
    "iter_suppressions",
]
