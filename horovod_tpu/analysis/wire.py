"""Ring-model wire-byte accounting over traced collective schedules.

The CPU-mesh benches (``tools/bench_zero.py``, ``bench_compression.py``,
``bench_overlap.py``) all answer the same question — "how many bytes
does one step move per worker?" — from the SAME source of truth: the
collective schedule ``analysis/schedule.py`` extracts from the step's
jaxpr.  This module is the one implementation of that accounting (it
used to live inline in each bench): per-collective transmit bytes under
the standard ring algorithms, summed over a schedule, plus the
primitive-count summary the A/B tables print.

The model is the textbook ring cost, not a profile: psum (allreduce)
moves ``2(n-1)/n`` of the payload per worker, reduce-scatter /
all_to_all ``(n-1)/n`` of the *input*, all_gather ``(n-1)/n`` of the
*output*.  Collectives over axes absent from ``axis_sizes`` (e.g. a tp
axis when only dp is being accounted) contribute zero; ``axis_filter``
restricts to one hop (e.g. only the DCN axis of a hierarchical
reduction).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

_AVAL_RE = re.compile(r"^(\w+)\[([\dx]*)\]$")


def aval_nbytes(aval: str) -> int:
    """Bytes of one ``dtype[axb...]`` aval string from a schedule record
    (widths from the fusion planner's table — unknown dtypes raise)."""
    from ..ops.fusion import dtype_nbytes
    m = _AVAL_RE.match(aval)
    if not m:
        raise ValueError(f"unparseable aval {aval!r}")
    dims = [int(d) for d in m.group(2).split("x")] if m.group(2) else []
    numel = 1
    for d in dims:
        numel *= d
    return numel * dtype_nbytes(m.group(1))


def ring_transmit_bytes(record, axis_sizes: Dict[str, int],
                        axis_filter: Optional[str] = None,
                        strict: bool = False) -> int:
    """Per-worker transmit bytes of one collective under the standard
    ring algorithms (see module docstring).  ``record`` is an
    ``analysis.schedule.CollectiveRecord``.

    ``pmax``/``pmin`` cost like ``psum`` (a combining allreduce moves
    the same bytes whatever the combiner) — they used to fall into the
    conservative unknown-prim fallback, which overstated the
    tail-reduce's pmin membership-agreement round ~2x.  ``strict=True``
    RAISES on a primitive the model doesn't know instead of guessing
    ``in_bytes``: byte-conservation gates (``tools/bench_tail.py``)
    must fail loudly when a schedule grows a collective the accounting
    silently mis-prices.

    With ``axis_filter`` the collective is priced as the filtered
    axis's HOP of a hierarchical factoring: ``n`` is that axis's size
    alone and the operand bytes are what cross it.  A psum over
    ``(data, model)`` filtered at ``data`` used to be priced with
    ``n = data*model`` — charging the model-hop bytes to the data
    (DCN) filter and over-counting the spec-aware sharded schedules,
    whose psum operands are model-axis SHARDS that only ever ride the
    data hop (the record's aval is the shard, so the operand bytes are
    already right; only the ``n`` factoring was not)."""
    axes = [a for a in record.axes if a in axis_sizes]
    if axis_filter is not None and axis_filter not in axes:
        return 0
    n = 1
    if axis_filter is not None:
        n = axis_sizes[axis_filter]
    else:
        for a in axes:
            n *= axis_sizes[a]
    if n <= 1:
        return 0
    in_bytes = sum(aval_nbytes(a) for a in record.inputs)
    out_bytes = sum(aval_nbytes(a) for a in record.outputs)
    if record.prim in ("psum", "pmax", "pmin"):
        return (2 * (n - 1) * in_bytes) // n
    if record.prim in ("psum_scatter", "reduce_scatter", "all_to_all"):
        return ((n - 1) * in_bytes) // n
    if record.prim == "all_gather":
        return ((n - 1) * out_bytes) // n
    if strict:
        raise ValueError(
            f"no ring-cost model for collective {record.prim!r} "
            f"(index {record.index}, axes {record.axes}): add one to "
            f"analysis.wire.ring_transmit_bytes before trusting a "
            f"byte-conservation gate over this schedule")
    return in_bytes  # conservative for anything unexpected


def schedule_transmit_bytes(schedule, axis_sizes=None,
                            axis_filter: Optional[str] = None,
                            strict: bool = False) -> int:
    """Total per-worker ring-model transmit bytes of a traced
    :class:`~.schedule.Schedule` (default ``axis_sizes``: the
    schedule's own axis_env).  ``strict=True`` raises on primitives
    the ring model doesn't cover (see :func:`ring_transmit_bytes`)."""
    sizes = dict(axis_sizes if axis_sizes is not None
                 else schedule.axis_env)
    return sum(ring_transmit_bytes(r, sizes, axis_filter, strict=strict)
               for r in schedule.records)


def schedule_prim_counts(schedule) -> Dict[str, int]:
    """Collective primitive -> count over a traced schedule (the
    one-line schedule summary the bench A/B tables print)."""
    counts: Dict[str, int] = {}
    for r in schedule.records:
        counts[r.prim] = counts.get(r.prim, 0) + 1
    return counts


#: Short alias (the name the bench tables/docs use).
prim_counts = schedule_prim_counts


def trace_transmit_bytes(fn, example_args: Sequence,
                         axis_env: Sequence[Tuple[str, int]],
                         axis_filter: Optional[str] = None,
                         entry: str = "wire") -> int:
    """Trace ``fn`` and return its per-worker ring-model transmit bytes
    in one call (the shape every bench's wire reading takes)."""
    from .schedule import trace_schedule
    sched = trace_schedule(fn, example_args, axis_env=axis_env,
                           entry=entry)
    return schedule_transmit_bytes(sched, dict(axis_env), axis_filter)
