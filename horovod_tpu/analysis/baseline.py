"""Findings baseline: a ratchet so CI fails only on *new* findings.

``tools/hvdlint_baseline.json`` records the accepted findings of a tree
(near-empty by policy — every real race gets fixed or suppressed inline
with a justification).  ``--baseline FILE`` subtracts baselined findings
from a run; ``--baseline FILE --update-baseline`` rewrites the file from
the current findings (the explicit ratchet step, reviewed in the diff).

Entries match on a **fingerprint** — ``v<analyzer> | code | path |
message with digit runs collapsed`` — so line-number drift from
unrelated edits does not invalidate the baseline, while a genuinely new
finding (different attribute, class, or rule) never matches.  Each
fingerprint carries a count: the baseline tolerates at most that many
occurrences.

Fingerprints and the file itself are keyed on
:data:`report.ANALYZER_VERSION`: a baseline recorded by an older rule
engine is **refused** (loud ``--update-baseline`` prompt), never
silently matched — an engine upgrade that reclassifies or renumbers
findings must re-ratchet explicitly, in a reviewed diff.
"""

from __future__ import annotations

import json
import os
import re
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

from .report import ANALYZER_VERSION, Finding

_DIGITS = re.compile(r"\d+")

_REPO_ROOT: Optional[str] = None


def _repo_root() -> str:
    """The enclosing git toplevel ('' when not in a repository)."""
    global _REPO_ROOT
    if _REPO_ROOT is None:
        import subprocess
        try:
            out = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                                 capture_output=True, text=True)
            _REPO_ROOT = (out.stdout.strip()
                          if out.returncode == 0 else "")
        except OSError:
            _REPO_ROOT = ""
    return _REPO_ROOT


def _canonical_path(path: str) -> str:
    """One spelling per file: repo-root-relative with forward slashes
    when inside a git checkout, absolute otherwise — so the same finding
    fingerprints identically whether hvdlint was invoked with absolute
    paths, from a subdirectory (``--changed`` relpaths), or from CI's
    repo-root-relative arguments."""
    p = os.path.abspath(path)
    root = _repo_root()
    if root and (p == root or p.startswith(root + os.sep)):
        p = os.path.relpath(p, root)
    return p.replace("\\", "/")


def fingerprint(finding: Finding) -> str:
    path = _canonical_path(finding.path)
    return (f"v{ANALYZER_VERSION}|{finding.code}|{path}|"
            f"{_DIGITS.sub('#', finding.message)}")


def load(path: str) -> Dict[str, int]:
    """fingerprint -> tolerated occurrence count.

    Raises ``ValueError`` when the baseline was recorded by a different
    analyzer generation: its entries describe what an *older* rule
    engine found, and matching them against this engine's output could
    silently swallow real new findings (or report baselined ones)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    recorded = int(data.get("analyzer_version", 0))
    if recorded != ANALYZER_VERSION:
        raise ValueError(
            f"baseline recorded by analyzer version {recorded}, this is "
            f"version {ANALYZER_VERSION} — re-ratchet with "
            f"--baseline {path} --update-baseline and review the diff")
    out: Dict[str, int] = {}
    for entry in data.get("findings", []):
        fp = entry["fingerprint"]
        out[fp] = out.get(fp, 0) + int(entry.get("count", 1))
    return out


def save(path: str, findings: Iterable[Finding]) -> int:
    """Write the baseline for ``findings``; returns the entry count."""
    counts = Counter()
    meta: Dict[str, Tuple[str, str]] = {}
    for f in findings:
        fp = fingerprint(f)
        counts[fp] += 1
        meta.setdefault(fp, (f.code, _canonical_path(f.path)))
    entries = [{"code": meta[fp][0], "path": meta[fp][1],
                "count": n, "fingerprint": fp}
               for fp, n in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "analyzer_version": ANALYZER_VERSION,
                   "findings": entries}, f, indent=2, sort_keys=False)
        f.write("\n")
    return len(entries)


def apply(findings: List[Finding], allowed: Dict[str, int]
          ) -> Tuple[List[Finding], int]:
    """(new findings, count suppressed by the baseline)."""
    remaining = dict(allowed)
    new: List[Finding] = []
    suppressed = 0
    for f in findings:
        fp = fingerprint(f)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            suppressed += 1
        else:
            new.append(f)
    return new, suppressed
