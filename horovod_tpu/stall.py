"""Stall inspector: detects collectives stuck in the queue.

Reference parity: ``horovod/common/stall_inspector.cc`` (SURVEY.md §5.2) —
the reference warns when some ranks submitted a tensor while others haven't
for ``HOROVOD_STALL_CHECK_TIME`` seconds, and aborts after
``HOROVOD_STALL_SHUTDOWN_TIME``.

On an SPMD substrate the analogous *semantic race* is a tensor that was
submitted but never dispatched (e.g. a process diverged and stopped feeding
the same program, or a multi-host peer stopped participating so the XLA
collective never completes).  We track enqueue→complete latency per tensor
name and surface the same warning/abort behavior.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict

from . import metrics as _metrics
from .exceptions import StallError

logger = logging.getLogger("horovod_tpu")

_m_warnings = _metrics.counter(
    "hvd_stall_warnings_total",
    "Stall-inspector warning batches issued")
_m_straggler = _metrics.gauge(
    "hvd_straggler_score",
    "Per-host straggler score: EWMA of observed collective-arrival "
    "lateness (seconds); feeds the elastic blacklist as a soft failure "
    "past HOROVOD_TAIL_BLACKLIST_SCORE", labels=("process",))
_m_lateness = _metrics.histogram(
    "hvd_tail_lateness_seconds",
    "Observed per-host DCN arrival lateness (every observation the "
    "straggler EWMA ingests, incl. 0.0 on-time rounds): the EWMA "
    "gauge alone cannot distinguish a chronic 100 ms host from a rare "
    "2 s one — the distribution can.  Fixed log2 edges merge "
    "bucket-wise in /metrics/job", labels=("process",), lo=-10, hi=7)

#: EWMA weight of one observed arrival lateness.  High enough that a
#: chronically slow host crosses a seconds-scale blacklist bar within a
#: handful of rounds, low enough that one hiccup decays away.
EWMA_ALPHA = 0.25


class StallInspector:
    def __init__(self, check_time: float = 60.0, shutdown_time: float = 0.0,
                 disabled: bool = False, use_native: bool = True,
                 blacklist_score: float = 0.0, on_straggler=None):
        self.check_time = check_time
        self.shutdown_time = shutdown_time
        self.disabled = disabled or check_time <= 0
        # straggler scoring (OptiReduce, ROADMAP item 2): per-host EWMA
        # of observed arrival lateness.  Two feeds converge here: the
        # eager DCN tail rounds (per cross-group injected/observed
        # lateness, including 0.0 for on-time rounds — the decay) and
        # the negotiation controller (a process first reported missing
        # and later arriving was late by the missing->arrival gap).
        # ``on_straggler(process, score)`` fires edge-triggered when a
        # score crosses ``blacklist_score`` (> 0), re-arming once the
        # score decays below half the bar — the hook the elastic plane
        # uses to blacklist a chronically slow host BEFORE it dies.
        self.blacklist_score = float(blacklist_score)
        self.on_straggler = on_straggler
        self._scores: Dict[int, float] = {}
        self._flagged: set = set()
        # (name, process) -> when the controller first reported the
        # process missing for that tensor (lateness = clear time - this)
        self._missing_since: Dict[tuple, float] = {}
        # guards _pending/_warned/_missing/warnings_issued: record_enqueue
        # runs on the submitting user thread while check() iterates the
        # same dicts on the engine thread — unguarded, a submission racing
        # a scan dies with "dictionary changed size during iteration"
        # (found by hvdlint's guarded-by pass, HVD110/HVD113 family)
        self._lock = threading.Lock()
        self._pending: Dict[str, float] = {}
        self._warned: Dict[str, float] = {}
        # tensor name -> processes that have not submitted it, reported by
        # the negotiation controller (reference: stall_inspector.cc's
        # missing-rank list from ComputeResponseList)
        self._missing: Dict[str, list] = {}
        self.warnings_issued = 0
        # Native bookkeeping (reference: stall_inspector.cc) when built.
        self._native = None
        if not self.disabled and use_native:
            try:
                from .native import loader
                core = loader.load()
                if core is not None:
                    self._native = core.StallTracker(
                        check_time=check_time, shutdown_time=shutdown_time)
            except Exception:  # noqa: BLE001 - Python fallback
                self._native = None

    def record_enqueue(self, name: str, t: float):
        if self.disabled:
            return
        if self._native is not None:
            self._native.record_enqueue(name, t)
        else:
            with self._lock:
                self._pending.setdefault(name, t)

    def record_missing(self, name: str, processes, now: float = None):
        """Record which processes have not announced ``name`` (from the
        cross-process controller's negotiation round).

        Arrival timestamps ride along: the first round that reports a
        process missing stamps ``_missing_since[(name, process)]``, and
        the round that no longer reports it (or ``record_complete``)
        turns the gap into an observed LATENESS fed to the straggler
        EWMA — absence alone says a host is behind, the timestamps say
        by how much."""
        if self.disabled:
            return
        now = time.monotonic() if now is None else now
        cleared = []
        with self._lock:
            procs = sorted(set(int(p) for p in processes))
            self._missing[name] = procs
            live = set(procs)
            for p in procs:
                self._missing_since.setdefault((name, p), now)
            for key in [k for k in self._missing_since
                        if k[0] == name and k[1] not in live]:
                cleared.append((key[1], now - self._missing_since.pop(key)))
        for p, lateness in cleared:
            self.note_lateness(p, lateness, now=now)

    def missing_processes(self, name: str):
        with self._lock:
            return list(self._missing.get(name, []))

    def missing_since(self, name: str, process: int):
        """When ``process`` was first reported missing for ``name``
        (None if it is not currently missing) — the arrival-timestamp
        bookkeeping behind lateness observation."""
        with self._lock:
            return self._missing_since.get((name, int(process)))

    def note_lateness(self, process: int, lateness_s: float,
                      now: float = None):
        """Feed one observed arrival lateness (seconds; 0.0 = on time)
        into ``process``'s straggler EWMA.  Fires ``on_straggler``
        edge-triggered past ``blacklist_score``."""
        if self.disabled:
            return
        p = int(process)
        fire = None
        with self._lock:
            score = self._scores.get(p, 0.0)
            score += EWMA_ALPHA * (max(float(lateness_s), 0.0) - score)
            self._scores[p] = score
            if self.blacklist_score > 0:
                if score >= self.blacklist_score and p not in self._flagged:
                    self._flagged.add(p)
                    fire = score
                elif (score < self.blacklist_score / 2.0
                      and p in self._flagged):
                    self._flagged.discard(p)   # re-arm after decay
        if _metrics.ACTIVE:
            _m_straggler.set(score, process=str(p))
            _m_lateness.observe(max(float(lateness_s), 0.0),
                                process=str(p))
        if fire is not None and self.on_straggler is not None:
            # outside the lock: the hook may RPC the elastic driver
            try:
                self.on_straggler(p, fire)
            except Exception:  # noqa: BLE001 - observability must not
                # fail the dispatch path
                logger.warning("straggler report hook failed",
                               exc_info=True)

    def straggler_scores(self) -> Dict[int, float]:
        """Per-process straggler score snapshot (exposed through
        ``engine.stats()['stall']``)."""
        with self._lock:
            return dict(self._scores)

    def record_complete(self, name: str, now: float = None):
        if self.disabled:
            return
        now = time.monotonic() if now is None else now
        cleared = []
        with self._lock:
            self._missing.pop(name, None)
            # a process still stamped missing when the tensor completes
            # arrived last: its lateness is the full missing->complete
            # gap (the arrival-timestamp satellite of the tail PR)
            for key in [k for k in self._missing_since if k[0] == name]:
                cleared.append((key[1], now - self._missing_since.pop(key)))
            # _warned is cleared on BOTH paths: the native tracker keeps
            # its own warned set, but _warn() mirrors warned names into
            # this dict (so warnings_issued bookkeeping is path-
            # independent) — a tensor that completes after warning must
            # reset either way, or a later genuine re-stall of the same
            # name would go unwarned.  _pending is popped in the SAME
            # critical section: split sections would let check() observe
            # the name still pending with its warned entry already gone
            # and re-warn a completing tensor
            self._warned.pop(name, None)
            if self._native is None:
                self._pending.pop(name, None)
        for p, lateness in cleared:
            self.note_lateness(p, lateness, now=now)
        if self._native is not None:
            self._native.record_complete(name)

    def check(self, now: float = None):
        """Scan pending tensors; warn on stalls, raise past the shutdown bar.

        Called once per engine cycle (reference: CheckForStalledTensors from
        ComputeResponseList).
        """
        if self.disabled:
            return
        now = time.monotonic() if now is None else now
        if self._native is not None:
            stalled, shutdown = self._native.check(now)
            if shutdown is not None:
                name, age = shutdown
                self._abort(name, age)
            self._warn(stalled, now)
            return
        # scan a snapshot: record_enqueue() on the submitting thread must
        # not resize the dict mid-iteration (the race the guarded-by
        # analyzer exists to catch)
        with self._lock:
            pending = list(self._pending.items())
            warned = set(self._warned)
        stalled = []
        for name, t0 in pending:
            age = now - t0
            if age > self.check_time and name not in warned:
                stalled.append((name, age))
            if self.shutdown_time > 0 and age > self.shutdown_time:
                self._abort(name, age)
        self._warn(stalled, now)

    def _abort(self, name: str, age: float):
        """Raise the shutdown-bar StallError, dumping the flight
        recorder first (the black-box read of what led to the stall)."""
        if _metrics.RECORDING:
            _metrics.event("stall.abort", tensor=name, age_s=round(age, 1),
                           missing=self.missing_processes(name))
            _metrics.flight_dump("StallError: stalled tensor")
        raise StallError(
            f"tensor {self._describe(name, age)} stalled past "
            f"HOROVOD_STALL_SHUTDOWN_TIME_SECONDS="
            f"{self.shutdown_time:.0f}; aborting")

    def _describe(self, name: str, age: float) -> str:
        missing = self.missing_processes(name)
        if missing:
            return f"{name} ({age:.0f}s; missing on processes {missing})"
        return f"{name} ({age:.0f}s)"

    def _warn(self, stalled, now: float = None):
        if not stalled:
            return
        now = time.monotonic() if now is None else now
        # mirror warned names on both paths so record_complete's reset
        # (and tests over the bookkeeping) see one source of truth
        with self._lock:
            for n, _ in stalled:
                self._warned.setdefault(n, now)
            self.warnings_issued += 1
        if _metrics.ACTIVE:
            _m_warnings.inc()
        if _metrics.RECORDING:
            _metrics.event("stall.warning",
                           tensors=[n for n, _ in stalled])
        names = ", ".join(self._describe(n, a) for n, a in stalled)
        logger.warning(
            "One or more tensors were submitted to be reduced/gathered "
            "but were not dispatched for over %.0f seconds: [%s]. "
            "Processes listed as missing have not announced the tensor in "
            "negotiation (reference: stall_inspector missing ranks).",
            self.check_time, names)
