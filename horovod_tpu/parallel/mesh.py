"""Multi-axis device mesh management.

The reference's only grouping concepts are MPI_COMM_WORLD plus process sets
(``horovod/common/process_set.cc``).  On TPU, parallelism is expressed as a
multi-dimensional ``jax.sharding.Mesh`` whose axes carry meaning:

  * ``dp`` — data parallel (gradient psum; the reference's core capability)
  * ``pp`` — pipeline parallel (stage-to-stage ppermute)
  * ``sp`` — sequence/context parallel (ring attention / Ulysses)
  * ``tp`` — tensor parallel (megatron-style column/row sharding)
  * ``ep`` — expert parallel (MoE all_to_all routing)

Axis order matters on hardware: the innermost axes get the
fastest-wraparound ICI links, so tp (latency-bound, every layer) sits last
and dp (bandwidth-bound, once per step, overlappable) first — the layout
the scaling playbook prescribes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np

AXIS_ORDER = ("dp", "pp", "sp", "tp")  # ep is aliased onto dp by default
# With a dedicated expert axis, ep sits between sp and tp: all_to_all token
# routing is bandwidth-bound but per-layer, so it deserves faster links than
# dp/pp, while tp (latency-bound matmul collectives) keeps the innermost ring.
AXIS_ORDER_EP = ("dp", "pp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: Optional[int] = None  # None → experts sharded over the dp axis

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * (self.ep or 1)

    def axis_sizes(self) -> Dict[str, int]:
        sizes = {"dp": self.dp, "pp": self.pp, "sp": self.sp, "tp": self.tp}
        if self.ep:
            sizes["ep"] = self.ep
        return sizes


class ParallelMesh:
    """A named multi-axis mesh plus convenience queries.

    ``ep`` (expert parallel) is by default an *alias* of the dp axis — the
    standard MoE layout where experts shard over data-parallel ranks and
    tokens move via all_to_all on that axis — so no devices are wasted on a
    separate axis unless requested.
    """

    def __init__(self, config: MeshConfig, devices: Optional[Sequence] = None):
        self.config = config
        # optional pytree of PartitionSpecs describing how PARAMS are
        # sharded over this mesh's axes: set it (directly or via
        # with_param_specs) before entering the mesh context and the
        # spec-aware gradient plane (optim.distributed
        # DistributedGradientTransform(param_specs=None)) reads it from
        # current_mesh() instead of requiring the tree at every call
        self.param_specs = None
        devices = list(devices if devices is not None else jax.devices())
        n = config.n_devices
        if len(devices) < n:
            raise ValueError(
                f"mesh needs {n} devices ({config}), only "
                f"{len(devices)} available")
        axes = AXIS_ORDER_EP if config.ep else AXIS_ORDER
        shape = tuple(config.axis_sizes()[a] for a in axes)
        arr = np.array(devices[:n]).reshape(shape)
        self.mesh = jax.sharding.Mesh(arr, axes)
        self.ep_axis = "ep" if config.ep else "dp"

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self.mesh.axis_names

    def axis_size(self, name: str) -> int:
        if name == "ep" and self.config.ep is None:
            return self.config.dp  # aliased onto dp
        return self.config.axis_sizes()[name]

    def with_param_specs(self, param_specs) -> "ParallelMesh":
        """Attach a param PartitionSpec pytree for the NEXT context
        entry (returns self, so ``with pmesh.with_param_specs(specs):``
        reads naturally).  The attachment is SCOPED: ``__exit__``
        clears it, so a later unrelated ``with pmesh:`` block cannot
        silently inherit stale specs.  Assign ``pmesh.param_specs``
        directly for a persistent attachment."""
        self.param_specs = param_specs
        self._specs_scoped = True
        return self

    def __enter__(self):
        self._ctx = self.mesh
        # enter the jax mesh FIRST: if it raises, the with-statement
        # never runs __exit__, and a pre-pushed entry would leak on
        # the context stack for the process lifetime
        out = self.mesh.__enter__()
        _ACTIVE_MESHES.append(self)
        return out

    def __exit__(self, *a):
        if _ACTIVE_MESHES and _ACTIVE_MESHES[-1] is self:
            _ACTIVE_MESHES.pop()
        if getattr(self, "_specs_scoped", False):
            self.param_specs = None
            self._specs_scoped = False
        return self.mesh.__exit__(*a)


#: innermost-first stack of ParallelMesh contexts currently entered
#: (trace-time Python state, like the overlap taps' _ACTIVE token)
_ACTIVE_MESHES: list = []


def current_mesh() -> Optional["ParallelMesh"]:
    """The innermost active ``ParallelMesh`` context (None outside any).
    The spec-aware gradient plane reads ``param_specs`` from here when a
    transform is built without an explicit tree."""
    return _ACTIVE_MESHES[-1] if _ACTIVE_MESHES else None


def make_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None,
              pp: int = 1, sp: int = 1, tp: int = 1,
              devices: Optional[Sequence] = None) -> ParallelMesh:
    """Build a ParallelMesh; ``dp`` defaults to whatever devices remain."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if dp is None:
        denom = pp * sp * tp
        if n % denom:
            raise ValueError(f"{n} devices not divisible by pp*sp*tp={denom}")
        dp = n // denom
    return ParallelMesh(MeshConfig(dp=dp, pp=pp, sp=sp, tp=tp),
                        devices=devices)


def factor_mesh(n: int, want_pp: bool = True) -> MeshConfig:
    """Factor ``n`` devices into a sensible (dp, pp, sp, tp) for dry runs.

    Greedy: grow tp, then sp, then pp, then dp — each axis gets a factor of
    2 while available, mirroring how real slices are carved.
    """
    sizes = {"dp": 1, "pp": 1, "sp": 1, "tp": 1}
    order = ["tp", "sp", "pp", "dp"] if want_pp else ["tp", "sp", "dp"]
    rem = n
    for axis in order:
        if rem % 2 == 0 and rem > 1:
            sizes[axis] *= 2
            rem //= 2
    # remaining factor goes to dp
    sizes["dp"] *= rem
    return MeshConfig(**sizes)
