"""Pipeline parallelism: GPipe-style microbatch streaming over the pp axis.

Beyond-reference capability (SURVEY.md §2.9: the reference has no PP).
TPU-native design: each pp-mesh shard holds one stage's parameters
(sharded ``P('pp')`` on the stacked stage dim); microbatches stream through
the stages with stage-to-stage ``lax.ppermute`` hops over ICI inside one
compiled program.  The schedule is the classic GPipe fill/steady/drain loop
written as ``lax.scan`` — n_micro + n_stages - 1 ticks, bubble fraction
(n_stages-1)/(n_micro+n_stages-1) — and the backward pipeline falls out of
autodiff (the transpose of ppermute runs the ring backwards).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = "pp"):
    """Run ``microbatches`` through a pipeline of identical-signature stages.

    Args:
      stage_fn: ``f(stage_params, x) -> y`` with ``y.shape == x.shape``
        (the transformer-block case; stages must be shape-preserving so the
        inter-stage wire format is fixed).
      stage_params: this shard's stage parameters (use spec ``P('pp')`` on
        the stacked leading dim outside, so each shard sees its own stage;
        pass the already-unstacked local pytree here).
      microbatches: ``[n_micro, mb, ...]`` input microbatches (replicated
        across pp shards).
      axis_name: the pipeline mesh axis.

    Returns ``[n_micro, mb, ...]`` outputs, replicated across pp shards.
    """
    n_stages = lax.axis_size(axis_name)
    if n_stages == 1:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(microbatches)

    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    total_ticks = n_micro + n_stages - 1
    mb_shape = microbatches.shape[1:]
    # send stage s → s+1 (no wraparound: last stage's send is discarded)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t (clamped during drain ticks);
        # later stages consume what arrived from the previous stage
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(
            microbatches, mb_idx, axis=0, keepdims=False)
        x = jnp.where(stage == 0, first_in, incoming)
        y = stage_fn(stage_params, x)
        # last stage retires microbatch t-(n_stages-1) (ignored while <0)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        live = t - (n_stages - 1) >= 0
        retired = jnp.where(
            jnp.logical_and(stage == n_stages - 1, live),
            y, lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False))
        outputs = lax.dynamic_update_index_in_dim(outputs, retired,
                                                  out_idx, 0)
        incoming = lax.ppermute(y, axis_name, perm)
        return (incoming, outputs), None

    from .vma import as_varying
    # derive carries from the inputs (×0) so they inherit the inputs'
    # varying axes, then add the pipeline axis (check_vma=True contract)
    exemplar = jax.tree_util.tree_leaves(stage_params)[0]
    incoming0 = as_varying(microbatches[0] * 0, axis_name, like=exemplar)
    outputs0 = as_varying(microbatches * 0, axis_name, like=exemplar)
    (_, outputs), _ = lax.scan(tick, (incoming0, outputs0),
                               jnp.arange(total_ticks))
    # outputs live on the last stage; replicate so every pp shard returns
    # the same value (mask-and-psum broadcast over the pp ring)
    mask = (stage == n_stages - 1).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis_name)
