"""Pipeline parallelism: GPipe-style microbatch streaming over the pp axis.

Beyond-reference capability (SURVEY.md §2.9: the reference has no PP).
TPU-native design: each pp-mesh shard holds one stage's parameters
(sharded ``P('pp')`` on the stacked stage dim); microbatches stream through
the stages with stage-to-stage ``lax.ppermute`` hops over ICI inside one
compiled program.  The schedule is the classic GPipe fill/steady/drain loop
written as ``lax.scan`` — n_micro + n_stages - 1 ticks, bubble fraction
(n_stages-1)/(n_micro+n_stages-1) — and the backward pipeline falls out of
autodiff (the transpose of ppermute runs the ring backwards).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = "pp", with_aux: bool = False):
    """Run ``microbatches`` through a pipeline of identical-signature stages.

    Args:
      stage_fn: ``f(stage_params, x) -> y`` with ``y.shape == x.shape``
        (the transformer-block case; stages must be shape-preserving so the
        inter-stage wire format is fixed).  With ``with_aux=True``:
        ``f(stage_params, x) -> (y, aux_scalar)`` — the per-stage scalar
        (e.g. a MoE load-balance loss) is accumulated over *live* ticks
        only and summed across stages, so it never needs to ride the
        inter-stage wire.
      stage_params: this shard's stage parameters (use spec ``P('pp')`` on
        the stacked leading dim outside, so each shard sees its own stage;
        pass the already-unstacked local pytree here).
      microbatches: ``[n_micro, mb, ...]`` input microbatches (replicated
        across pp shards).
      axis_name: the pipeline mesh axis.

    Returns ``[n_micro, mb, ...]`` outputs replicated across pp shards —
    with ``with_aux``, ``(outputs, aux_total)``.
    """
    n_stages = lax.axis_size(axis_name)

    def run(stage_params, x):
        out = stage_fn(stage_params, x)
        return out if with_aux else (out, jnp.float32(0.0))

    n_micro = microbatches.shape[0]
    if n_stages == 1:
        out, auxes = jax.vmap(
            lambda x: run(stage_params, x))(microbatches)
        # MEAN over microbatches: the aux (load-balance fractions) is
        # scale-free, so each microbatch contributes ~the full-batch
        # value — summing would scale the coefficient by n_micro
        return (out, auxes.sum() / n_micro) if with_aux else out

    stage = lax.axis_index(axis_name)
    total_ticks = n_micro + n_stages - 1
    # send stage s → s+1 (no wraparound: last stage's send is discarded)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        incoming, outputs, aux_total = carry
        # stage 0 injects microbatch t (clamped during drain ticks);
        # later stages consume what arrived from the previous stage
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(
            microbatches, mb_idx, axis=0, keepdims=False)
        x = jnp.where(stage == 0, first_in, incoming)
        y, aux = run(stage_params, x)
        # stage s processes microbatch t-s at tick t; fill/drain ticks run
        # on clamped garbage and must not contribute aux (or its grads)
        live_here = jnp.logical_and(t >= stage, t - stage < n_micro)
        aux_total = aux_total + jnp.where(live_here,
                                          aux.astype(jnp.float32), 0.0)
        # last stage retires microbatch t-(n_stages-1) (ignored while <0)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        live = t - (n_stages - 1) >= 0
        retired = jnp.where(
            jnp.logical_and(stage == n_stages - 1, live),
            y, lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False))
        outputs = lax.dynamic_update_index_in_dim(outputs, retired,
                                                  out_idx, 0)
        incoming = lax.ppermute(y, axis_name, perm)
        return (incoming, outputs, aux_total), None

    from .vma import as_varying
    # derive carries from the inputs (×0) so they inherit the inputs'
    # varying axes, then add the pipeline axis (check_vma=True contract)
    exemplar = jax.tree_util.tree_leaves(stage_params)[0]
    incoming0 = as_varying(microbatches[0] * 0, axis_name, like=exemplar)
    outputs0 = as_varying(microbatches * 0, axis_name, like=exemplar)
    aux0 = (incoming0.astype(jnp.float32) * 0).sum()
    (_, outputs, aux_total), _ = lax.scan(
        tick, (incoming0, outputs0, aux0), jnp.arange(total_ticks))
    # outputs live on the last stage; replicate so every pp shard returns
    # the same value (mask-and-psum broadcast over the pp ring); each
    # stage's aux covers its own layers, so the total is the plain psum
    mask = (stage == n_stages - 1).astype(outputs.dtype)
    outputs = lax.psum(outputs * mask, axis_name)
    if with_aux:
        # psum over stages (each stage's own layers), MEAN over
        # microbatches (scale-free aux — see the n_stages==1 path)
        return outputs, lax.psum(aux_total, axis_name) / n_micro
    return outputs
