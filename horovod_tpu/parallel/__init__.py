"""Multi-axis parallelism over TPU device meshes.

Beyond-reference capability (SURVEY.md §2.9): the reference is DP-only; this
package adds the parallelism families a modern TPU framework needs — tensor
(tp), sequence/context (sp: ring attention + Ulysses), pipeline (pp), and
expert (ep) — all expressed as mesh axes with XLA collectives over ICI.
"""

from .mesh import MeshConfig, ParallelMesh, make_mesh  # noqa: F401
from .vma import as_varying  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
