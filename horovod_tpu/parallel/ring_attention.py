"""Ring attention: exact blockwise attention over a sequence-parallel axis.

Beyond-reference capability (SURVEY.md §5.7 notes the reference has no
long-context machinery; its only related primitive is alltoall).  This is
the TPU-native form: the sequence is sharded over the ``sp`` mesh axis;
each step of a ring schedule computes one query-block × key/value-block
tile with an online-softmax accumulator while the K/V blocks rotate around
the ICI ring via ``lax.ppermute`` — compute overlaps the neighbor exchange,
total memory stays O(T/sp) per chip, and the result is *exact* attention
(not an approximation).  Gradients flow through the loop by autodiff
(the transpose of ppermute is the reverse rotation), with
``jax.checkpoint`` on the per-step kernel to keep backward memory flat.

Use inside ``shard_map`` with the sequence axis in scope; plain jnp
fallback when the axis size is 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, q_blk, kv_blk, t_local, causal, scale):
    """One tile: scores q·k with causal masking by global block position,
    folded into the (m, l, o) online-softmax accumulator.  fp32 accumulate
    regardless of input dtype (MXU-native bf16 inputs are fine).

    GQA: when q has H heads and k/v have Hkv < H heads (H % Hkv == 0),
    queries are grouped so each kv head serves H/Hkv query heads — kv
    blocks circulate the ring at 1/(H/Hkv) the bytes of the repeated form.
    Query head h maps to kv head h // (H/Hkv), matching
    ``jnp.repeat(k, H//Hkv, axis=2)`` semantics.
    """
    # q: [B, Tq, H, D], k/v: [B, Tk, Hkv, D]
    B, Tq, H, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    if H == Hkv:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
    else:
        g = H // Hkv
        qg = q.reshape(B, Tq, Hkv, g, D)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32) * scale
        s = s.reshape(B, H, Tq, Tk)
    if causal:
        tq = jnp.arange(t_local)[:, None] + q_blk * t_local
        tk = jnp.arange(t_local)[None, :] + kv_blk * t_local
        s = jnp.where((tk <= tq)[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))            # [B, H, Tq]
    p = jnp.exp(s - m_new[..., None])                  # [B, H, Tq, Tk]
    corr = jnp.exp(m - m_new)                          # [B, H, Tq]
    l_new = l * corr + p.sum(axis=-1)
    vf = v.astype(jnp.float32)
    if H == Hkv:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf,
                        preferred_element_type=jnp.float32)
    else:
        g = H // Hkv
        pg = p.reshape(B, Hkv, g, Tq, Tk)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", pg, vf,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(B, Tq, H, D)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: Optional[str] = None,
                   causal: bool = True, sm_scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis_name``.

    Args:
      q, k, v: ``[batch, t_local, heads, head_dim]`` — the local sequence
        shard.  k/v may carry fewer heads than q (GQA): with
        ``Hkv = k.shape[2]`` dividing ``H = q.shape[2]``, the grouped path
        circulates only the Hkv kv heads around the ring.
      axis_name: the sp mesh axis; ``None`` (or size 1) → single-shard path.
      causal: apply a causal mask using *global* token positions.
      sm_scale: softmax scale; default ``1/sqrt(head_dim)``.

    Returns ``[batch, t_local, heads, head_dim]`` in q's dtype.
    """
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name) if axis_name is not None else 1
    B, Tl, H, D = q.shape

    if n == 1:
        m = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        l = jnp.zeros((B, H, Tl), jnp.float32)
        o = jnp.zeros((B, Tl, H, D), jnp.float32)
        m, l, o = _block_attend(q, k, v, m, l, o, 0, 0, Tl, causal, scale)
        return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)

    my_blk = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    attend = jax.checkpoint(
        functools.partial(_block_attend, t_local=Tl, causal=causal,
                          scale=scale))

    def step(carry, s):
        m, l, o, ck, cv = carry
        kv_blk = (my_blk - s) % n  # whose block we hold after s rotations
        m, l, o = attend(q, ck, cv, m, l, o, my_blk, kv_blk)
        # rotate k/v around the ICI ring (skipped result on last step is
        # dead code XLA drops)
        ck = lax.ppermute(ck, axis_name, perm)
        cv = lax.ppermute(cv, axis_name, perm)
        return (m, l, o, ck, cv), None

    from .vma import as_varying
    # derive accumulators from q (×0) so they inherit q's varying axes
    # (dp/tp/…), then add the ring axis — scan carries must match the body
    # output's VMA exactly under check_vma=True
    zero_bht = (q[:, :, :, 0].transpose(0, 2, 1) * 0).astype(jnp.float32)
    m0 = zero_bht + NEG_INF
    l0 = zero_bht
    o0 = (q * 0).astype(jnp.float32)
    m0, l0, o0 = as_varying((m0, l0, o0), axis_name, like=k)
    (m, l, o, _, _), _ = lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(n))
    # causal guarantees every query attends at least to itself → l > 0
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)
