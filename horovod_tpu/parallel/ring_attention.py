"""Ring attention: exact blockwise attention over a sequence-parallel axis.

Beyond-reference capability (SURVEY.md §5.7 notes the reference has no
long-context machinery; its only related primitive is alltoall).  This is
the TPU-native form: the sequence is sharded over the ``sp`` mesh axis;
each step of a ring schedule computes one query-block × key/value-block
tile with an online-softmax accumulator while the K/V blocks rotate around
the ICI ring via ``lax.ppermute`` — compute overlaps the neighbor exchange,
total memory stays O(T/sp) per chip, and the result is *exact* attention
(not an approximation).  Gradients flow through the loop by autodiff
(the transpose of ppermute is the reverse rotation), with
``jax.checkpoint`` on the per-step kernel to keep backward memory flat.

Use inside ``shard_map`` with the sequence axis in scope; plain jnp
fallback when the axis size is 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, q_start, k_start, causal, scale):
    """One tile: scores q·k with causal masking by global token position,
    folded into the (m, l, o) online-softmax accumulator.  fp32 accumulate
    regardless of input dtype (MXU-native bf16 inputs are fine).

    ``q_start``/``k_start`` are the global positions of the first query /
    key row in this tile (q and k may be different block sizes).

    GQA: when q has H heads and k/v have Hkv < H heads (H % Hkv == 0),
    queries are grouped so each kv head serves H/Hkv query heads — kv
    blocks circulate the ring at 1/(H/Hkv) the bytes of the repeated form.
    Query head h maps to kv head h // (H/Hkv), matching
    ``jnp.repeat(k, H//Hkv, axis=2)`` semantics.
    """
    # q: [B, Tq, H, D], k/v: [B, Tk, Hkv, D]
    B, Tq, H, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    if H == Hkv:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
    else:
        g = H // Hkv
        qg = q.reshape(B, Tq, Hkv, g, D)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32) * scale
        s = s.reshape(B, H, Tq, Tk)
    if causal:
        tq = jnp.arange(Tq)[:, None] + q_start
        tk = jnp.arange(Tk)[None, :] + k_start
        s = jnp.where((tk <= tq)[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))            # [B, H, Tq]
    p = jnp.exp(s - m_new[..., None])                  # [B, H, Tq, Tk]
    corr = jnp.exp(m - m_new)                          # [B, H, Tq]
    l_new = l * corr + p.sum(axis=-1)
    vf = v.astype(jnp.float32)
    if H == Hkv:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf,
                        preferred_element_type=jnp.float32)
    else:
        g = H // Hkv
        pg = p.reshape(B, Hkv, g, Tq, Tk)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", pg, vf,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(B, Tq, H, D)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def blockwise_attend(q, k, v, m, l, o, q_start, k_start, causal: bool,
                     scale: float, block_size: int = 512):
    """Fold one q-shard × kv-shard tile into the ``(m, l, o)`` accumulator
    with O(Tq·block) live memory: an online-softmax sub-scan over
    key/value blocks, each block ``jax.checkpoint``-ed.  ``q_start`` /
    ``k_start`` may be traced (ring steps pass dynamic block offsets).
    """
    B, Tq, H, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    blk = min(block_size, Tk)
    if Tk % blk:
        # largest divisor of Tk that fits the requested block, so the
        # O(T·blk) bound survives odd sequence lengths; truly degenerate
        # sizes (no divisor ≥ 64) collapse to one checkpointed tile
        blk = next((b for b in range(blk, 63, -1) if Tk % b == 0), Tk)
    nblk = Tk // blk
    attend = jax.checkpoint(
        functools.partial(_block_attend, causal=causal, scale=scale))
    # kv laid out block-major as scan xs: [nblk, B, blk, Hkv, D]
    # (nblk == 1 degenerates to a length-1 scan over the single tile)
    kb = k.reshape(B, nblk, blk, Hkv, D).swapaxes(0, 1)
    vb = v.reshape(B, nblk, blk, Hkv, D).swapaxes(0, 1)

    def step(carry, xs):
        m, l, o = carry
        kj, vj, off = xs
        m, l, o = attend(q, kj, vj, m, l, o, q_start, k_start + off)
        return (m, l, o), None

    offs = jnp.arange(nblk, dtype=jnp.int32) * blk
    (m, l, o), _ = lax.scan(step, (m, l, o), (kb, vb, offs))
    return m, l, o


def local_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_size: int = 512):
    """Exact single-shard attention with O(T·block) live memory.

    On TPU the fused Pallas kernel path
    (:mod:`horovod_tpu.ops.flash_attention`) is preferred when the shapes
    fit; otherwise :func:`blockwise_attend` (the flash-attention
    recurrence expressed in XLA) is the portable fallback and the
    CPU-mesh test path.

    q: ``[B, T, H, D]``; k/v: ``[B, Tk, Hkv, D]`` with ``Hkv | H`` (GQA).
    """
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5

    from ..ops import flash_attention as _fa
    if _fa.supported(q, k, v, causal):
        return _fa.flash_attention(q, k, v, causal=causal, sm_scale=scale)

    # derive accumulators from the operands (×0) so they inherit their
    # varying mesh axes (dp/tp/…) — scan carries must match the body
    # output's VMA exactly under shard_map check_vma=True
    opzero = ((q.astype(jnp.float32) * 0).sum()
              + (k.astype(jnp.float32) * 0).sum()
              + (v.astype(jnp.float32) * 0).sum())
    zero_bht = (q[:, :, :, 0].transpose(0, 2, 1) * 0
                ).astype(jnp.float32) + opzero
    m0 = zero_bht + NEG_INF
    l0 = zero_bht
    o0 = (q * 0).astype(jnp.float32) + opzero
    m, l, o = blockwise_attend(q, k, v, m0, l0, o0, 0, 0, causal, scale,
                               block_size)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, axis_name: Optional[str] = None,
                   causal: bool = True, sm_scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis_name``.

    Args:
      q, k, v: ``[batch, t_local, heads, head_dim]`` — the local sequence
        shard.  k/v may carry fewer heads than q (GQA): with
        ``Hkv = k.shape[2]`` dividing ``H = q.shape[2]``, the grouped path
        circulates only the Hkv kv heads around the ring.
      axis_name: the sp mesh axis; ``None`` (or size 1) → single-shard path.
      causal: apply a causal mask using *global* token positions.
      sm_scale: softmax scale; default ``1/sqrt(head_dim)``.

    Returns ``[batch, t_local, heads, head_dim]`` in q's dtype.
    """
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name) if axis_name is not None else 1
    B, Tl, H, D = q.shape

    if n == 1:
        return local_attention(q, k, v, causal=causal, sm_scale=scale)

    my_blk = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    from ..ops import flash_attention as _fa
    use_kernel = _fa.supported(q, k, v, causal)

    def _merge_tile(mlo, out_t, lse_t):
        """Fold a kernel tile (normalized out + logsumexp) into the
        accumulator: the tile contributes exp(lse) absolute weight."""
        m, l, o = mlo
        m_new = jnp.maximum(m, lse_t)
        corr = jnp.exp(m - m_new)
        w_t = jnp.exp(lse_t - m_new)
        l_new = l * corr + w_t
        o_new = (o * corr.transpose(0, 2, 1)[..., None]
                 + out_t.astype(jnp.float32)
                 * w_t.transpose(0, 2, 1)[..., None])
        return m_new, l_new, o_new

    def _kernel_tile(mlo, ck, cv, kv_blk):
        """Per-ring-step tile through the fused Pallas kernel.  Causality
        at block granularity: past blocks attend fully, the diagonal block
        masks within the tile, future blocks are skipped — decided per
        device at runtime (kv_blk is the traced rotation index)."""

        def tile(tile_causal):
            def f(args):
                mlo, ck, cv = args
                out_t, lse_t = _fa.flash_attention_lse(
                    q, ck, cv, causal=tile_causal, sm_scale=scale)
                return _merge_tile(mlo, out_t, lse_t)
            return f

        def skip(args):
            return args[0]

        if not causal:
            return tile(False)((mlo, ck, cv))
        branch = jnp.where(kv_blk < my_blk, 0,
                           jnp.where(kv_blk == my_blk, 1, 2))
        return lax.switch(branch, [tile(False), tile(True), skip],
                          (mlo, ck, cv))

    def step(carry, s):
        m, l, o, ck, cv = carry
        kv_blk = (my_blk - s) % n  # whose block we hold after s rotations
        if use_kernel:
            m, l, o = _kernel_tile((m, l, o), ck, cv, kv_blk)
        else:
            # blockwise sub-scan: the per-step tile stays O(Tl·blk), never
            # materializing the [B,H,Tl,Tl] score matrix (VERDICT r2 #7)
            m, l, o = blockwise_attend(q, ck, cv, m, l, o, my_blk * Tl,
                                       kv_blk * Tl, causal, scale)
        # rotate k/v around the ICI ring (skipped result on last step is
        # dead code XLA drops)
        ck = lax.ppermute(ck, axis_name, perm)
        cv = lax.ppermute(cv, axis_name, perm)
        return (m, l, o, ck, cv), None

    from .vma import as_varying
    # derive accumulators from q (×0) so they inherit q's varying axes
    # (dp/tp/…), then add the ring axis — scan carries must match the body
    # output's VMA exactly under check_vma=True
    zero_bht = (q[:, :, :, 0].transpose(0, 2, 1) * 0).astype(jnp.float32)
    m0 = zero_bht + NEG_INF
    l0 = zero_bht
    o0 = (q * 0).astype(jnp.float32)
    m0, l0, o0 = as_varying((m0, l0, o0), axis_name, like=k)
    (m, l, o, _, _), _ = lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(n))
    # causal guarantees every query attends at least to itself → l > 0
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)
