"""Varying-manual-axes (VMA) helpers.

The training step runs its shard_map with ``check_vma=True`` so that JAX
tracks replication and emits *correct* psum transposes in the backward
pass (with the check off, gradients through forward psums come out
multiplied by the axis size — a silent ×tp/×pp error this framework hit
and now regression-tests).  The cost of the check is that loop carries
initialized from constants are "invariant" while the loop body makes them
"varying" over a mesh axis; these helpers cast explicitly.
"""

from __future__ import annotations

import jax


def as_varying(tree, axis_name, like=None):
    """Cast every leaf to varying over ``axis_name``.

    ``like`` is an exemplar value that WOULD be varying over the axis when
    VMA tracking is on (e.g. a sharded input): if its vma is empty, the
    surrounding shard_map runs with ``check_vma=False`` and casting would
    poison the (untracked) types — no-op instead.
    """
    if axis_name is None:
        return tree
    if like is not None:
        try:
            if axis_name not in jax.typeof(like).vma:
                return tree  # VMA tracking off in this context
        except AttributeError:  # pragma: no cover - aval without .vma
            return tree
    pcast = getattr(jax.lax, "pcast", None)

    def cast(x):
        try:
            if axis_name in jax.typeof(x).vma:
                return x  # already varying over this axis
        except AttributeError:
            pass
        if pcast is None:  # pragma: no cover - API fallback
            return jax.lax.pvary(x, axis_name)
        try:
            return pcast(x, axis_name, to="varying")
        except ValueError:
            return x

    return jax.tree_util.tree_map(cast, tree)
