"""Ulysses-style sequence parallelism: all_to_all head scatter.

Beyond-reference capability (SURVEY.md §5.7): the alternative long-context
scheme — instead of rotating K/V blocks (ring attention), one all_to_all
re-shards the activations from sequence-sharded to head-sharded, each chip
computes *full-sequence* attention for its subset of heads, and a second
all_to_all restores sequence sharding.  Two collectives per attention call
vs. ring's n-step rotation: cheaper when heads ≥ sp and the sequence fits
per-chip once gathered per-head; ring wins at extreme lengths.  The
reference's ``hvd.alltoall`` is exactly the primitive this builds on.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def seq_to_heads(x, axis_name: str):
    """[B, T/sp, H, D] seq-sharded → [B, T, H/sp, D] head-sharded.

    One tiled all_to_all: head-chunk j goes to chip j; the received
    sequence blocks concatenate in source order along the time dim.
    """
    n = lax.axis_size(axis_name)
    H = x.shape[2]
    if H % n:
        raise ValueError(f"heads {H} not divisible by sp={n}")
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name: str):
    """Inverse of seq_to_heads: [B, T, H/sp, D] → [B, T/sp, H, D]."""
    n = lax.axis_size(axis_name)
    T = x.shape[1]
    if T % n:
        raise ValueError(f"sequence {T} not divisible by sp={n}")
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: Optional[str] = None,
                      attn_fn: Optional[Callable] = None,
                      causal: bool = True,
                      sm_scale: Optional[float] = None):
    """Attention over a seq-sharded input via head scatter.

    q/k/v: ``[B, T/sp, H, D]`` sequence-sharded.  ``attn_fn(q, k, v)`` runs
    full attention on head-sharded tensors; defaults to the single-shard
    path of :func:`ring_attention` (exact softmax attention).

    GQA (``Hkv = k.shape[2] < H``): when sp divides Hkv the kv tensors
    scatter as-is (each chip holds Hkv/sp kv heads serving its H/sp query
    heads — the grouped head layout keeps every query's kv head local);
    otherwise kv heads are repeated to ``lcm(Hkv, sp)``, the minimum that
    scatters evenly, before the all_to_all.
    """
    from .ring_attention import ring_attention
    if attn_fn is None:
        def attn_fn(q, k, v):
            return ring_attention(q, k, v, axis_name=None, causal=causal,
                                  sm_scale=sm_scale)
    if axis_name is None or lax.axis_size(axis_name) == 1:
        return attn_fn(q, k, v)
    n = lax.axis_size(axis_name)
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H and Hkv % n:
        # lcm(Hkv, n) divides H whenever Hkv | H and n | H, so the
        # partially-repeated layout still scatters evenly and the grouped
        # q-head → kv-head mapping stays chip-local after the all_to_all.
        rep = (n * Hkv // math.gcd(Hkv, n)) // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh = seq_to_heads(q, axis_name)
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    oh = attn_fn(qh, kh, vh)
    return heads_to_seq(oh, axis_name)
