"""Data loader base classes.

Reference parity: ``horovod/data/data_loader_base.py`` (SURVEY.md §2.2) —
``BaseDataLoader`` (the iteration contract used by the elastic sampler
examples) and ``AsyncDataLoaderMixin`` (a background thread prefetches
batches through a bounded queue so host-side data prep overlaps device
compute).

TPU addition: :class:`ShardedLoader` composes the base contract with the
worker mesh — each batch is ``device_put`` against a batch-sharded
``NamedSharding``, so host→HBM transfer of the next batch overlaps the
current step (the reference leaves device placement to torch samplers).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional

_STOP = object()


class BaseDataLoader:
    """Iteration contract (reference: BaseDataLoader).

    Subclasses implement :meth:`__len__` and :meth:`_iterate`; users
    iterate the loader itself.  ``batch_size`` and epoch restarts are the
    subclass's business — this base only fixes the surface the rest of
    the framework (elastic sampler, examples) relies on.
    """

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Background-thread prefetch (reference: AsyncDataLoaderMixin).

    Mix in BEFORE the loader class::

        class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader): ...

    ``async_loader_queue_size`` bounds prefetch depth (0 = synchronous
    passthrough).  ``close()`` joins the worker thread; iteration
    re-raises any producer exception at the consumption point.
    """

    def __init__(self, *args, async_loader_queue_size: int = 4, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        super().__init__(*args, **kwargs)
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_requested: Optional[threading.Event] = None

    def _producer(self, q: queue.Queue, stop: threading.Event):
        def bounded_put(item) -> bool:
            # stays responsive to close(): a consumer that abandons
            # iteration must not strand this thread on a full queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for batch in super()._iterate():
                if not bounded_put(batch):
                    return
        except Exception as e:  # noqa: BLE001 - re-raised on the consumer
            bounded_put(e)
        finally:
            # the consumer waits for _STOP on normal completion, so it
            # must be delivered (the queue may be full right now); only
            # close() skips the wait, and it sets `stop`
            bounded_put(_STOP)

    def _iterate(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            yield from super()._iterate()
            return
        self.close()  # reclaim a producer from an abandoned iteration
        q = queue.Queue(self.async_loader_queue_size)
        stop = threading.Event()
        self._queue, self._stop_requested = q, stop
        self._thread = threading.Thread(
            target=self._producer, args=(q, stop),
            name="hvd-data-loader", daemon=True)
        self._thread.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            self.close()

    def close(self):
        """Stop and join the prefetch thread (reference: shutdown_async)."""
        t, self._thread = self._thread, None
        stop, self._stop_requested = self._stop_requested, None
        if t is not None and t.is_alive():
            if stop is not None:
                stop.set()
            try:  # unblock a producer waiting on a full queue
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5)


class ShardedLoader(BaseDataLoader):
    """Shard a numpy dataset over the worker mesh, one batch at a time.

    TPU-native composition of the base contract with ``jax.sharding``:
    every yielded batch is already ``device_put`` with the batch dim
    sharded over the worker axis (ready for a shard_map train step).

    Args:
      arrays: tuple of same-length numpy arrays (e.g. (x, y)).
      global_batch_size: rows per step across ALL workers; must divide
        by the worker count.
      process_set: placement target; defaults to the global set.
      drop_last: drop the trailing partial batch (default True — XLA
        wants static shapes).
    """

    def __init__(self, arrays, global_batch_size: int, process_set=None,
                 drop_last: bool = True):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import runtime
        self._arrays = tuple(arrays)
        if not self._arrays:
            raise ValueError("need at least one array")
        n = len(self._arrays[0])
        if any(len(a) != n for a in self._arrays):
            raise ValueError("arrays must share their leading dimension")
        ps = process_set or runtime._get_global_process_set()
        if global_batch_size % ps.size():
            raise ValueError(
                f"global_batch_size {global_batch_size} not divisible by "
                f"{ps.size()} workers")
        if not drop_last and (n % global_batch_size) % ps.size():
            raise ValueError(
                f"drop_last=False needs the trailing batch "
                f"({n % global_batch_size} rows) divisible by "
                f"{ps.size()} workers for the batch sharding")
        self._bs = global_batch_size
        self._n = n
        self._drop_last = drop_last
        self._sharding = NamedSharding(ps.mesh, P(ps.axis))
        self._jax = jax

    def __len__(self) -> int:
        full, rem = divmod(self._n, self._bs)
        return full if (self._drop_last or rem == 0) else full + 1

    def _iterate(self):
        import jax.numpy as jnp
        for i in range(len(self)):
            lo = i * self._bs
            yield tuple(
                self._jax.device_put(jnp.asarray(a[lo:lo + self._bs]),
                                     self._sharding)
                for a in self._arrays)
