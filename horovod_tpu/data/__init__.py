"""Data-loading utilities (reference: ``horovod/data/``)."""

from .data_loader import AsyncDataLoaderMixin, BaseDataLoader, ShardedLoader  # noqa: F401,E501

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "ShardedLoader"]
