"""Data-loading utilities (reference: ``horovod/data/`` + the Spark
store's parquet materialization / petastorm read-back)."""

from .data_loader import AsyncDataLoaderMixin, BaseDataLoader, ShardedLoader  # noqa: F401,E501
from .parquet import ParquetDataset, ParquetLoader, write_parquet  # noqa: F401,E501

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "ShardedLoader",
           "ParquetDataset", "ParquetLoader", "write_parquet"]
