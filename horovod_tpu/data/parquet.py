"""Out-of-core parquet datasets: the estimators' on-disk data plane.

Reference parity: the reference's Spark estimators never ship training
data through the task payload — the Store materializes the DataFrame as
parquet and every worker reads back only its shard through Petastorm
(``horovod/spark/common/store.py`` + petastorm readers; SURVEY.md §2.2
Spark row).  This module is that data flow rebuilt for the TPU stack:

* :func:`write_parquet` materializes named numpy columns with a chosen
  row-group size (the out-of-core granule);
* :class:`ParquetDataset` is a cheap, picklable handle (path + column
  selection) workers open themselves — the launcher payload carries the
  path, never the data;
* :meth:`ParquetDataset.read_shard` streams row groups and keeps only
  this worker's strided rows (``global_row % nproc == rank``), so the
  result is EXACTLY the ``X[rank::nproc]`` shard of the in-memory path
  — estimator loss histories from disk and from memory are identical —
  while peak memory is one row group plus the worker's own shard;
* :meth:`ParquetDataset.iter_batches` goes further: row-group-sharded
  windowed-shuffle streaming for datasets whose SHARD exceeds memory
  (peak = one row group + shuffle buffer + one batch).
  :class:`ParquetLoader` wraps it in the :class:`BaseDataLoader`
  contract (composable with :class:`AsyncDataLoaderMixin`).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .data_loader import BaseDataLoader


def write_parquet(path: str, columns: Dict[str, np.ndarray],
                  rows_per_group: int = 4096) -> None:
    """Materialize named numpy columns as one parquet file.

    ``rows_per_group`` sets the row-group size — the unit of streaming
    I/O and of :meth:`ParquetDataset.iter_batches` sharding; pick it so
    one group fits comfortably in memory (reference: the Spark store's
    parquet materialization step).
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    if not columns:
        raise ValueError("need at least one column")
    n = len(next(iter(columns.values())))
    if any(len(v) != n for v in columns.values()):
        raise ValueError("columns must share their leading dimension")
    table = pa.table({k: pa.array(np.asarray(v)) for k, v in columns.items()})
    pq.write_table(table, path, row_group_size=rows_per_group)


class ParquetDataset:
    """Handle to a parquet file or a directory of ``*.parquet`` shards.

    Picklable by (path, columns) — a worker that receives this handle
    opens the files itself and reads only its shard; the handle is what
    rides the launcher's cloudpickle payload.

    Args:
      path: a ``.parquet`` file or a directory of them (sorted by name,
        concatenated in order — the multi-writer layout).
      features: feature column names, stacked in order into the 2-D
        ``X`` matrix by :meth:`read_xy`.  Default: every column except
        ``label``.
      label: label column name for :meth:`read_xy` (default ``"y"``).
    """

    def __init__(self, path: str, features: Optional[Sequence[str]] = None,
                 label: str = "y"):
        self.path = path
        self.label = label
        self._features = list(features) if features is not None else None
        self._files: Optional[List[str]] = None
        self._meta = None

    def __reduce__(self):
        return (ParquetDataset, (self.path, self._features, self.label))

    # -- metadata -----------------------------------------------------------

    def _file_list(self) -> List[str]:
        if self._files is None:
            if os.path.isdir(self.path):
                self._files = sorted(
                    os.path.join(self.path, f)
                    for f in os.listdir(self.path)
                    if f.endswith(".parquet"))
                if not self._files:
                    raise FileNotFoundError(
                        f"no *.parquet files under {self.path}")
            else:
                self._files = [self.path]
        return self._files

    def _metadata(self):
        """[(file, row_group_index, num_rows, global_offset), ...]"""
        import pyarrow.parquet as pq
        if self._meta is None:
            meta, off = [], 0
            for f in self._file_list():
                md = pq.ParquetFile(f).metadata
                for g in range(md.num_row_groups):
                    rows = md.row_group(g).num_rows
                    meta.append((f, g, rows, off))
                    off += rows
            self._meta = meta
        return self._meta

    @property
    def num_rows(self) -> int:
        m = self._metadata()
        return (m[-1][2] + m[-1][3]) if m else 0

    @property
    def columns(self) -> List[str]:
        import pyarrow.parquet as pq
        schema = pq.ParquetFile(self._file_list()[0]).schema_arrow
        return list(schema.names)

    def feature_columns(self) -> List[str]:
        if self._features is not None:
            return list(self._features)
        return [c for c in self.columns if c != self.label]

    # -- streaming reads ----------------------------------------------------

    def _iter_row_groups(self, columns: Sequence[str],
                         groups: Optional[Sequence[int]] = None
                         ) -> Iterator[Tuple[int, Dict[str, np.ndarray]]]:
        """Yield ``(global_offset, {col: ndarray})`` one row group at a
        time — the only place that touches pyarrow readers."""
        import pyarrow.parquet as pq
        meta = self._metadata()
        take = range(len(meta)) if groups is None else groups
        open_file, pf = None, None
        for gi in take:
            fname, g, _rows, off = meta[gi]
            if fname != open_file:
                pf = pq.ParquetFile(fname)
                open_file = fname
            tbl = pf.read_row_group(g, columns=list(columns))
            yield off, {c: tbl.column(c).to_numpy(zero_copy_only=False)
                        for c in columns}

    def read_shard(self, rank: int = 0, nproc: int = 1,
                   columns: Optional[Sequence[str]] = None
                   ) -> Dict[str, np.ndarray]:
        """This worker's strided rows (``global_row % nproc == rank``),
        streamed row group by row group.

        The result equals ``{c: col[rank::nproc]}`` of the full dataset
        — the same shard the in-memory estimator path takes — without
        any process ever holding the full dataset.
        """
        cols = list(columns) if columns is not None else self.columns
        parts: Dict[str, List[np.ndarray]] = {c: [] for c in cols}
        for off, data in self._iter_row_groups(cols):
            start = (rank - off) % nproc
            for c in cols:
                parts[c].append(data[c][start::nproc])
        return {c: (np.concatenate(parts[c]) if parts[c]
                    else np.empty((0,))) for c in cols}

    def read_xy(self, rank: int = 0, nproc: int = 1
                ) -> Tuple[np.ndarray, np.ndarray]:
        """Shard as ``(X, y)``: feature columns stacked into a 2-D float
        matrix (order = ``features``), label as an ``(n, 1)`` column —
        the estimator contract."""
        feats = self.feature_columns()
        shard = self.read_shard(rank, nproc, columns=feats + [self.label])
        X = np.column_stack([shard[c] for c in feats])
        y = shard[self.label].reshape(-1, 1)
        return X, y

    def iter_batches(self, batch_size: int, rank: int = 0, nproc: int = 1,
                     columns: Optional[Sequence[str]] = None,
                     shuffle_buffer: int = 0, seed: int = 0,
                     drop_last: bool = True
                     ) -> Iterator[Dict[str, np.ndarray]]:
        """Stream batches from this worker's ROW-GROUP shard
        (``groups[rank::nproc]``) with an optional windowed shuffle.

        For shards too large to materialize: peak memory is one row
        group + ``shuffle_buffer`` rows + one batch.  With a nonzero
        buffer the row-group visit order is permuted per epoch
        (``seed``) and rows are permuted within each
        leftover+row-group window — the streaming analog of a full
        permutation (Petastorm's reader semantics, not bit-identical to
        the in-memory shuffle).  Per-batch cost is a slice; the merge +
        window permutation happens once per row group.
        """
        cols = list(columns) if columns is not None else self.columns
        n_groups = len(self._metadata())
        mine = list(range(rank, n_groups, nproc))
        rng = np.random.RandomState(seed)
        if shuffle_buffer > 0:
            rng.shuffle(mine)
        merged: Optional[Dict[str, np.ndarray]] = None
        cursor = 0

        def held() -> int:
            return 0 if merged is None else len(merged[cols[0]]) - cursor

        for _off, data in self._iter_row_groups(cols, groups=mine):
            # fold the unemitted leftover into the fresh group; one
            # concatenate + (shuffled mode) one permutation per group
            if merged is None or held() == 0:
                merged = dict(data)
            else:
                merged = {c: np.concatenate([merged[c][cursor:], data[c]])
                          for c in cols}
            cursor = 0
            if shuffle_buffer > 0:
                perm = rng.permutation(len(merged[cols[0]]))
                merged = {c: merged[c][perm] for c in cols}
            # drain down to the buffer watermark so later groups still
            # have rows to mix with; batches are O(batch) slices
            while held() - batch_size >= shuffle_buffer:
                yield {c: merged[c][cursor:cursor + batch_size]
                       for c in cols}
                cursor += batch_size
        while held() >= batch_size:
            yield {c: merged[c][cursor:cursor + batch_size] for c in cols}
            cursor += batch_size
        if not drop_last and held():
            yield {c: merged[c][cursor:] for c in cols}

    def shard_rows(self, rank: int = 0, nproc: int = 1) -> int:
        """Row count of this worker's row-group shard (iter_batches)."""
        meta = self._metadata()
        return sum(meta[g][2] for g in range(rank, len(meta), nproc))


class ParquetLoader(BaseDataLoader):
    """:class:`BaseDataLoader` over :meth:`ParquetDataset.iter_batches`
    (compose with :class:`AsyncDataLoaderMixin` for background
    prefetch)::

        class Prefetching(AsyncDataLoaderMixin, ParquetLoader): ...
    """

    def __init__(self, dataset: ParquetDataset, batch_size: int,
                 rank: int = 0, nproc: int = 1,
                 columns: Optional[Sequence[str]] = None,
                 shuffle_buffer: int = 0, seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.rank, self.nproc = rank, nproc
        self.columns = columns
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        self._epoch = 0

    def __len__(self) -> int:
        return self.dataset.shard_rows(
            self.rank, self.nproc) // self.batch_size

    def _iterate(self):
        epoch, self._epoch = self._epoch, self._epoch + 1
        return self.dataset.iter_batches(
            self.batch_size, self.rank, self.nproc, columns=self.columns,
            shuffle_buffer=self.shuffle_buffer, seed=self.seed + epoch)
