"""LightningEstimator: the reference's third estimator flavor.

Reference parity: ``horovod/spark/lightning/estimator.py`` (SURVEY.md
§2.2 — Keras/Torch/Lightning estimators).  PyTorch Lightning is not in
the TPU image, so this is a gated adapter: with ``lightning`` (or
``pytorch_lightning``) importable it trains a ``LightningModule`` over
the launcher tier by driving the module's own ``training_step`` /
``configure_optimizers`` contract through the torch adapter; without it,
construction raises a clear ImportError naming the missing dependency —
the same graceful-absence contract as the MXNet binding.
"""

from __future__ import annotations

import uuid
from typing import Any, Optional, Sequence

import numpy as np

from .store import Store


def _lightning_module_cls():
    try:
        import lightning  # noqa: F401
        return lightning.LightningModule
    except ImportError:
        try:
            import pytorch_lightning  # noqa: F401
            return pytorch_lightning.LightningModule
        except ImportError:
            return None


def _first_optimizer(ret):
    """Normalize configure_optimizers()'s documented return forms —
    a single optimizer, a list/tuple of optimizers, a list of config
    dicts, an ``([optimizers], [schedulers])`` pair, or a dict with an
    ``"optimizer"`` key — down to the first optimizer.  Schedulers are
    dropped (the estimator drives fixed-epoch training)."""
    while isinstance(ret, (dict, list, tuple)):
        ret = ret["optimizer"] if isinstance(ret, dict) else ret[0]
    return ret


def _train_on_worker(model_bytes, X, y, epochs, batch_size, seed,
                     validation=0.0):
    """Runs on every launched worker (cloudpickled)."""
    import io

    import torch
    import horovod_tpu.torch as hvd

    module = torch.load(io.BytesIO(model_bytes), weights_only=False)
    module.train()

    def loss_of_batch(m, xb, yb, step_idx):
        out = m.training_step((xb, yb), step_idx)
        return out["loss"] if isinstance(out, dict) else out

    from ._worker import run_data_parallel_training
    hist = run_data_parallel_training(
        module, _first_optimizer(module.configure_optimizers()),
        loss_of_batch, X, y, epochs, batch_size, seed,
        validation=validation)

    if hvd.cross_rank() == 0:
        buf = io.BytesIO()
        torch.save(module, buf)
        return {"module": buf.getvalue(), "history": hist["loss"],
                "val_history": hist["val_loss"]}
    return None


class LightningEstimator:
    """sklearn-style fit/predict around a ``LightningModule``.

    Drives the module's ``training_step``/``configure_optimizers``
    contract on ``num_proc`` launched workers with data-parallel
    gradient reduction; rank 0's fitted module comes back for
    ``predict``.  Requires PyTorch Lightning — absent, ``__init__``
    raises ImportError immediately (fail at construction, not at fit).
    """

    def __init__(self, model, num_proc: int = 2, epochs: int = 1,
                 batch_size: int = 32, store: Optional[Store] = None,
                 seed: int = 0, env: Optional[dict] = None,
                 port: int = 0, validation: float = 0.0):
        lm = _lightning_module_cls()
        if lm is None:
            raise ImportError(
                "LightningEstimator needs `lightning` or "
                "`pytorch_lightning`, neither of which is installed. "
                "Use TorchEstimator for plain torch modules.")
        if not isinstance(model, lm):
            raise TypeError(f"model must be a LightningModule, got "
                            f"{type(model).__name__}")
        self.model = model
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.store = store
        self.seed = seed
        self.env = env
        self.port = port
        if not 0.0 <= validation < 1.0:
            raise ValueError(
                f"validation must be a fraction in [0, 1), got {validation}")
        self.validation = validation

    def fit(self, X: Sequence, y: Sequence) -> "LightningModelWrapper":
        import io

        import torch

        from ..runner import api as runner_api

        buf = io.BytesIO()
        torch.save(self.model, buf)
        extra = {} if self.port == 0 else {"port": self.port}
        results = runner_api.run(
            _train_on_worker,
            args=(buf.getvalue(), np.asarray(X), np.asarray(y),
                  self.epochs, self.batch_size, self.seed,
                  self.validation),
            np=self.num_proc, env=self.env, **extra)
        fitted = next(r for r in results if r is not None)
        if self.store is not None:
            run_id = f"lightning-{uuid.uuid4().hex[:8]}"
            self.store.save_checkpoint(run_id, fitted)
        module = torch.load(io.BytesIO(fitted["module"]),
                            weights_only=False)
        return LightningModelWrapper(module, fitted["history"],
                                     fitted.get("val_history"))


class LightningModelWrapper:
    """Fitted module + per-epoch loss history (parity with
    TorchModel.history — the reference's lightning estimator records
    metrics on the returned model)."""

    def __init__(self, module: Any, history: Optional[list] = None,
                 val_history: Optional[list] = None):
        self.module = module
        self.history = list(history or [])
        self.val_history = list(val_history or [])

    def predict(self, X) -> np.ndarray:
        import torch
        self.module.eval()
        with torch.no_grad():
            out = self.module(torch.from_numpy(np.asarray(X)))
        return out.numpy()
