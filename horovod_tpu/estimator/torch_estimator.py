"""TorchEstimator: fit/predict orchestration over the launcher tier.

Reference parity: ``horovod/spark/torch/estimator.py`` (SURVEY.md §2.2)
— the reference's largest integration: an sklearn-style estimator that
ships a torch model + optimizer to ``np`` Horovod workers, trains
data-parallel with per-worker shards, checkpoints through the Store,
and returns a fitted model wrapper with ``predict``.

TPU-native redesign: the data plane is this framework's own launcher
(``runner.run`` — fresh workers per fit, the reference's Spark-task
model) with the torch adapter's ``DistributedOptimizer`` inside.
Inputs are in-memory arrays (``fit(X, y)``) or an on-disk
:class:`~horovod_tpu.data.ParquetDataset` (``fit(dataset)``) — the
disk form reproduces the reference's Store/Petastorm flow: only the
dataset handle rides the payload and each worker streams its own
shard.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from .store import Store


def _train_on_worker(model_bytes, opt_factory, loss_fn, X, y, epochs,
                     batch_size, seed, shuffle, validation):
    """Runs on every launched worker (cloudpickled)."""
    import io
    import numpy as np
    import torch
    import horovod_tpu.torch as hvd

    rank = hvd.cross_rank()
    model = torch.load(io.BytesIO(model_bytes), weights_only=False)
    from ._worker import run_data_parallel_training
    pre_sharded = False
    if y is None:
        # on-disk data plane (reference: the Spark store's parquet
        # materialization + per-worker petastorm read-back): the payload
        # carried only the dataset handle; stream THIS worker's strided
        # shard from disk — identical rows to the in-memory
        # X[rank::nproc], so loss histories match exactly
        X, y = X.read_xy(rank, hvd.cross_size())
        pre_sharded = True
    hist = run_data_parallel_training(
        model, opt_factory(model.parameters()),
        lambda m, xb, yb, _s: loss_fn(m(xb), yb),
        X, y, epochs, batch_size, seed, shuffle, validation,
        pre_sharded=pre_sharded)
    buf = io.BytesIO()
    torch.save(model.state_dict(), buf)
    return {"state_dict": buf.getvalue() if rank == 0 else None,
            "history": hist["loss"], "val_history": hist["val_loss"]}


class TorchModel:
    """Fitted model wrapper (reference: TorchModel transformer)."""

    def __init__(self, model, history: List[float], run_id: str,
                 val_history: Optional[List[float]] = None):
        self.model = model
        self.history = history
        self.val_history = list(val_history or [])
        self.run_id = run_id

    def predict(self, X: np.ndarray) -> np.ndarray:
        import torch
        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.from_numpy(np.ascontiguousarray(X)))
        return out.numpy()

    def getModel(self):  # reference naming
        return self.model


class TorchEstimator:
    """Distributed-training estimator for torch models.

    Args mirror the reference's essentials: ``model`` (an ``nn.Module``),
    ``optimizer`` (factory ``params -> torch.optim.Optimizer``; a factory
    rather than an instance so fresh workers can rebuild it), ``loss``
    (``(pred, target) -> scalar``), ``epochs``, ``batch_size`` (per
    worker), ``np`` workers, ``store`` for checkpoints, ``run_id``.
    """

    def __init__(self, model, optimizer: Callable, loss: Callable,
                 epochs: int = 1, batch_size: int = 32, np: int = 1,
                 store: Optional[Store] = None,
                 run_id: Optional[str] = None, shuffle: bool = True,
                 seed: int = 0, env: Optional[dict] = None,
                 port: int = 29600, verbose: int = 0,
                 validation: float = 0.0):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.epochs = epochs
        self.batch_size = batch_size
        self.num_proc = np
        self.store = store
        self.run_id = run_id or f"torch-{uuid.uuid4().hex[:8]}"
        self.shuffle = shuffle
        self.seed = seed
        self.env = env
        self.port = port
        self.verbose = verbose
        if not 0.0 <= validation < 1.0:
            raise ValueError(
                f"validation must be a fraction in [0, 1), got {validation}")
        self.validation = validation

    def fit(self, X, y: Optional[np.ndarray] = None) -> TorchModel:
        """Train on in-memory arrays ``fit(X, y)`` or on an on-disk
        dataset ``fit(ParquetDataset(path))`` — the disk form ships only
        the dataset handle to the workers; each reads its own shard
        (reference: Spark estimator + store/petastorm data flow)."""
        import io
        import torch
        from ..data.parquet import ParquetDataset
        from ..runner import run

        if isinstance(X, ParquetDataset):
            if y is not None:
                raise ValueError("fit(dataset) takes no y — the label "
                                 "column lives in the dataset")
            data_args = (X, None)
        else:
            if y is None:
                raise TypeError("fit(X, y) needs y for array inputs "
                                "(only fit(ParquetDataset) omits it)")
            data_args = (np.asarray(X), np.asarray(y))
        buf = io.BytesIO()
        torch.save(self.model, buf)
        results = run(
            _train_on_worker,
            args=(buf.getvalue(), self.optimizer, self.loss,
                  *data_args, self.epochs,
                  self.batch_size, self.seed, self.shuffle,
                  self.validation),
            np=self.num_proc, env=self.env, port=self.port,
            verbose=bool(self.verbose))
        state_bytes = results[0]["state_dict"]
        history = results[0]["history"]
        val_history = results[0].get("val_history", [])
        fitted = torch.load(io.BytesIO(buf.getvalue()),
                            weights_only=False)
        fitted.load_state_dict(torch.load(
            io.BytesIO(state_bytes), weights_only=False))
        if self.store is not None:
            # SELF-CONTAINED checkpoint: the serialized fitted module
            # (definition + weights) rides along with the raw state dict,
            # so load_model() needs no matching live estimator
            # (reference: the store checkpoint is self-contained)
            mbuf = io.BytesIO()
            torch.save(fitted, mbuf)
            self.store.save_checkpoint(
                self.run_id, {"model": mbuf.getvalue(),
                              "history": history,
                              "val_history": val_history})
        return TorchModel(fitted, history, self.run_id,
                          val_history=val_history)

    def load(self, store: Optional[Store] = None,
             run_id: Optional[str] = None) -> TorchModel:
        """Rehydrate a fitted model from the store (reference:
        TorchModel load from checkpoint)."""
        store = store or self.store
        run_id = run_id or self.run_id
        # the method itself as a LAZY fallback: only legacy (state-dict-
        # only) checkpoints pay for serializing self.model
        return load_model(store, run_id,
                          fallback_model_bytes=self._serialized_model)

    def _serialized_model(self) -> bytes:
        import io
        import torch
        buf = io.BytesIO()
        torch.save(self.model, buf)
        return buf.getvalue()


def load_model(store: Store, run_id: str,
               fallback_model_bytes: Optional[Any] = None) -> TorchModel:
    """Rehydrate a fitted :class:`TorchModel` from a store checkpoint,
    with NO live estimator required: the checkpoint carries the model
    definition (``"model"``).  Pre-round-4 checkpoints that hold only a
    state dict need ``fallback_model_bytes`` — a ``torch.save``'d module
    of the matching architecture, or a zero-arg callable returning one
    (evaluated only on the legacy path)."""
    import io
    import torch
    ckpt = store.load_checkpoint(run_id)
    if "model" in ckpt:
        model = torch.load(io.BytesIO(ckpt["model"]), weights_only=False)
    elif fallback_model_bytes is not None:
        if callable(fallback_model_bytes):
            fallback_model_bytes = fallback_model_bytes()
        model = torch.load(io.BytesIO(fallback_model_bytes),
                           weights_only=False)
        model.load_state_dict(torch.load(
            io.BytesIO(ckpt["state_dict"]), weights_only=False))
    else:
        raise ValueError(
            f"checkpoint '{run_id}' predates self-contained checkpoints "
            f"(no serialized model); pass fallback_model_bytes or load "
            f"through an estimator constructed with the architecture")
    return TorchModel(model, ckpt.get("history", []), run_id,
                      val_history=ckpt.get("val_history", []))
