"""Shared data-parallel training loop for the estimator workers.

Runs inside processes launched by ``runner.run`` (cloudpickled callers).
Both the Torch and Lightning estimators drive this loop; they differ
only in how a batch becomes a loss (``loss_of_batch``) and in what they
serialize back.

The per-epoch step count is the GLOBAL MINIMUM of every rank's batch
count (``X[rank::nproc]`` shards differ by up to one sample): each
``opt.step()`` issues gradient all-reduces, so ranks must take exactly
the same number of steps or the collectives desynchronize — one rank's
spare step would pair with another's next epoch, and the final epoch
would hang on a collective nobody else joins.
"""

from __future__ import annotations

from typing import Callable, List


def run_data_parallel_training(model, optimizer,
                               loss_of_batch: Callable,
                               X, y, epochs: int, batch_size: int,
                               seed: int, shuffle: bool = True
                               ) -> List[float]:
    """Train ``model`` data-parallel; returns per-epoch averaged losses.

    ``loss_of_batch(model, xb, yb, step_idx) -> scalar torch loss``
    (``step_idx`` is the within-epoch batch index — Lightning's
    ``training_step`` contract receives it).
    """
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd

    rank, nproc = hvd.cross_rank(), hvd.cross_size()
    opt = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    Xs = torch.from_numpy(np.ascontiguousarray(X[rank::nproc]))
    ys = torch.from_numpy(np.ascontiguousarray(y[rank::nproc]))
    gen = torch.Generator().manual_seed(seed + rank)
    steps_per_epoch = int(hvd.allreduce(
        torch.tensor(float(len(Xs) // batch_size)), op=hvd.Min,
        name="estimator.steps_per_epoch"))

    history: List[float] = []
    for _ in range(epochs):
        order = (torch.randperm(len(Xs), generator=gen) if shuffle
                 else torch.arange(len(Xs)))
        epoch_loss = 0.0
        for s in range(steps_per_epoch):
            idx = order[s * batch_size:(s + 1) * batch_size]
            opt.zero_grad()
            loss = loss_of_batch(model, Xs[idx], ys[idx], s)
            loss.backward()
            opt.step()
            epoch_loss += float(loss.detach())
        avg = hvd.allreduce(
            torch.tensor(epoch_loss / max(steps_per_epoch, 1)),
            name="estimator.epoch_loss")
        history.append(float(avg))
    return history
