"""Shared data-parallel training loop for the estimator workers.

Runs inside processes launched by ``runner.run`` (cloudpickled callers).
Both the Torch and Lightning estimators drive this loop; they differ
only in how a batch becomes a loss (``loss_of_batch``) and in what they
serialize back.

The per-epoch step count is the GLOBAL MINIMUM of every rank's batch
count (``X[rank::nproc]`` shards differ by up to one sample): each
``opt.step()`` issues gradient all-reduces, so ranks must take exactly
the same number of steps or the collectives desynchronize — one rank's
spare step would pair with another's next epoch, and the final epoch
would hang on a collective nobody else joins.

``validation`` (reference: the Spark estimators' ``validation`` param)
holds out that fraction of each rank's shard before training; the
per-epoch validation loss is reduced as a (sum, count) pair so ranks
with differently-sized (even empty) validation shards stay in lockstep
— exactly one extra allreduce per epoch.
"""

from __future__ import annotations

from typing import Callable, Dict, List


def run_data_parallel_training(model, optimizer,
                               loss_of_batch: Callable,
                               X, y, epochs: int, batch_size: int,
                               seed: int, shuffle: bool = True,
                               validation: float = 0.0,
                               pre_sharded: bool = False
                               ) -> Dict[str, List[float]]:
    """Train ``model`` data-parallel; returns per-epoch histories:
    ``{"loss": [...], "val_loss": [...]}`` (``val_loss`` empty when
    ``validation`` is 0).

    ``loss_of_batch(model, xb, yb, step_idx) -> scalar torch loss``
    (``step_idx`` is the within-epoch batch index — Lightning's
    ``training_step`` contract receives it).

    ``pre_sharded=True`` means ``X``/``y`` are already THIS worker's
    shard (the on-disk data plane reads ``rank::nproc`` rows itself);
    otherwise the global arrays are strided here.
    """
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd

    rank, nproc = hvd.cross_rank(), hvd.cross_size()
    opt = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    if pre_sharded:
        Xs = torch.from_numpy(np.ascontiguousarray(X))
        ys = torch.from_numpy(np.ascontiguousarray(y))
    else:
        Xs = torch.from_numpy(np.ascontiguousarray(X[rank::nproc]))
        ys = torch.from_numpy(np.ascontiguousarray(y[rank::nproc]))
    Xv = yv = None
    if validation > 0.0:
        n_val = int(len(Xs) * validation)
        split_gen = torch.Generator().manual_seed(seed + 977)
        perm = torch.randperm(len(Xs), generator=split_gen)
        Xv, yv = Xs[perm[:n_val]], ys[perm[:n_val]]
        Xs, ys = Xs[perm[n_val:]], ys[perm[n_val:]]
    gen = torch.Generator().manual_seed(seed + rank)
    steps_per_epoch = int(hvd.allreduce(
        torch.tensor(float(len(Xs) // batch_size)), op=hvd.Min,
        name="estimator.steps_per_epoch"))

    history: Dict[str, List[float]] = {"loss": [], "val_loss": []}
    for _ in range(epochs):
        order = (torch.randperm(len(Xs), generator=gen) if shuffle
                 else torch.arange(len(Xs)))
        epoch_loss = 0.0
        for s in range(steps_per_epoch):
            idx = order[s * batch_size:(s + 1) * batch_size]
            opt.zero_grad()
            loss = loss_of_batch(model, Xs[idx], ys[idx], s)
            loss.backward()
            opt.step()
            epoch_loss += float(loss.detach())
        avg = hvd.allreduce(
            torch.tensor(epoch_loss / max(steps_per_epoch, 1)),
            name="estimator.epoch_loss")
        history["loss"].append(float(avg))

        if validation > 0.0:
            vsum, vcnt = 0.0, 0
            model.eval()
            with torch.no_grad():
                for s in range(0, len(Xv), batch_size):
                    xb, yb = Xv[s:s + batch_size], yv[s:s + batch_size]
                    vsum += float(loss_of_batch(
                        model, xb, yb, s // batch_size)) * len(xb)
                    vcnt += len(xb)
            model.train()
            # (sum, count) reduce: ranks may hold different (even zero)
            # validation counts without desynchronizing
            tot = hvd.allreduce(torch.tensor([vsum, float(vcnt)]),
                                op=hvd.Sum, name="estimator.val_loss")
            history["val_loss"].append(
                float(tot[0]) / max(float(tot[1]), 1.0))
    return history
