"""Artifact store for estimator training runs.

Reference parity: ``horovod/spark/common/store.py`` (SURVEY.md §2.2) —
the reference's ``Store`` abstracts where intermediate training data,
checkpoints, and logs live (HDFS/S3/local) for its Spark estimators.
The TPU-native tier keeps the same surface with a filesystem backend
(cloud buckets mount as filesystems on TPU VMs via gcsfuse, so one
backend covers the reference's remote cases too).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Optional


class Store:
    """Where estimator runs keep checkpoints and logs."""

    def checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def save_checkpoint(self, run_id: str, obj: Any):
        raise NotImplementedError

    def load_checkpoint(self, run_id: str) -> Any:
        raise NotImplementedError

    def exists(self, run_id: str) -> bool:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: Optional[str] = None,
               **storage_options) -> "Store":
        """Dispatch on the path scheme (reference: Store.create returns
        HDFSStore/S3Store/GCSStore/LocalStore by URL).  ``gs://``,
        ``s3://``, ``hdfs://``, ``memory://`` (tests) and every other
        fsspec protocol go to :class:`RemoteStore`; bare paths and
        ``file://`` stay on :class:`FilesystemStore`."""
        if prefix_path and "://" in prefix_path:
            scheme = prefix_path.split("://", 1)[0]
            if scheme not in ("file", "local"):
                return RemoteStore(prefix_path, **storage_options)
            prefix_path = prefix_path.split("://", 1)[1]
        if storage_options:
            raise ValueError(
                f"storage_options {sorted(storage_options)} only apply "
                f"to remote URLs (gs://, s3://, ...), not filesystem "
                f"path {prefix_path!r}")
        return FilesystemStore(prefix_path)


class FilesystemStore(Store):
    """Filesystem-backed store (reference: LocalStore/FilesystemStore)."""

    def __init__(self, prefix_path: Optional[str] = None):
        self._own = prefix_path is None
        self.prefix_path = (prefix_path if prefix_path is not None
                            else tempfile.mkdtemp(prefix="hvd_store_"))
        os.makedirs(self.prefix_path, exist_ok=True)

    def _run_dir(self, run_id: str) -> str:
        d = os.path.join(self.prefix_path, run_id)
        os.makedirs(d, exist_ok=True)
        return d

    def checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._run_dir(run_id), "checkpoint.pkl")

    def logs_path(self, run_id: str) -> str:
        d = os.path.join(self._run_dir(run_id), "logs")
        os.makedirs(d, exist_ok=True)
        return d

    def save_checkpoint(self, run_id: str, obj: Any):
        path = self.checkpoint_path(run_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, path)

    def load_checkpoint(self, run_id: str) -> Any:
        with open(self.checkpoint_path(run_id), "rb") as f:
            return pickle.load(f)

    def exists(self, run_id: str) -> bool:
        return os.path.exists(self.checkpoint_path(run_id))

    def cleanup(self):
        if self._own:
            shutil.rmtree(self.prefix_path, ignore_errors=True)


class RemoteStore(Store):
    """fsspec-backed store for cloud/remote URLs (reference:
    ``horovod/spark/common/store.py`` HDFSStore/S3Store — the remote
    backends the estimators checkpoint through).

    TPU-native note: a training job on a preemptible TPU slice needs its
    checkpoints OFF the slice — ``gs://bucket/prefix`` is the canonical
    choice (``checkpoint.py``'s async saves compose with this store for
    the estimator tier).  Any fsspec protocol works; ``memory://``
    backs the tests.  Credentials/config ride through
    ``storage_options`` to the fsspec filesystem.
    """

    def __init__(self, prefix_url: str, **storage_options):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover - baked into image
            raise ImportError(
                "RemoteStore needs fsspec (for gs:// install gcsfs, "
                "s3:// needs s3fs); use FilesystemStore for local "
                "paths") from e
        self.prefix_path = prefix_url.rstrip("/")
        self._fs, self._root = fsspec.core.url_to_fs(
            self.prefix_path, **storage_options)
        self._fs.makedirs(self._root, exist_ok=True)

    def checkpoint_path(self, run_id: str) -> str:
        # pure path computation: probes (exists) must not issue write
        # RPCs or materialize directories for runs that never happened.
        # Returned WITH the protocol — the Store contract (reference:
        # get_checkpoint_path returns full URLs) hands out paths any
        # fsspec-aware consumer can use directly.
        return self._fs.unstrip_protocol(
            f"{self._root}/{run_id}/checkpoint.pkl")

    def logs_path(self, run_id: str) -> str:
        d = f"{self._root}/{run_id}/logs"
        self._fs.makedirs(d, exist_ok=True)
        return self._fs.unstrip_protocol(d)

    def save_checkpoint(self, run_id: str, obj: Any):
        # object stores PUT atomically per key; directory-like backends
        # get tmp+mv (fsspec implements mv as copy+rm where the backend
        # has no rename)
        self._fs.makedirs(f"{self._root}/{run_id}", exist_ok=True)
        path = self.checkpoint_path(run_id)
        tmp = path + ".tmp"
        with self._fs.open(tmp, "wb") as f:
            pickle.dump(obj, f)
        self._fs.mv(tmp, path)

    def load_checkpoint(self, run_id: str) -> Any:
        with self._fs.open(self.checkpoint_path(run_id), "rb") as f:
            return pickle.load(f)

    def exists(self, run_id: str) -> bool:
        return self._fs.exists(self.checkpoint_path(run_id))

    def cleanup(self):
        pass  # remote prefixes are never owned by the process


LocalStore = FilesystemStore  # reference alias
