"""Artifact store for estimator training runs.

Reference parity: ``horovod/spark/common/store.py`` (SURVEY.md §2.2) —
the reference's ``Store`` abstracts where intermediate training data,
checkpoints, and logs live (HDFS/S3/local) for its Spark estimators.
The TPU-native tier keeps the same surface with a filesystem backend
(cloud buckets mount as filesystems on TPU VMs via gcsfuse, so one
backend covers the reference's remote cases too).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Optional


class Store:
    """Where estimator runs keep checkpoints and logs."""

    def checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def save_checkpoint(self, run_id: str, obj: Any):
        raise NotImplementedError

    def load_checkpoint(self, run_id: str) -> Any:
        raise NotImplementedError

    def exists(self, run_id: str) -> bool:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: Optional[str] = None) -> "Store":
        """Reference: Store.create dispatches on the path scheme; every
        TPU-VM-reachable path is a filesystem path here."""
        return FilesystemStore(prefix_path)


class FilesystemStore(Store):
    """Filesystem-backed store (reference: LocalStore/FilesystemStore)."""

    def __init__(self, prefix_path: Optional[str] = None):
        self._own = prefix_path is None
        self.prefix_path = (prefix_path if prefix_path is not None
                            else tempfile.mkdtemp(prefix="hvd_store_"))
        os.makedirs(self.prefix_path, exist_ok=True)

    def _run_dir(self, run_id: str) -> str:
        d = os.path.join(self.prefix_path, run_id)
        os.makedirs(d, exist_ok=True)
        return d

    def checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._run_dir(run_id), "checkpoint.pkl")

    def logs_path(self, run_id: str) -> str:
        d = os.path.join(self._run_dir(run_id), "logs")
        os.makedirs(d, exist_ok=True)
        return d

    def save_checkpoint(self, run_id: str, obj: Any):
        path = self.checkpoint_path(run_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, path)

    def load_checkpoint(self, run_id: str) -> Any:
        with open(self.checkpoint_path(run_id), "rb") as f:
            return pickle.load(f)

    def exists(self, run_id: str) -> bool:
        return os.path.exists(self.checkpoint_path(run_id))

    def cleanup(self):
        if self._own:
            shutil.rmtree(self.prefix_path, ignore_errors=True)


LocalStore = FilesystemStore  # reference alias
