"""Estimator tier: sklearn-style distributed fit/predict (L5).

Reference parity: ``horovod/spark/`` estimators (SURVEY.md §2.2, the
reference's largest Python integration) — the capability class is
"hand an unfitted model + data to an estimator, get a fitted model
back, with the distributed training orchestrated for you and artifacts
in a Store".  Spark itself (DataFrames, Petastorm) is intentionally
absent: TPU pipelines feed arrays/tf.data, and the launcher tier plays
the role of Spark's barrier-mode tasks.
"""

from .keras_estimator import (  # noqa: F401
    KerasEstimator, KerasModel, load_keras_model)
from .lightning_estimator import (  # noqa: F401
    LightningEstimator, LightningModelWrapper)
from .store import (  # noqa: F401
    FilesystemStore, LocalStore, RemoteStore, Store)
from .torch_estimator import (  # noqa: F401
    TorchEstimator, TorchModel, load_model)

__all__ = ["Store", "LocalStore", "FilesystemStore", "RemoteStore",
           "TorchEstimator", "TorchModel", "KerasEstimator", "KerasModel",
           "LightningEstimator", "LightningModelWrapper", "load_model",
           "load_keras_model"]
