"""KerasEstimator: fit/predict orchestration for tf.keras models.

Reference parity: ``horovod/spark/keras/estimator.py`` (SURVEY.md §2.2)
— sklearn-style fit over ``np`` workers with the Keras callbacks
(broadcast, metric averaging) installed, checkpointing through the
Store, returning a fitted wrapper with ``predict``.
"""

from __future__ import annotations

import uuid
from typing import Callable, List, Optional

import numpy as np

from .store import Store


def _train_on_worker(model_bytes, compile_kwargs, X, y, epochs,
                     batch_size, seed, validation=0.0):
    """Runs on every launched worker (cloudpickled)."""
    import numpy as np
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd
    import horovod_tpu.keras as khvd

    rank, nproc = hvd.cross_rank(), hvd.cross_size()
    tf.keras.utils.set_random_seed(seed + rank)
    model = tf.keras.models.model_from_json(model_bytes["json"])
    model.set_weights(model_bytes["weights"])
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.get(dict(compile_kwargs["optimizer"])))
    model.compile(optimizer=opt, loss=compile_kwargs["loss"],
                  metrics=compile_kwargs.get("metrics"))
    if y is None:
        # on-disk data plane: the payload carried only the dataset
        # handle; read THIS worker's strided shard (identical rows to
        # the in-memory X[rank::nproc] branch below)
        Xs, ys = X.read_xy(rank, nproc)
    else:
        Xs, ys = X[rank::nproc], y[rank::nproc]
    hist = model.fit(
        Xs, ys, epochs=epochs,
        batch_size=batch_size, verbose=0,
        validation_split=validation or 0.0,
        callbacks=[khvd.BroadcastGlobalVariablesCallback(0),
                   khvd.MetricAverageCallback()])
    return {"weights": model.get_weights() if rank == 0 else None,
            "history": {k: [float(v) for v in vs]
                        for k, vs in hist.history.items()}}


class KerasModel:
    """Fitted model wrapper (reference: KerasModel transformer)."""

    def __init__(self, model, history, run_id: str):
        self.model = model
        self.history = history
        self.run_id = run_id

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(X, verbose=0))

    def getModel(self):  # reference naming
        return self.model


class KerasEstimator:
    """Distributed-training estimator for tf.keras models.

    ``model`` must be json-serializable (Sequential/functional);
    ``optimizer`` is a keras identifier dict/config (workers rebuild it);
    ``loss``/``metrics`` as in ``model.compile``.
    """

    def __init__(self, model, optimizer, loss, metrics=None,
                 epochs: int = 1, batch_size: int = 32, np: int = 1,
                 store: Optional[Store] = None,
                 run_id: Optional[str] = None, seed: int = 0,
                 env: Optional[dict] = None, port: int = 29610,
                 verbose: int = 0, validation: float = 0.0):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics
        self.epochs = epochs
        self.batch_size = batch_size
        self.num_proc = np
        self.store = store
        self.run_id = run_id or f"keras-{uuid.uuid4().hex[:8]}"
        self.seed = seed
        self.env = env
        self.port = port
        self.verbose = verbose
        if not 0.0 <= validation < 1.0:
            raise ValueError(
                f"validation must be a fraction in [0, 1), got {validation}")
        self.validation = validation

    def fit(self, X, y: Optional[np.ndarray] = None) -> KerasModel:
        """``fit(X, y)`` on in-memory arrays, or ``fit(ParquetDataset)``
        on an on-disk dataset (only the handle rides the payload; each
        worker reads its own shard — the Spark store data flow)."""
        import tensorflow as tf
        from ..data.parquet import ParquetDataset
        from ..runner import run

        if isinstance(X, ParquetDataset):
            if y is not None:
                raise ValueError("fit(dataset) takes no y — the label "
                                 "column lives in the dataset")
            data_args = (X, None)
        else:
            if y is None:
                raise TypeError("fit(X, y) needs y for array inputs "
                                "(only fit(ParquetDataset) omits it)")
            data_args = (np.asarray(X), np.asarray(y))
        opt_cfg = tf.keras.optimizers.serialize(
            tf.keras.optimizers.get(self.optimizer))
        payload = {"json": self.model.to_json(),
                   "weights": self.model.get_weights()}
        results = run(
            _train_on_worker,
            args=(payload, {"optimizer": opt_cfg, "loss": self.loss,
                            "metrics": self.metrics},
                  *data_args, self.epochs,
                  self.batch_size, self.seed, self.validation),
            np=self.num_proc, env=self.env, port=self.port,
            verbose=bool(self.verbose))
        fitted = tf.keras.models.model_from_json(payload["json"])
        fitted.set_weights(results[0]["weights"])
        history = results[0]["history"]
        if self.store is not None:
            # SELF-CONTAINED: the model json rides along so
            # load_keras_model() needs no live estimator (parity with
            # the torch store checkpoints)
            self.store.save_checkpoint(
                self.run_id, {"json": payload["json"],
                              "weights": results[0]["weights"],
                              "history": history})
        return KerasModel(fitted, history, self.run_id)


def load_keras_model(store: Store, run_id: str,
                     fallback_json: Optional[str] = None) -> KerasModel:
    """Rehydrate a fitted :class:`KerasModel` from a SELF-CONTAINED store
    checkpoint (model json + weights), with no live estimator required —
    parity with :func:`torch_estimator.load_model` and the reference's
    store round-trip.  Legacy (weights-only) checkpoints need
    ``fallback_json`` (``model.to_json()`` of the matching
    architecture)."""
    import tensorflow as tf
    ckpt = store.load_checkpoint(run_id)
    json_def = ckpt.get("json", fallback_json)
    if json_def is None:
        raise ValueError(
            f"checkpoint '{run_id}' predates self-contained keras "
            f"checkpoints (no model json); pass fallback_json="
            f"model.to_json() of the matching architecture")
    model = tf.keras.models.model_from_json(json_def)
    model.set_weights(ckpt["weights"])
    return KerasModel(model, ckpt.get("history", {}), run_id)
