"""hvdtrace CLI: critical-path attribution over a merged job trace.

    tools/hvdtrace trace.json            # analyze a saved merged trace
    tools/hvdtrace --url http://driver:29410/trace/job
    tools/hvdtrace --json trace.json     # machine-readable report
    tools/hvdtrace --smoke               # CI: recorded chaos fixture

The input is the ``GET /trace/job`` object (or any Chrome-trace JSON
whose events carry ``host``/``round`` args — ``GET /trace`` per-worker
output works too, it just has one host to attribute to).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

from . import critical

#: The recorded fixture --smoke replays: a 4-host merged trace captured
#: under the pinned ``collective.dcn group=1 every=3 action=delay:0.8``
#: chaos seed (tests/test_tracing.py regenerates it; the injected host
#: is recorded in otherData.chaos).
SMOKE_FIXTURE = os.path.join("tests", "traces", "chaos_4proc.trace.json")


def _load(args) -> dict:
    if args.url:
        with urllib.request.urlopen(args.url, timeout=10.0) as resp:
            return json.loads(resp.read().decode("utf-8"))
    with open(args.trace) as f:
        return json.load(f)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _smoke() -> int:
    path = os.path.join(_repo_root(), SMOKE_FIXTURE)
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    pids = {e["pid"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert len(pids) >= 2, f"fixture has {len(pids)} host pid(s)"
    report = critical.analyze(trace)
    assert report["rounds"] >= 3, report
    chaos = (trace.get("otherData") or {}).get("chaos") or {}
    injected = chaos.get("injected_host")
    assert injected, "fixture missing otherData.chaos.injected_host"
    assert report["top"] and report["top"][0] == injected, (
        f"critical-path verdict {report['top']} != injected straggler "
        f"{injected!r}")
    assert report["top"][1] > 0.5, report["top"]
    print(f"hvdtrace smoke OK: {report['rounds']} rounds, "
          f"critical-path host {injected} at {report['top'][1]:.1%} "
          f"(clock err bound {report['max_clock_err_s'] * 1e3:.2f}ms)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hvdtrace",
        description="critical-path attribution over a merged job trace "
                    "(GET /trace/job output)")
    ap.add_argument("trace", nargs="?",
                    help="merged trace JSON file")
    ap.add_argument("--url", help="scrape the trace from a URL "
                                  "(e.g. http://driver:29410/trace/job)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as JSON")
    ap.add_argument("--top", type=int, default=8,
                    help="hosts shown in the table (default 8)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke over the recorded chaos fixture")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    if not args.trace and not args.url:
        ap.error("a trace file or --url is required")
    trace = _load(args)
    report = critical.analyze(trace)
    if args.as_json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        print(critical.render_table(report, top=args.top))
    return 0 if report["rounds"] else 2


if __name__ == "__main__":
    sys.exit(main())
