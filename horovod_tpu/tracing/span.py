"""Per-worker span records: the raw material of the job-wide trace.

A :class:`SpanBuffer` is a bounded ring of closed spans, each tagged
with the negotiation round id and elastic epoch of the cycle it
belongs to — the correlation key that lets the driver line spans up
ACROSS workers without any shared clock (the round id advances in
lockstep on every member of a negotiation group; OptiReduce's
observation is that *which host's which phase* gated a round is the
question per-process timelines cannot answer, arXiv:2310.06993).

Timestamps are seconds on the buffer's own ``clock`` (default
``time.monotonic`` — per-host, arbitrary epoch).  The driver-side
merger (:mod:`.merge`) estimates each host's clock offset from RPC
request/response timestamps and maps every span onto its own clock;
nothing here needs wall-clock time or NTP.

Hot-path discipline (hvdmetrics precedent): instrumented sites guard
on ``tracing.ACTIVE`` so a disabled tracer costs one false branch;
``add()`` itself is a dict build + deque append under a lock.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

#: Spans kept per worker (ring; oldest dropped).  HOROVOD_TRACE_BUFFER.
DEFAULT_CAPACITY = 4096

#: Span categories the critical-path analyzer orders a round's DAG by
#: (submit → negotiate → fuse → dispatch → dcn); other categories
#: (``cycle`` envelope, trace-time ``overlap`` staging) ride the merged
#: trace but are not on the round path.
PHASES = ("submit", "negotiate", "fuse", "dispatch", "dcn")


class SpanBuffer:
    """Bounded ring of closed spans plus the identity/context tags the
    job-wide merge needs (host, process rank, elastic epoch, current
    negotiation round)."""

    def __init__(self, capacity: Optional[int] = None,
                 host: Optional[str] = None, process: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        # a malformed capacity (0, negative) degrades to the default —
        # this constructor runs at package import, and deque(maxlen=-1)
        # raising there would turn one bad env var into a failed
        # `import horovod_tpu`
        capacity = int(capacity or DEFAULT_CAPACITY)
        self.capacity = capacity if capacity > 0 else DEFAULT_CAPACITY
        self._clock = clock
        self._lock = threading.Lock()
        self._spans: "deque" = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0
        self.host = host or (os.environ.get("HOROVOD_HOSTNAME")
                             or socket.gethostname())
        self.process = int(process)
        self._epoch = 0
        self._round = -1
        self._cycle = -1
        self._group = ""

    # -- clock ---------------------------------------------------------------
    def now(self) -> float:
        """The buffer's clock.  Instrumentation sites stamp spans with
        this (NOT ``time.monotonic()`` directly) so tests can inject a
        skewed per-host clock and exercise the offset estimation the
        production path relies on."""
        return self._clock()

    # -- identity / context ---------------------------------------------------
    def set_identity(self, process: Optional[int] = None,
                     host: Optional[str] = None,
                     epoch: Optional[int] = None):
        with self._lock:
            if process is not None:
                self.process = int(process)
            if host:
                self.host = str(host)
            if epoch is not None:
                self._epoch = int(epoch)

    def set_context(self, round: Optional[int] = None,
                    cycle: Optional[int] = None,
                    epoch: Optional[int] = None,
                    group: Optional[str] = None):
        """Tag subsequent spans with the current negotiation round id /
        engine cycle / elastic epoch / negotiation group key.  Round
        ids are PER GROUP sequence numbers, so ``group`` disambiguates
        them when a job runs subset process sets alongside the global
        one ("" = no controller round — cycle-count correlation).
        Called by the engine thread once per cycle; spans recorded from
        other threads (e.g. trace-time overlap staging) pass an
        explicit ``round=-1`` instead of trusting this cycle-scoped
        state."""
        with self._lock:
            if round is not None:
                self._round = int(round)
            if cycle is not None:
                self._cycle = int(cycle)
            if epoch is not None:
                self._epoch = int(epoch)
            if group is not None:
                self._group = str(group)

    # -- recording ------------------------------------------------------------
    def add(self, cat: str, name: str, t0: float, t1: float,
            round: Optional[int] = None, group: Optional[str] = None,
            **args):
        """Record one closed span.  ``round=None``/``group=None``
        inherit the current context; args must be JSON-serializable
        (they ride the scrape reply verbatim)."""
        with self._lock:
            self._seq += 1
            if len(self._spans) >= self.capacity:
                self.dropped += 1
            self._spans.append({
                "seq": self._seq, "cat": str(cat), "name": str(name),
                "t0": float(t0), "t1": float(t1),
                "round": self._round if round is None else int(round),
                "group": self._group if group is None else str(group),
                "epoch": self._epoch, "cycle": self._cycle,
                "args": args,
            })

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def set_capacity(self, capacity: int):
        """Resize the ring in place (elastic re-init with a changed
        ``HOROVOD_TRACE_BUFFER``), keeping the newest spans and every
        identity/context tag.  Non-positive values degrade to the
        default (see ``__init__``)."""
        capacity = int(capacity)
        if capacity <= 0:
            capacity = DEFAULT_CAPACITY
        with self._lock:
            if capacity == self.capacity:
                return
            self.capacity = capacity
            self._spans = deque(self._spans, maxlen=capacity)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- scraping -------------------------------------------------------------
    def snapshot(self) -> Dict:
        """The scrape payload: identity + a copy of the ring + ``now``
        sampled on this buffer's clock (the merger's probe replies use
        the same field, so span timestamps and offset estimates are on
        one clock by construction)."""
        with self._lock:
            spans: List[Dict] = [dict(s) for s in self._spans]
            return {"host": self.host, "process": self.process,
                    "epoch": self._epoch, "dropped": self.dropped,
                    "capacity": self.capacity, "now": self.now(),
                    "spans": spans}

    def pull_handler(self):
        """A ``JsonRpcServer`` POST handler serving this buffer:
        ``{"probe": true}`` returns just ``now`` (clock-offset probe,
        kept tiny so the RTT bound stays tight); anything else returns
        the full :meth:`snapshot`."""
        def handle(payload):
            if isinstance(payload, dict) and payload.get("probe"):
                with self._lock:   # identity may be re-set at re-init
                    host, process = self.host, self.process
                # the clock sample deliberately comes LAST, outside the
                # lock: the probe's RTT bound covers the sample point,
                # and a lock wait inside the bracket only widens it
                return {"now": self.now(), "host": host,
                        "process": process}
            return self.snapshot()
        return handle
