"""hvdtracing: job-wide distributed tracing with clock-aligned merged
timelines and critical-path attribution.

The per-worker ``HOROVOD_TIMELINE`` (timeline.py) answers "what did MY
process do"; this package answers the multi-host question OptiReduce
(arXiv:2310.06993) says dominates DCN throughput — *which host's which
phase gated each round*:

* every worker keeps a bounded ring of span records
  (:class:`~.span.SpanBuffer`) for its engine cycles, negotiation
  rounds, fusion planning, per-bucket dispatches, DCN tail rounds
  (deadline + excluded hosts), and trace-time overlap staging — each
  tagged with the negotiation round id and elastic epoch, the
  correlation key that works without a global clock;
* the elastic driver's ``GET /trace/job`` scrapes every worker's
  buffer over the keep-alive RPC pool, estimates per-host clock
  offsets from RPC request/response timestamps (midpoint method,
  RTT-bounded error recorded on every span) and emits ONE
  Chrome-trace/Perfetto JSON with one ``pid`` per host
  (:mod:`.merge`);
* ``tools/hvdtrace`` (:mod:`.critical`) walks each round's span DAG
  (submit → negotiate → fuse → dispatch → dcn) and attributes the
  round's duration to the gating (host, phase, bucket), producing the
  per-host gating-fraction table that cross-checks the stall
  inspector's straggler EWMA with evidence.

Hot-path discipline (hvdmetrics/hvdchaos precedent): every
instrumented site guards on ``tracing.ACTIVE`` — one attribute load
and a false branch under ``HOROVOD_TRACE=0``.  Env table: docs/env.md;
span schema and offset method: docs/observability.md.
"""

from __future__ import annotations

import os
from typing import Optional

from . import critical, merge  # noqa: F401  (re-export for driver/tools)
from .span import DEFAULT_CAPACITY, PHASES, SpanBuffer  # noqa: F401

ENV_ENABLE = "HOROVOD_TRACE"
ENV_CAPACITY = "HOROVOD_TRACE_BUFFER"
ENV_PROBES = "HOROVOD_TRACE_PROBES"


def _env_on(name: str, default: bool = True, environ=os.environ) -> bool:
    from ..config import _env_bool  # one truthy grammar codebase-wide
    return _env_bool(name, default, environ)


def _env_capacity(environ=os.environ) -> int:
    try:
        return int(environ.get(ENV_CAPACITY, "") or DEFAULT_CAPACITY)
    except ValueError:
        return DEFAULT_CAPACITY


def probes(environ=os.environ) -> int:
    """Clock probes per scrape (``HOROVOD_TRACE_PROBES``, default 3;
    more probes tighten the min-RTT offset bound at scrape cost)."""
    try:
        return max(int(environ.get(ENV_PROBES, "3")), 1)
    except ValueError:
        return 3


#: Hot-path guard (one false branch when HOROVOD_TRACE=0).
ACTIVE = _env_on(ENV_ENABLE)

_BUFFER = SpanBuffer(capacity=_env_capacity())


def buffer() -> SpanBuffer:
    """The process-wide default span buffer (what ``trace_pull``
    serves)."""
    return _BUFFER


def swap_buffer(buf: SpanBuffer) -> SpanBuffer:
    """Replace the default buffer, returning the old one (tests only:
    isolates a scenario's spans; the engine reads the module default
    per call, so the swap takes effect immediately)."""
    global _BUFFER
    old, _BUFFER = _BUFFER, buf
    return old


def now() -> float:
    """The default buffer's clock (instrumentation sites stamp spans
    with this so tests can inject skewed clocks)."""
    return _BUFFER.now()


def span(cat: str, name: str, t0: float, t1: float,
         round: Optional[int] = None, group: Optional[str] = None,
         **args):
    """Record one closed span into the default buffer (call sites
    guard on ``tracing.ACTIVE``)."""
    if ACTIVE:
        _BUFFER.add(cat, name, t0, t1, round=round, group=group, **args)


def set_context(round: Optional[int] = None, cycle: Optional[int] = None,
                epoch: Optional[int] = None,
                group: Optional[str] = None):
    _BUFFER.set_context(round=round, cycle=cycle, epoch=epoch,
                        group=group)


def set_identity(process: Optional[int] = None, host: Optional[str] = None,
                 epoch: Optional[int] = None):
    _BUFFER.set_identity(process=process, host=host, epoch=epoch)


def pull_handler(payload):
    """``JsonRpcServer`` POST handler over the CURRENT default buffer
    (resolved per call so ``swap_buffer`` takes effect)."""
    return _BUFFER.pull_handler()(payload)


def local_trace() -> dict:
    """This process's buffer as a Chrome trace (``GET /trace``)."""
    return merge.local_trace(_BUFFER)


def enable():
    global ACTIVE
    ACTIVE = True


def disable():
    global ACTIVE
    ACTIVE = False


def init_from_env(environ=os.environ):
    """Apply the HOROVOD_TRACE* contract (called from ``hvd.init()``;
    idempotent across elastic re-inits): refresh the ACTIVE flag and
    resize the default buffer if the capacity changed (newest spans are
    kept — a re-init mid-job must not drop the history a post-mortem
    scrape wants)."""
    global ACTIVE
    ACTIVE = _env_on(ENV_ENABLE, environ=environ)
    _BUFFER.set_capacity(_env_capacity(environ))
