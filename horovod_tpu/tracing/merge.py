"""Driver-side trace merge: scrape every worker's span buffer, align
clocks, emit ONE Chrome-trace/Perfetto JSON with one ``pid`` per host.

Clock alignment needs no NTP and no shared clock: each scrape runs a
few tiny ``trace_pull`` probe RPCs over the existing keep-alive pool
and applies the midpoint method — the worker samples its clock inside
the handler, the driver brackets the request with its own clock, and

    offset = worker_now - (t_send + t_recv) / 2

is correct to within ``RTT / 2`` *regardless of how asymmetric the two
legs are* (the sample point lies somewhere inside the bracket).  The
probe with the smallest RTT wins, and its ``RTT / 2`` is recorded on
every merged span as ``clock_err_us`` — the error bound the
critical-path analyzer and the tests hold alignment claims to.

Merged layout: one ``pid`` per HOST (the unit OptiReduce's tail
question is about), one ``tid`` lane per (process, span category),
spans as complete ``"X"`` events carrying round id, epoch, and the
instrumentation args verbatim.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from .span import SpanBuffer

#: Sentinel: resolve the RPC signing secret from the environment (the
#: launcher/driver default); pass ``secret=None`` explicitly for
#: unauthenticated test servers.
_ENV = object()


def estimate_offset(addr: str, port: int, probes: int = 3,
                    timeout: float = 2.0, secret=_ENV,
                    _request=None) -> Tuple[float, float]:
    """(offset, error) of the worker's span clock relative to this
    process's ``time.monotonic``: ``driver_time = span_time - offset``,
    correct to within ``error`` seconds (best probe's RTT / 2)."""
    from ..runner.rpc import json_request
    request = _request or json_request
    best: Optional[Tuple[float, float]] = None
    kw = {} if secret is _ENV else {"secret": secret}
    for _ in range(max(int(probes), 1)):
        t0 = time.monotonic()
        reply = request(addr, port, "trace_pull", {"probe": True},
                       timeout=timeout, retries=0, **kw)
        t1 = time.monotonic()
        rtt = t1 - t0
        offset = float(reply["now"]) - (t0 + t1) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    return best[1], best[0] / 2.0


def pull_worker(addr: str, port: int, probes: int = 3,
                timeout: float = 2.0, secret=_ENV,
                _request=None) -> Tuple[Dict, float, float]:
    """(snapshot, offset, error) for one worker endpoint: probe the
    clock first (tiny replies — tight RTT bound), then pull the span
    buffer once."""
    from ..runner.rpc import json_request
    request = _request or json_request
    offset, err = estimate_offset(addr, port, probes=probes,
                                  timeout=timeout, secret=secret,
                                  _request=request)
    kw = {} if secret is _ENV else {"secret": secret}
    snap = request(addr, port, "trace_pull", {}, timeout=timeout,
                   retries=0, **kw)
    return snap, offset, err


def chrome_trace(workers: Dict[str, Tuple[Dict, float, float]],
                 unreachable: Optional[Dict[str, str]] = None) -> Dict:
    """Assemble ``{worker: (snapshot, offset_s, error_s)}`` into one
    Chrome-trace object (``traceEvents`` form, Perfetto-loadable).

    One ``pid`` per distinct host; one ``tid`` lane per
    (process, category); timestamps mapped onto the scraper's clock
    (``span_time - offset``) and rebased so the earliest span is 0.
    Every event's args carry ``host``/``process``/``round``/``epoch``
    plus ``clock_err_us``, so downstream analysis never needs the
    side tables.
    """
    hosts = sorted({snap.get("host", w)
                    for w, (snap, _o, _e) in workers.items()})
    pid_of = {h: i for i, h in enumerate(hosts)}
    events: List[Dict] = []
    for h in hosts:
        events.append({"name": "process_name", "ph": "M",
                       "pid": pid_of[h], "tid": 0,
                       "args": {"name": h}})
    base = None
    for _w, (snap, offset, _err) in sorted(workers.items()):
        for s in snap.get("spans", ()):
            t = float(s["t0"]) - offset
            if base is None or t < base:
                base = t
    base = base or 0.0
    tids: Dict[Tuple[int, int, str], int] = {}
    clock_meta: Dict[str, Dict] = {}
    for w, (snap, offset, err) in sorted(workers.items()):
        host = snap.get("host", w)
        pid = pid_of[host]
        proc = int(snap.get("process", 0))
        clock_meta[w] = {"host": host, "process": proc,
                         "offset_s": round(offset, 6),
                         "err_s": round(err, 6),
                         "dropped": int(snap.get("dropped", 0))}
        for s in snap.get("spans", ()):
            lane = (pid, proc, s["cat"])
            tid = tids.get(lane)
            if tid is None:
                tid = len(tids) + 1
                tids[lane] = tid
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid,
                    "args": {"name": f"p{proc} {s['cat']}"}})
            args = dict(s.get("args") or {})
            args.update(round=s.get("round", -1),
                        group=s.get("group", ""),
                        epoch=s.get("epoch", 0),
                        host=host, process=proc,
                        clock_err_us=round(err * 1e6, 1))
            events.append({
                "name": s["name"], "cat": s["cat"], "ph": "X",
                "pid": pid, "tid": tid,
                "ts": round((float(s["t0"]) - offset - base) * 1e6, 1),
                "dur": round((float(s["t1"]) - float(s["t0"])) * 1e6, 1),
                "args": args})
    other = {"hosts": hosts, "clock": clock_meta}
    if unreachable:
        other["unreachable"] = {w: str(e)
                                for w, e in sorted(unreachable.items())}
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def local_trace(buffer: SpanBuffer) -> Dict:
    """The single-process view (``GET /trace`` on any server): this
    buffer rendered as a Chrome trace with zero offset/error."""
    snap = buffer.snapshot()
    return chrome_trace({str(snap.get("process", 0)): (snap, 0.0, 0.0)})


def scrape_job_trace(endpoints: Dict[str, Tuple[str, int]],
                     timeout: float = 2.0, probes: int = 3,
                     secret=_ENV) -> Dict:
    """Scrape every ``{worker: (addr, port)}`` span buffer in parallel
    and merge into one job trace.  Unreachable workers become entries
    in ``otherData.unreachable``, never a failed scrape — mid-churn is
    exactly when this view matters (the shared-deadline fan-out is the
    unified ``metrics.jobscrape.fan_out`` engine; probes+pull make a
    few round trips, hence the larger per-worker budget)."""
    from ..metrics import jobscrape

    def _fetch(worker, addr, port):
        return pull_worker(addr, port, probes=probes, timeout=timeout,
                           secret=secret)

    workers, failed = jobscrape.fan_out(
        endpoints, _fetch, budget=timeout * (probes + 1) + 1.0,
        wedged="trace scrape timed out", name="trace")
    return chrome_trace(workers,
                        unreachable={w: str(e)
                                     for w, e in failed.items()})
