"""Critical-path attribution over a merged job trace.

For every negotiation round, the spans from all hosts form a small DAG
with a fixed phase order (submit → negotiate → fuse → dispatch → dcn;
:data:`~.span.PHASES`).  The round's wall time is attributed by walking
the phases in order and charging each segment to the host whose span of
that phase *finished last* — the gating host: everyone else had that
phase done and was waiting.  Summed over rounds this yields the
per-host gating-fraction table — the evidence form of "which host's
which phase is costing us", cross-checkable against the stall
inspector's per-host straggler EWMA (which sees only DCN arrival
lateness, not negotiate/fuse/dispatch gating).

Works on the ``chrome_trace`` object (events carry host/round/epoch in
their args), so it runs identically on a live ``GET /trace/job`` scrape
and on a recorded fixture file.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List

from .span import PHASES

_PHASE_INDEX = {p: i for i, p in enumerate(PHASES)}


def round_spans(trace: Dict) -> Dict[tuple, List[Dict]]:
    """Group the trace's phase spans by ``(epoch, group, round)`` —
    round ids are per-GROUP sequence numbers, so the negotiation group
    key disambiguates them when subset process sets negotiate alongside
    the global one.  Spans with ``round < 0`` (trace-time staging,
    envelope spans) and non-phase categories are not on any round's
    path."""
    rounds: Dict[tuple, List[Dict]] = defaultdict(list)
    for e in trace.get("traceEvents", ()):
        if e.get("ph") != "X" or e.get("cat") not in _PHASE_INDEX:
            continue
        args = e.get("args") or {}
        rnd = args.get("round", -1)
        if rnd is None or int(rnd) < 0:
            continue
        t0 = float(e.get("ts", 0.0)) / 1e6
        rounds[(int(args.get("epoch", 0)),
                str(args.get("group", "")), int(rnd))].append({
            "phase": e["cat"], "name": e.get("name", ""),
            "host": str(args.get("host", "?")),
            "process": args.get("process", 0),
            "t0": t0, "t1": t0 + float(e.get("dur", 0.0)) / 1e6,
            "err_s": float(args.get("clock_err_us", 0.0)) / 1e6,
            "args": args})
    return dict(rounds)


def analyze(trace: Dict) -> Dict:
    """Attribute every round's duration to its gating (host, phase,
    span) and aggregate the per-host gating fractions.

    Returns ``{"rounds", "attributed_s", "max_clock_err_s", "hosts":
    {host: {"gating_s", "fraction", "phases": {phase: s}, "spans":
    {span name: s}}}, "top": [host, fraction] | None}``.
    """
    rounds = round_spans(trace)
    hosts: Dict[str, Dict] = defaultdict(lambda: {
        "gating_s": 0.0,
        "phases": defaultdict(float),
        "spans": defaultdict(float)})
    total = 0.0
    max_err = 0.0
    for _key, spans in sorted(rounds.items()):
        mark = min(s["t0"] for s in spans)
        for phase in PHASES:
            in_phase = [s for s in spans if s["phase"] == phase]
            if not in_phase:
                continue
            gate = max(in_phase, key=lambda s: s["t1"])
            max_err = max(max_err, gate["err_s"])
            seg = gate["t1"] - mark
            if seg <= 0:
                continue   # finished before the previous gate: hidden
            h = hosts[gate["host"]]
            h["gating_s"] += seg
            h["phases"][phase] += seg
            h["spans"][gate["name"]] += seg
            total += seg
            mark = gate["t1"]
    out_hosts: Dict[str, Dict] = {}
    for host, h in hosts.items():
        out_hosts[host] = {
            "gating_s": round(h["gating_s"], 6),
            "fraction": round(h["gating_s"] / total, 6) if total else 0.0,
            "phases": {p: round(v, 6)
                       for p, v in sorted(h["phases"].items())},
            "spans": {n: round(v, 6)
                      for n, v in sorted(h["spans"].items())},
        }
    top = None
    if out_hosts:
        name = max(out_hosts, key=lambda h: out_hosts[h]["gating_s"])
        top = [name, out_hosts[name]["fraction"]]
    return {"rounds": len(rounds), "attributed_s": round(total, 6),
            "max_clock_err_s": round(max_err, 6),
            "hosts": out_hosts, "top": top}


def render_table(report: Dict, top: int = 8) -> str:
    """The per-host gating-fraction table, worst first."""
    lines = [f"rounds analyzed: {report['rounds']}   "
             f"attributed: {report['attributed_s']:.3f}s   "
             f"clock error bound: "
             f"{report['max_clock_err_s'] * 1e3:.2f}ms"]
    header = (f"{'host':<24} {'gating_s':>10} {'fraction':>9}  "
              f"by phase")
    lines.append(header)
    lines.append("-" * len(header))
    ranked = sorted(report["hosts"].items(),
                    key=lambda kv: -kv[1]["gating_s"])[:top]
    for host, h in ranked:
        phases = " ".join(f"{p}={v:.3f}s"
                          for p, v in sorted(h["phases"].items(),
                                             key=lambda kv: -kv[1]))
        lines.append(f"{host:<24} {h['gating_s']:>10.3f} "
                     f"{h['fraction']:>9.1%}  {phases}")
    if report["top"]:
        lines.append(f"critical-path host: {report['top'][0]} "
                     f"({report['top'][1]:.1%} of attributed time)")
    else:
        lines.append("no round spans found (is HOROVOD_TRACE enabled?)")
    return "\n".join(lines)
