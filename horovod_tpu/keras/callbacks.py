"""Keras training callbacks.

Reference parity: ``horovod/_keras/callbacks.py`` (SURVEY.md §2.2) —
``BroadcastGlobalVariablesCallback`` (weight sync at train start),
``MetricAverageCallback`` (allreduce-averaged epoch metrics),
``LearningRateWarmupCallback`` (linear LR ramp over the first epochs,
scaling to ``size()`` workers, per the large-batch training recipe the
reference ships) and ``LearningRateScheduleCallback`` (staircase /
smooth LR decay over an epoch range).
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

keras = tf.keras


def _set_model_lr(model, lr: float):
    """Assign the optimizer's learning rate (shared by the LR callbacks:
    one place to extend when an optimizer's learning_rate is a schedule
    object rather than a variable/attribute)."""
    opt = model.optimizer
    lr_attr = getattr(opt, "learning_rate", None)
    if lr_attr is None:
        return
    if hasattr(lr_attr, "assign"):
        lr_attr.assign(lr)
    else:
        opt.learning_rate = lr


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast initial model + optimizer state from ``root_rank`` so all
    workers start identical (reference: BroadcastGlobalVariablesCallbackImpl).
    """

    def __init__(self, root_rank: int = 0, process_set=None):
        super().__init__()
        self.root_rank = root_rank
        self.process_set = process_set
        self.broadcast_done = False

    def on_batch_begin(self, batch, logs=None):
        if self.broadcast_done:
            return
        from ..tensorflow import broadcast_variables
        broadcast_variables(self.model.weights, self.root_rank,
                            process_set=self.process_set)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None):
            vars_ = opt.variables if not callable(opt.variables) \
                else opt.variables()
            broadcast_variables([v for v in vars_], self.root_rank,
                                process_set=self.process_set)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over workers before other callbacks see them
    (reference: MetricAverageCallbackImpl, used so checkpoint/early-stop
    decisions agree across workers)."""

    def __init__(self, process_set=None):
        super().__init__()
        self.process_set = process_set

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        from .. import api
        for k in sorted(logs):
            v = logs[k]
            if isinstance(v, (int, float, np.floating, np.integer)):
                logs[k] = float(np.asarray(api.allreduce(
                    np.float32(v), name=f"metric.{k}",
                    process_set=self.process_set)))


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the base LR by ``multiplier`` over an epoch range
    (reference: LearningRateScheduleCallbackImpl — the staircase /
    exponential-decay half of the large-batch recipe, which
    LearningRateWarmupCallback complements).

    ``multiplier`` is a constant or a callable ``epoch -> factor``;
    the schedule applies on ``[start_epoch, end_epoch)``.  With
    ``staircase=True`` the factor updates once per epoch; otherwise it
    updates every batch using fractional epochs (needs
    ``steps_per_epoch``)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch=None, staircase: bool = True,
                 steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if not staircase and steps_per_epoch is None:
            raise ValueError(
                "staircase=False requires steps_per_epoch so the "
                "schedule can compute fractional epochs")
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _set_lr(self, lr: float):
        _set_model_lr(self.model, lr)

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_train_batch_begin(self, batch, logs=None):
        if self.staircase:
            return
        # keras passes the in-epoch batch index — no extra counter needed
        epoch = self.current_epoch + batch / self.steps_per_epoch
        if self._in_range(epoch):
            self._set_lr(self.initial_lr * self.multiplier(epoch))


class LearningRateWarmupCallback(keras.callbacks.Callback):
    """Linearly ramp LR from the single-worker rate to ``initial_lr`` over
    ``warmup_epochs`` (reference: LearningRateWarmupCallbackImpl;
    Goyal et al.'s gradual warmup for large-batch DP training)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._steps = 0

    def _set_lr(self, lr: float):
        _set_model_lr(self.model, lr)

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if self.current_epoch >= self.warmup_epochs:
            return
        self._steps += 1
        if self.steps_per_epoch:
            progress = self._steps / (self.steps_per_epoch
                                      * self.warmup_epochs)
        else:
            progress = (self.current_epoch + 1) / self.warmup_epochs
        progress = min(progress, 1.0)
        from ..runtime import size
        base = self.initial_lr / size()
        self._set_lr(base + (self.initial_lr - base) * progress)

    def on_epoch_end(self, epoch, logs=None):
        if epoch == self.warmup_epochs - 1:
            self._set_lr(self.initial_lr)
            if self.verbose:
                print(f"Epoch {epoch + 1}: finished gradual learning rate "
                      f"warmup to {self.initial_lr}.")
