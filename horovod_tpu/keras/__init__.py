"""Keras adapter: optimizer wrapper + training callbacks.

Reference parity: ``horovod/keras/`` + ``horovod/_keras/callbacks.py``
(SURVEY.md §2.2) — ``DistributedOptimizer`` plus the three canonical
callbacks (``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateWarmupCallback``), built on the TF adapter's collectives.
"""

from __future__ import annotations

from ..tensorflow import (DistributedOptimizer, allreduce, broadcast,  # noqa: F401,E501
                          broadcast_variables, init, is_initialized, join,
                          rank, size, local_rank, local_size, cross_rank,
                          cross_size, shutdown, Average, Sum, Adasum,
                          Compression)
from .callbacks import (BroadcastGlobalVariablesCallback,  # noqa: F401
                        LearningRateScheduleCallback,
                        LearningRateWarmupCallback, MetricAverageCallback)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "Average", "Sum", "Adasum",
    "DistributedOptimizer", "allreduce", "broadcast", "broadcast_variables",
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateWarmupCallback", "LearningRateScheduleCallback",
    "Compression",
]
