"""Driver-side aggregation: scrape every worker, serve one merged view.

The registry's histograms carry fixed log2 bucket edges declared with
the metric (``registry.log2_edges``), so per-worker series are
bucket-identical and merge by summing counts bucket-wise — the property
that makes a job-level p99 exact instead of an average of per-worker
quantiles.  Merge rules:

* **counter**: summed across workers per label set.
* **histogram**: ``_bucket``/``_sum``/``_count`` summed across workers
  per label set; mismatched ``le`` sets raise (a version-skewed worker
  must surface, not silently corrupt the tails).
* **gauge**: per-worker spread — ``{agg="min",worker=k}`` /
  ``{agg="max",worker=k}`` (each naming the owning worker, so a single
  scrape answers "which worker is the straggler") plus ``{agg="sum"}``.

``scrape`` GETs a worker's ``/metrics`` route (``JsonRpcServer`` serves
it unauthenticated — exposition is read-only); unreachable workers are
reported as a comment line in the merged output rather than failing the
whole scrape.
"""

from __future__ import annotations

import re
import urllib.request
from typing import Dict, List, Optional, Tuple

from .registry import _escape, _fmt

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$')
_LABEL_RE = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]'
                       r'|\\.)*)"')


_UNESCAPE_RE = re.compile(r"\\(.)")


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted sequence — the
    one definition every bench's p50/p99 means (tools/bench_control,
    bench_serve, the serving example all delegate here so 'p99' cannot
    silently diverge between the gates CI pins)."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _unescape(value: str) -> str:
    # single left-to-right scan: sequential str.replace corrupts values
    # where a literal backslash precedes an 'n' or quote (the escaped
    # form '\\n' must collapse to '\'+'n', never to a newline)
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value)


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Parse text exposition into
    ``{family: {"type": t, "samples": [(name, labels, value), ...]}}``
    where histogram ``_bucket``/``_sum``/``_count`` samples are grouped
    under their family name.  Raises ValueError on malformed sample
    lines — the CI scrape doubles as a format check."""
    families: Dict[str, dict] = {}
    typed: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                typed[parts[2]] = parts[3]
                families.setdefault(
                    parts[2], {"type": parts[3], "samples": []})
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {line!r}")
        name = m.group("name")
        labels = {lm.group("k"): _unescape(lm.group("v"))
                  for lm in _LABEL_RE.finditer(m.group("labels") or "")}
        raw = m.group("value")
        value = float("inf") if raw == "+Inf" else float(raw)
        fam = name
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and name[:-len(sfx)] in typed:
                fam = name[:-len(sfx)]
                break
        families.setdefault(
            fam, {"type": typed.get(fam, "untyped"), "samples": []})
        families[fam]["samples"].append((name, labels, value))
    return families


def _series_key(labels: Dict[str, str],
                drop: Tuple[str, ...] = ()) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, v) for k, v in labels.items()
                        if k not in drop))


def merge(per_worker: Dict[str, Dict[str, dict]]) -> Dict[str, dict]:
    """Merge ``{worker: parse_prometheus(...)}`` into one family dict
    (same shape as ``parse_prometheus`` output)."""
    merged: Dict[str, dict] = {}
    names = sorted({n for fams in per_worker.values() for n in fams})
    for name in names:
        types = {fams[name]["type"]
                 for fams in per_worker.values() if name in fams}
        if len(types) > 1:
            raise ValueError(
                f"family {name!r} has conflicting types across workers: "
                f"{sorted(types)}")
        kind = types.pop()
        out: List[Tuple[str, Dict[str, str], float]] = []
        if kind == "gauge":
            # per-label-set spread over workers, owner-attributed
            by_series: Dict = {}
            for worker, fams in sorted(per_worker.items()):
                for sname, labels, value in fams.get(
                        name, {"samples": []})["samples"]:
                    by_series.setdefault(
                        _series_key(labels), []).append((worker, value))
            for key, vals in sorted(by_series.items()):
                base = dict(key)
                mn = min(vals, key=lambda wv: wv[1])
                mx = max(vals, key=lambda wv: wv[1])
                out.append((name, dict(base, agg="min",
                                       worker=str(mn[0])), mn[1]))
                out.append((name, dict(base, agg="max",
                                       worker=str(mx[0])), mx[1]))
                out.append((name, dict(base, agg="sum"),
                            sum(v for _, v in vals)))
        else:
            # counters, histogram components, untyped: sum per label set
            sums: Dict = {}
            le_sets: Dict = {}
            for worker, fams in sorted(per_worker.items()):
                for sname, labels, value in fams.get(
                        name, {"samples": []})["samples"]:
                    if kind == "histogram" and sname.endswith("_bucket"):
                        le_sets.setdefault(worker, set()).add(
                            labels.get("le"))
                    key = (sname, _series_key(labels))
                    if key in sums:
                        sums[key] = (sums[key][0], sums[key][1] + value)
                    else:
                        sums[key] = (dict(labels), value)
            if len({frozenset(s) for s in le_sets.values()}) > 1:
                raise ValueError(
                    f"histogram {name!r} has mismatched bucket edges "
                    f"across workers; cannot merge bucket-wise")

            def _order(kv):
                (sname, key), (labels, _) = kv
                le = labels.get("le")
                le_v = (float("inf") if le == "+Inf"
                        else float(le) if le is not None else -1.0)
                rest = tuple(i for i in key if i[0] != "le")
                return (sname, rest, le_v)

            for (sname, _), (labels, value) in sorted(
                    sums.items(), key=_order):
                out.append((sname, labels, value))
        merged[name] = {"type": kind, "samples": out}
    return merged


def render(families: Dict[str, dict],
           comments: Tuple[str, ...] = ()) -> str:
    """Render a (merged) family dict back to text exposition format."""
    lines: List[str] = [f"# {c}" for c in comments]
    for name in sorted(families):
        fam = families[name]
        lines.append(f"# TYPE {name} {fam['type']}")
        for sname, labels, value in fam["samples"]:
            val = "+Inf" if value == float("inf") else _fmt(value)
            if labels:
                body = ",".join(
                    f'{k}="{_escape(v)}"'
                    for k, v in sorted(labels.items()))
                lines.append(f"{sname}{{{body}}} {val}")
            else:
                lines.append(f"{sname} {val}")
    return "\n".join(lines) + "\n"


def scrape(addr: str, port: int, route: str = "metrics",
           timeout: float = 2.0) -> str:
    with urllib.request.urlopen(
            f"http://{addr}:{port}/{route}", timeout=timeout) as resp:
        return resp.read().decode("utf-8", errors="replace")


def scrape_and_merge(endpoints: Dict[str, Tuple[str, int]],
                     timeout: float = 2.0) -> str:
    """Scrape every ``{worker: (addr, port)}`` endpoint and render one
    merged job-level exposition.  Unreachable workers become comment
    lines, never a failed scrape.  Workers are scraped in parallel so
    the route's latency is one timeout, not timeouts × dead workers —
    mid-churn (when half the endpoints are corpses) is exactly when
    this view matters, and a serial scrape would blow the caller's own
    scrape deadline then.  The fan-out itself (daemon threads, ONE
    shared deadline, wedged threads degrading to unreachable) is the
    unified ``jobscrape.fan_out`` engine; only the degrade RENDERING —
    corpse comment lines in the merged exposition — lives here."""
    from . import jobscrape

    def _fetch(worker, addr, port):
        return parse_prometheus(scrape(addr, port, timeout=timeout))

    per_worker, failed = jobscrape.fan_out(
        endpoints, _fetch, budget=timeout + 1.0,
        wedged="scrape timed out", name="scrape")
    comments: List[str] = [f"worker {w} unreachable: {e}"
                           for w, e in failed.items()]
    comments.insert(0, f"aggregated over {len(per_worker)} worker(s)")
    return render(merge(per_worker), comments=tuple(comments))
