"""Crash flight recorder: a bounded ring of recent structured events.

When a worker dies today the only artifact is a stack trace; the events
that *led* there — elastic epoch churn, RPC retries, chaos injections,
stall warnings, engine state transitions — are gone.  This ring keeps
the last N of them (cheap: one deque append per low-frequency event)
and dumps them:

* on ``StallError`` (stall inspector aborts, controller peer-wait
  aborts),
* on a fatal engine-thread exception,
* on ``SIGUSR1`` (operator-triggered black-box read of a live process),
* attached to a worker's FAILURE report so the elastic driver logs the
  last events of a crashed worker.

Dump format (``HOROVOD_FLIGHT_RECORDER_PATH``, else stderr): one header
JSON line ``{"flight_recorder": ..., "reason": ..., "events": N}``
followed by one JSON object per event in recording order, each
``{"seq": n, "t": monotonic_s, "wall": unix_s, "kind": ..., **fields}``.
Dumps append, so a stall dump and a later crash dump of the same
process coexist in one file.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger("horovod_tpu")

DEFAULT_CAPACITY = 512


def _jsonable(value):
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


class FlightRecorder:
    """Thread-safe bounded event ring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(capacity), 1))
        self._seq = 0
        self._dumps = 0

    def record(self, kind: str, /, **fields):
        ev = {"kind": str(kind)}
        for k, v in fields.items():
            if k in ("kind", "seq", "t", "wall"):
                k += "_"   # reserved envelope keys; keep the field
            ev[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            ev["t"] = round(time.monotonic(), 6)
            ev["wall"] = round(time.time(), 3)
            self._ring.append(ev)

    def events(self, limit: Optional[int] = None) -> List[Dict]:
        """Most recent ``limit`` events, oldest first."""
        with self._lock:
            evs = list(self._ring)
        return evs[-limit:] if limit else evs

    def clear(self):
        with self._lock:
            self._ring.clear()

    @property
    def dumps(self) -> int:
        with self._lock:   # written under the lock in dump() (HVD113)
            return self._dumps

    def dump(self, reason: str, path: Optional[str] = None,
             limit: Optional[int] = None) -> int:
        """Write the ring to ``path`` (append) or stderr; returns the
        number of events written.  Never raises: this runs on failure
        paths where a second error would mask the first."""
        evs = self.events(limit)
        header = {"flight_recorder": "horovod_tpu", "reason": reason,
                  "pid": os.getpid(), "wall": round(time.time(), 3),
                  "events": len(evs)}
        try:
            lines = [json.dumps(header, separators=(",", ":"))]
            lines += [json.dumps(ev, separators=(",", ":"))
                      for ev in evs]
            blob = "\n".join(lines) + "\n"
            if path:
                with open(path, "a") as f:
                    f.write(blob)
            else:
                # leading newline: stderr may be mid-line (e.g. a test
                # runner's progress dots) — never splice into it
                sys.stderr.write("\n" + blob)
                sys.stderr.flush()
            with self._lock:
                self._dumps += 1
            return len(evs)
        except Exception:  # noqa: BLE001 - never mask the primary failure
            logger.debug("flight recorder dump failed", exc_info=True)
            return 0
