"""Unified driver-side job scraper: ONE parallel fan-out engine and ONE
route table behind every job-level GET view.

PR 13 left a documented deferred cleanup: the driver grew three (then
five) copy-pasted parallel-scrape fan-outs — ``/metrics/job``,
``/trace/job``, ``/health/job``, plus the in-process ``/serve/stats``
and ``/recovery/stats`` JSON routes.  Each fan-out re-implemented the
same discipline: daemon threads per worker, ONE shared deadline (a
per-thread join degrades to N x timeout with several wedged workers —
the serial bound the fan-out exists to avoid), and a wedged thread
still reported as unreachable instead of hanging the route.

This module owns that discipline once:

* :func:`fan_out` — the parallel-scrape engine, parameterized by the
  fetch callable (GET text, ``json_request`` RPC, multi-probe pull),
  the deadline budget (metrics/health: ``timeout + 1``; tracing:
  ``timeout * (probes + 1) + 1`` for its clock probes), and the wedge
  message.  The per-plane DEGRADE POLICIES stay in their planes —
  corpse comment lines in the merged exposition
  (``aggregate.scrape_and_merge``), ``otherData.unreachable`` in the
  merged trace, the healthy→degraded verdict demotion
  (``health.merge_job_health``) — pinned byte-identical by the
  existing route tests.
* :class:`JobScraper` — the route table the elastic driver registers:
  all six job routes (``metrics/job``, ``trace/job``, ``health/job``,
  ``timeseries/job``, ``recovery/stats``, and ``serve/stats`` once a
  plane attaches) delegate here instead of living as driver methods.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Callable, Dict, Optional, Tuple

_JSON_CT = "application/json"
_PROM_CT = "text/plain; version=0.0.4; charset=utf-8"


def fan_out(endpoints: Dict[str, Tuple[str, int]],
            fetch: Callable[[str, str, int], object], *,
            budget: float, wedged: str = "scrape timed out",
            name: str = "scrape",
            ) -> Tuple[Dict[str, object], Dict[str, Exception]]:
    """Scrape every ``{worker: (addr, port)}`` endpoint in parallel.

    ``fetch(worker, addr, port)`` runs on a daemon thread per worker;
    whatever it raises marks that worker failed, never the whole
    scrape — mid-churn (when half the endpoints are corpses) is
    exactly when a job view matters.  ONE shared deadline of
    ``budget`` seconds bounds the entire fan-out (transport timeouts
    do not bound DNS, and a per-thread join would degrade back to
    N x timeout with several wedged workers); a thread still running
    at the deadline yields ``TimeoutError(wedged)`` for its worker.

    Returns ``(ok, failed)``, both keyed by ``str(worker)`` in sorted
    order — callers render ``failed`` into their plane's degrade form
    (comment lines, ``unreachable`` maps, verdict demotion).
    """
    results: Dict[str, object] = {}

    def one(worker, addr, port):
        try:
            results[worker] = fetch(worker, addr, port)
        except Exception as e:  # noqa: BLE001 - partial view is useful
            results[worker] = e

    threads = [threading.Thread(target=one, args=(str(w), a, p),
                                name=f"hvd-{name}-{w}", daemon=True)
               for w, (a, p) in endpoints.items()]
    for t in threads:
        t.start()
    deadline = time.monotonic() + budget
    for t in threads:
        t.join(max(deadline - time.monotonic(), 0.0))
    for w in endpoints:   # a wedged thread still reports as unreachable
        results.setdefault(str(w), TimeoutError(wedged))
    ok: Dict[str, object] = {}
    failed: Dict[str, Exception] = {}
    for w in sorted(results):
        got = results[w]
        if isinstance(got, Exception):
            failed[w] = got
        else:
            ok[w] = got
    return ok, failed


def http_get(addr: str, port: int, route: str,
             timeout: float = 2.0) -> str:
    """GET a worker's unauthenticated exposition route (``/metrics``,
    ``/timeseries``, ...) — exposition is read-only, so it rides plain
    HTTP rather than the signed RPC path."""
    with urllib.request.urlopen(
            f"http://{addr}:{port}/{route}", timeout=timeout) as resp:
        return resp.read().decode("utf-8", errors="replace")


class JobScraper:
    """The route table behind every job-level GET view on the driver.

    ``endpoints`` is a zero-arg callable returning the CURRENT
    ``{worker: (addr, port)}`` notification-endpoint snapshot (the
    driver re-snapshots under its lock on every scrape — a re-form
    mid-scrape must see the new fleet, not a stale copy);
    ``recovery_stats`` / the plane passed to :meth:`serving_routes`
    supply the two in-process JSON stats views.
    """

    def __init__(self, endpoints: Callable[[], Dict[str, Tuple[str, int]]],
                 recovery_stats: Optional[Callable[[], dict]] = None):
        self._endpoints = endpoints
        self._recovery_stats = recovery_stats

    def routes(self) -> Dict[str, Callable]:
        """The driver's ``get_routes`` table.  Each route returns the
        ``(status, content_type, body)`` tuple ``JsonRpcServer``
        serves; the merge/degrade semantics live in the owning plane
        (docs/observability.md)."""
        routes = {
            # job-level metrics: every registered worker scraped and
            # merged (histograms bucket-wise, gauges per-worker
            # min/max/sum) so one scrape answers "which worker is the
            # straggler"; unreachable workers render as comment lines
            "metrics/job": self._metrics_job,
            # job-wide distributed trace: every worker's span buffer
            # pulled over the keep-alive pool, clocks aligned via RPC
            # midpoint offsets, one Chrome-trace JSON with one pid per
            # host (docs/observability.md "Distributed trace";
            # tools/hvdtrace analyzes the critical path over it)
            "trace/job": self._trace_job,
            # job health verdict: every worker's health_pull snapshot
            # merged into ONE verdict with (worker, bucket, step)
            # attribution (docs/observability.md "Training health";
            # tools/hvddoctor prints the table)
            "health/job": self._health_job,
            # job time-series: every worker's windowed-delta ring
            # merged into per-worker rates/percentiles plus job-level
            # windowed histograms (docs/metrics.md "Time series";
            # tools/hvdtop renders the table)
            "timeseries/job": self._timeseries_job,
        }
        if self._recovery_stats is not None:
            # who holds redundancy for whom, and every fleet rebuild
            # (docs/observability.md "Checkpointless recovery stats")
            routes["recovery/stats"] = self._recovery_stats_route
        return routes

    def serving_routes(self, stats: Callable[[], dict]) -> Dict[str, Callable]:
        """The ``serve/stats`` route a ``ServingPlane`` adds on attach
        (queue depth, leases, per-worker service EWMAs)."""
        def _serve_stats():
            return (200, _JSON_CT,
                    json.dumps(stats(), separators=(",", ":")))
        return {"serve/stats": _serve_stats}

    # -- the six delegates ---------------------------------------------------

    def _recovery_stats_route(self):
        return (200, _JSON_CT,
                json.dumps(self._recovery_stats(), separators=(",", ":")))

    def _metrics_job(self):
        from . import aggregate
        body = aggregate.scrape_and_merge(self._endpoints())
        return (200, _PROM_CT, body)

    def _trace_job(self):
        from .. import tracing as _tracing
        trace = _tracing.merge.scrape_job_trace(
            self._endpoints(), probes=_tracing.probes())
        return (200, _JSON_CT, json.dumps(trace, separators=(",", ":")))

    def _health_job(self):
        from .. import health as _health
        job = _health.scrape_job_health(self._endpoints())
        return (200, _JSON_CT, json.dumps(job, separators=(",", ":")))

    def _timeseries_job(self):
        from . import timeseries as _timeseries
        job = _timeseries.scrape_job_timeseries(self._endpoints())
        return (200, _JSON_CT, json.dumps(job, separators=(",", ":")))
